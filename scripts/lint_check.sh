#!/usr/bin/env bash
# Framework self-scan gate: Families B (locks), C (concurrency) and D
# (protocol invariants vs lint/catalog.py) must be clean over ray_tpu/.
# Exits non-zero on any finding — wire this wherever CI runs; tier-1
# runs the same scan through tests/test_lint_self.py (keep both in
# sync: this script and the self-scan test pin the SAME invocation).
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m ray_tpu.lint ray_tpu --framework --select RT2,RT3,RT4 "$@"
