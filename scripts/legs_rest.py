"""Run the non-headline core-bench legs (spawn-safe: must be a real file)."""
import sys
import time

sys.path.insert(0, "/root/repo")
import ray_tpu  # noqa: E402
from ray_tpu._private import perf  # noqa: E402

if __name__ == "__main__":
    ray_tpu.init(num_cpus=2, num_nodes=1)
    legs = [
        ("actor_concurrent", perf.bench_actor_calls_concurrent, (1000,)),
        ("n_n", perf.bench_actor_calls_n_n, ()),
        ("multi_client_tasks", perf.bench_multi_client_tasks_async, ()),
        ("get_calls", perf.bench_get_calls, (2000,)),
        ("put_calls", perf.bench_put_calls, (2000,)),
        ("wait_1k", perf.bench_wait_1k_refs, (1000,)),
    ]
    for name, fn, a in legs:
        t0 = time.perf_counter()
        try:
            v = fn(*a)
        except Exception as e:
            print(name, "ERROR", repr(e)[:200], flush=True)
            continue
        print(name, round(v, 1), "wall", round(time.perf_counter() - t0, 1),
              flush=True)
    ray_tpu.shutdown()
    print("DONE", flush=True)
