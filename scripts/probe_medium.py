"""Probe which GPT-2-medium train configs compile+run on this chip.

Walks a ladder of (B, T, remat, policy) configs — the default ladder or one
given on the CLI as comma-separated rungs ``B:T:remat:policy`` — and records
tokens/sec + MFU for each that works into scripts/medium_probe.jsonl.
Run from /root/repo (axon backend is cwd-sensitive)::

    python scripts/probe_medium.py                 # default ladder, stop at
                                                   # first success
    python scripts/probe_medium.py 32:1024:1:dots 16:1024:0:dots --all
"""
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
except Exception:
    pass

sys.path.insert(0, "/root/repo")
from ray_tpu.models import gpt2  # noqa: E402
from ray_tpu.train.step import (  # noqa: E402
    OptimizerConfig,
    create_train_state,
    make_train_step,
)

LOG = "/root/repo/scripts/medium_probe.jsonl"


def log(rec):
    rec["t"] = round(time.time(), 1)
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(rec, flush=True)


def try_config(B, T, remat, policy, steps=10):
    config = gpt2.GPT2Config(
        vocab_size=50304, max_seq_len=T, num_layers=24, num_heads=16,
        embed_dim=1024, remat=remat, remat_policy=policy,
    )
    opt = OptimizerConfig().build()
    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    state = create_train_state(config, opt, jax.random.PRNGKey(0))
    step = make_train_step(config, opt)
    batch = {"tokens": jnp.asarray(rng.randint(0, 50304, (B, T + 1)))}
    state, m = step(state, batch)
    float(m["loss"])
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(steps):
        state, m = step(state, batch)
        if (i + 1) % 5 == 0:
            float(m["loss"])  # real device->host sync (tunnel-honest)
    float(m["loss"])
    dt = time.perf_counter() - t0
    tps = steps * B * T / dt
    mfu = gpt2.flops_per_token(config) * tps / 197e12
    return {"tps": round(tps, 1), "mfu": round(mfu, 4),
            "compile_s": round(compile_s, 1), "loss": float(m["loss"])}


DEFAULT_LADDER = [
    (16, 1024, True, "dots"),
    (8, 1024, True, "dots"),
    (8, 1024, True, "full"),
    (4, 1024, True, "full"),
    (8, 512, True, "dots"),
]


def main(argv):
    run_all = "--all" in argv
    rungs = [a for a in argv if ":" in a]
    if rungs:
        ladder = []
        for r in rungs:
            b, t, rm, pol = r.split(":")
            ladder.append((int(b), int(t), bool(int(rm)), pol))
    else:
        ladder = DEFAULT_LADDER
    for B, T, remat, policy in ladder:
        key = {"B": B, "T": T, "remat": remat, "policy": policy}
        try:
            res = try_config(B, T, remat, policy)
            log({**key, "ok": True, **res})
            if not run_all:
                break
        except Exception as e:
            log({**key, "ok": False,
                 "error": f"{type(e).__name__}: {e}"[:500]})
    log({"done": True})


if __name__ == "__main__":
    main(sys.argv[1:])
