"""A/B the regressed hot-path legs: native store on vs off, current HEAD.

Usage: python scripts/hotpath_ab.py [on|off]
"""
import os
import sys
import time

if len(sys.argv) > 1 and sys.argv[1] == "off":
    os.environ["RT_DISABLE_NATIVE_STORE"] = "1"

sys.path.insert(0, "/root/repo")
import ray_tpu  # noqa: E402
from ray_tpu._private import perf  # noqa: E402
from ray_tpu._private import worker as worker_mod  # noqa: E402

ray_tpu.init(num_cpus=2, num_nodes=1)
print("native:", worker_mod.get_global_worker().shm.native_enabled)
for name, fn, n in [
    ("tasks_async", perf.bench_single_client_tasks_async, 2000),
    ("actor_async", perf.bench_actor_calls_async, 2000),
    ("async_actor", perf.bench_async_actor_calls, 1000),
]:
    vals = []
    for _ in range(3):
        vals.append(fn(n))
    print(name, [round(v, 1) for v in vals])
ray_tpu.shutdown()
