"""Round benchmark: prints ONE JSON line with the headline metric.

Headline = single_client_tasks_async vs the reference's checked-in number
(BASELINE.md: 7,096.8 tasks/s on a release CPU node). Extra fields carry the
other core microbenchmarks plus GPT-2 train throughput on the local
accelerator (tokens/sec/chip — the BASELINE.json north star; the reference
publishes no TPU number for it, so vs_baseline stays anchored to tasks/s).

Usage: python bench.py [--quick] [--no-train]
"""
from __future__ import annotations

import argparse
import json
import os
import time

# Persistent XLA compilation cache: the big-model compiles (~60-500 s
# through the tunneled compile helper) are paid once per machine, not once
# per bench run. Must be set before jax initializes.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

BASELINE_TASKS_ASYNC = 7096.8  # reference release/perf_metrics/microbenchmark.json
# End-to-end regression guard for the device plane: GPT-2 train throughput
# measured in BENCH_r05 on this hardware. A device-plane change that taxes
# the hot path shows up here before anything else.
BASELINE_GPT2_TOKENS_PER_SEC_PER_CHIP = 86_200.0  # BENCH_r05.json


def measure_achievable_tflops() -> float:
    """Measured matmul roof of the local accelerator (bf16 4k x 4k,
    chained INSIDE one jit so per-dispatch overhead — multi-ms on tunneled
    devices — cannot deflate the roof).

    MFU against the nominal datasheet peak can be misleading: real chips
    execute below it even on pure matmul chains (observed ~158 TF vs the
    197 TF v5e datasheet number). Reporting the measured roof lets
    `gpt2_train_mfu_vs_achievable` say how close the train step is to what
    this device can actually do."""
    import time as _t

    import jax
    import jax.numpy as jnp

    # Transformer-MLP-shaped chain with resident weights — the sustained
    # rate a well-tiled model layer can actually reach (measured 158 TF on
    # a v5e whose datasheet says 197 and whose single-dispatch matmuls
    # read ~80-115 TF through a tunnel).
    M, E, H = 32 * 1024, 1024, 4096
    inner = 12
    x = jnp.full((M, E), 1.0 / E, jnp.bfloat16)
    w1 = jnp.full((E, H), 1.0 / H, jnp.bfloat16)
    w2 = jnp.full((H, E), 1.0 / E, jnp.bfloat16)

    @jax.jit
    def chain(x):
        for _ in range(inner):
            x = (x @ w1) @ w2
        return x

    out = chain(x)
    float(jnp.sum(out[:1, :1]))  # real device->host sync
    steps = 5
    t0 = _t.perf_counter()
    for _ in range(steps):
        out = chain(out)
    float(jnp.sum(out[:1, :1]))
    dt = _t.perf_counter() - t0
    return 2 * M * E * H * 2 * inner * steps / dt


def bench_train_tokens_per_sec(quick: bool = False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt2
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not quick:
        # remat=False first (no recompute — fastest when activations fit
        # the 16GB HBM at this size), falling back to the dots policy if
        # the compile or first step fails (OOM / compile-helper limits on
        # tunneled devices).
        candidates = [
            gpt2.GPT2Config(
                vocab_size=50304, max_seq_len=1024, num_layers=12,
                num_heads=12, embed_dim=768, remat=False,
            ),
            gpt2.GPT2Config(
                vocab_size=50304, max_seq_len=1024, num_layers=12,
                num_heads=12, embed_dim=768,
            ),
        ]
        B, T = 32, 1024
        steps = 20
    else:
        candidates = [
            gpt2.GPT2Config(
                vocab_size=2048, max_seq_len=256, num_layers=4, num_heads=4,
                embed_dim=256, dtype=jnp.float32,
            )
        ]
        B, T = 4, 256
        steps = 5
    opt = OptimizerConfig().build()
    rng = np.random.RandomState(0)
    state = step = batch = m = None
    last_exc = None
    for config in candidates:
        try:
            state = create_train_state(config, opt, jax.random.PRNGKey(0))
            step = make_train_step(config, opt)
            batch = {
                "tokens": jnp.asarray(
                    rng.randint(0, config.vocab_size, (B, T + 1))
                )
            }
            state, m = step(state, batch)  # compile
            jax.block_until_ready((jax.tree.leaves(state), m["loss"]))
            break
        except Exception as e:
            last_exc = e
            state = None
            continue
    if state is None:
        raise RuntimeError("no train config compiled/ran") from last_exc
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    # Block on the FULL final state, not just the loss scalar: some remote
    # execution paths report scalar readiness early, which would time
    # dispatch instead of compute.
    jax.block_until_ready((jax.tree.leaves(state), m["loss"]))
    dt = time.perf_counter() - t0
    tokens_per_sec = steps * B * T / dt
    mfu = None
    if on_tpu:
        flops = gpt2.flops_per_token(config) * tokens_per_sec
        peak = 197e12  # v5e bf16 peak; approximate
        mfu = flops / peak
        if mfu > 1.0:
            # physically impossible: async timing leaked through
            # (block_until_ready reported early). Re-time with real
            # device->host value syncs every few steps — a lower bound on
            # the true rate, but honest.
            sync_every = 5
            float(m["loss"])  # drain the un-synced first loop's queue
            t0 = time.perf_counter()
            for i in range(steps):
                state, m = step(state, batch)
                if (i + 1) % sync_every == 0:
                    float(m["loss"])  # forces the whole chain's bytes
            float(m["loss"])
            dt = time.perf_counter() - t0
            tokens_per_sec = steps * B * T / dt
            mfu = gpt2.flops_per_token(config) * tokens_per_sec / peak
    out = {
        "gpt2_train_tokens_per_sec_per_chip": tokens_per_sec,
        "gpt2_train_loss": float(m["loss"]),
        "gpt2_train_mfu_est": mfu,
        "gpt2_train_remat": bool(config.remat),
        "train_backend": jax.default_backend(),
    }
    if on_tpu:
        from ray_tpu.ops.attention import pallas_available

        out["flash_attention_active"] = bool(pallas_available())
        try:
            roof = measure_achievable_tflops()
            out["tpu_matmul_tflops_measured"] = roof / 1e12
            out["gpt2_train_mfu_vs_achievable"] = (
                gpt2.flops_per_token(config) * tokens_per_sec / roof
            )
        except Exception:
            pass
        try:
            ref = bench_reference_jax_step(quick=quick)
            out.update(ref)
            if ref.get("gpt2_reference_impl_tokens_per_sec"):
                out["gpt2_train_vs_reference_impl"] = (
                    tokens_per_sec / ref["gpt2_reference_impl_tokens_per_sec"]
                )
        except Exception:
            pass
        if not quick:
            try:
                # In-process first (works wherever HBM suffices, and is
                # the only option on TPU VMs whose libtpu grants exclusive
                # device ownership to this process). If the small leg's
                # resident HBM starves it (RESOURCE_EXHAUSTED observed on
                # 16GB chips), retry in a FRESH process: clean HBM, ~10s
                # jax import, compile from the persistent cache.
                med = bench_train_medium()
                if "RESOURCE_EXHAUSTED" in med.get("gpt2_medium_error", ""):
                    # only the HBM-residue failure benefits from a fresh
                    # process; deterministic failures would just burn the
                    # watchdog re-compiling toward the same error
                    sub = _bench_train_medium_subprocess()
                    if "gpt2_medium_error" not in sub:
                        med = sub
                    else:
                        med["gpt2_medium_error"] += (
                            " | subprocess: " + sub["gpt2_medium_error"]
                        )
                out.update(med)
            except Exception as e:
                out["gpt2_medium_error"] = f"{type(e).__name__}: {e}"
    return out


def _bench_train_medium_subprocess():
    import subprocess
    import sys

    code = (
        "import json, bench\n"
        "print('RTMED' + json.dumps(bench.bench_train_medium()))\n"
    )
    # 1200s: room for one cold ~500s tunnel compile + fast-fail rungs +
    # the timed steps, while fitting inside the 1800s train watchdog.
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=1200,
        cwd=os.path.dirname(os.path.abspath(__file__)),  # axon needs this cwd
    )
    for line in proc.stdout.splitlines():
        if line.startswith("RTMED"):
            return json.loads(line[len("RTMED"):])
    return {
        "gpt2_medium_error": (
            f"medium subprocess rc={proc.returncode}: "
            f"{proc.stderr[-300:]}"
        )
    }


def bench_train_medium():
    """GPT-2-medium (350M) tokens/sec/chip — the BASELINE.md north-star
    model size. Larger dims (E=1024, L=24) fill the MXU better than small.

    Ladder ordered upside-first: the tunneled compile helper rejects
    programs over its size limit with a FAST HTTP 500 (seconds, measured),
    so trying bigger-batch / no-remat configs first costs little, and the
    final rung — B=16 + remat "dots" — is the measured-feasible config on
    the v5e (35.2k tok/s, MFU 0.38)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt2
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    T, steps = 1024, 10
    opt = OptimizerConfig().build()
    rng = np.random.RandomState(0)
    errors = []
    for B, remat in ((32, False), (32, True), (16, False), (16, True)):
        config = gpt2.GPT2Config(
            vocab_size=50304, max_seq_len=1024, num_layers=24, num_heads=16,
            embed_dim=1024, remat=remat,
        )
        try:
            state = create_train_state(config, opt, jax.random.PRNGKey(0))
            step = make_train_step(config, opt)
            batch = {
                "tokens": jnp.asarray(
                    rng.randint(0, config.vocab_size, (B, T + 1))
                )
            }
            state, m = step(state, batch)
            float(m["loss"])
            t0 = time.perf_counter()
            for i in range(steps):
                state, m = step(state, batch)
                if (i + 1) % 5 == 0:
                    float(m["loss"])  # real device->host sync
            float(m["loss"])
            dt = time.perf_counter() - t0
            tps = steps * B * T / dt
            return {
                "gpt2_medium_tokens_per_sec_per_chip": tps,
                "gpt2_medium_mfu_est": (
                    gpt2.flops_per_token(config) * tps / 197e12
                ),
                "gpt2_medium_remat": remat,
                "gpt2_medium_batch": B,
            }
        except Exception as e:
            errors.append(f"B{B}/remat{remat}: {type(e).__name__}: {e}"[:300])
            continue
    return {"gpt2_medium_error": " | ".join(errors) or "no config tried"}


def bench_reference_jax_step(quick: bool = False):
    """A deliberately *stock* JAX GPT-2-small train step, written the way a
    typical user would (plain remat'd blocks, optax softmax-xent on full
    logits, no pallas / no vocab chunking / no fused policies). Same chip,
    same model dims, same token budget — the denominator the north-star
    metric needs in the absence of a torch-xla install (BASELINE.md: target
    >=90% of a stock SPMD implementation; we aim to beat it outright)."""
    import time as _t

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    if jax.default_backend() != "tpu" or quick:
        return {}
    V, T, L, H, E = 50304, 1024, 12, 12, 768
    key = jax.random.PRNGKey(0)

    def init(key):
        ks = jax.random.split(key, 6)
        def nrm(k, shape, s=0.02):
            return (s * jax.random.normal(k, shape)).astype(jnp.float32)
        return {
            "wte": nrm(ks[0], (V, E)),
            "wpe": nrm(ks[1], (T, E)),
            "blocks": {
                "ln1": jnp.ones((L, E)), "ln1b": jnp.zeros((L, E)),
                "qkv": nrm(ks[2], (L, E, 3 * E)), "qkvb": jnp.zeros((L, 3 * E)),
                "proj": nrm(ks[3], (L, E, E)), "projb": jnp.zeros((L, E)),
                "ln2": jnp.ones((L, E)), "ln2b": jnp.zeros((L, E)),
                "fc": nrm(ks[4], (L, E, 4 * E)), "fcb": jnp.zeros((L, 4 * E)),
                "out": nrm(ks[5], (L, 4 * E, E)), "outb": jnp.zeros((L, E)),
            },
            "lnf": jnp.ones((E,)), "lnfb": jnp.zeros((E,)),
        }

    def ln(x, g, b):
        x32 = x.astype(jnp.float32)
        y = (x32 - x32.mean(-1, keepdims=True)) * jax.lax.rsqrt(
            x32.var(-1, keepdims=True) + 1e-5)
        return (y * g + b).astype(x.dtype)

    def block(x, lp):
        B = x.shape[0]
        h = ln(x, lp["ln1"], lp["ln1b"])
        qkv = (h @ lp["qkv"].astype(h.dtype)) + lp["qkvb"].astype(h.dtype)
        q, k, v = jnp.split(qkv.reshape(B, T, 3, 12, 64), 3, axis=2)
        q, k, v = (t[:, :, 0].transpose(0, 2, 1, 3) for t in (q, k, v))
        s = (q @ k.transpose(0, 1, 3, 2)) * (64 ** -0.5)
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask, s.astype(jnp.float32), -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        a = (p @ v).transpose(0, 2, 1, 3).reshape(B, T, E)
        x = x + (a @ lp["proj"].astype(x.dtype)) + lp["projb"].astype(x.dtype)
        h = ln(x, lp["ln2"], lp["ln2b"])
        h = jax.nn.gelu((h @ lp["fc"].astype(h.dtype)) + lp["fcb"].astype(h.dtype))
        return x + (h @ lp["out"].astype(h.dtype)) + lp["outb"].astype(h.dtype)

    def loss_fn(params, tokens):
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
        x = params["wte"][inp].astype(jnp.bfloat16)
        x = x + params["wpe"][None].astype(jnp.bfloat16)
        body = jax.checkpoint(block)
        x, _ = jax.lax.scan(
            lambda c, lp: (body(c, lp), None), x, params["blocks"]
        )
        x = ln(x, params["lnf"], params["lnfb"])
        logits = (x @ params["wte"].T.astype(x.dtype)).astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt).mean()

    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(3e-4))
    best = None
    for B in (16, 8):  # full f32 logits cap the feasible batch
        try:
            params = init(key)
            opt_state = opt.init(params)

            @jax.jit
            def step(params, opt_state, tokens):
                l, g = jax.value_and_grad(loss_fn)(params, tokens)
                up, opt_state = opt.update(g, opt_state, params)
                return optax.apply_updates(params, up), opt_state, l

            rng = np.random.RandomState(0)
            tokens = jnp.asarray(rng.randint(0, V, (B, T + 1)))
            params, opt_state, l = step(params, opt_state, tokens)
            jax.block_until_ready(jax.tree.leaves(params)); float(l)
            n = 10
            t0 = _t.perf_counter()
            for _ in range(n):
                params, opt_state, l = step(params, opt_state, tokens)
            # same sync discipline as the framework-step timing above
            jax.block_until_ready(jax.tree.leaves(params)); float(l)
            rate = n * B * T / (_t.perf_counter() - t0)
            best = max(best or 0.0, rate)
            del params, opt_state
            break  # largest feasible batch measured; done
        except Exception:
            continue
    if not best:
        return {}
    return {"gpt2_reference_impl_tokens_per_sec": best}


def run_flight_benchmarks(quick: bool = False, phases: bool = False,
                          attrib_path: str = None) -> dict:
    """Flight-instrumented runs of the two ROADMAP perf open items
    (``queued_*_tasks_s``, ``many_actors_per_s``): the recorder stays ON,
    and after each leg the cluster-wide ring is drained into a per-verb
    time-attribution table — the measured breakdown the next perf
    tentpoles (batched lease-grant, batch create_actor) design against.

    ``phases=True`` (``bench.py --phases``) additionally joins the task
    phase spans to the task events and records the per-function phase
    table (p50/p99 per submit/queue/exec/... phase) under ``task_phases``
    in the bench JSON — the perf trajectory carries attribution, not just
    totals.

    Writes ``flight_attrib.json`` next to the bench JSON and prints the
    tables to stderr."""
    import sys

    from ray_tpu._private import flight, taskpath
    from ray_tpu._private.perf import bench_many_actors, bench_queued_tasks
    from ray_tpu._private.worker import get_global_worker

    flight.enable()
    w = get_global_worker()

    def drain():
        h, _ = w.run_sync(w._head_call("flight_snapshot", {}), 60)
        snaps = h["snapshots"]
        return flight.merge_snapshots(snaps), snaps

    def transit_stats():
        """Cluster transit-pacing snapshot: the DRIVER contributes the
        per-peer push windows + its settle stats; node processes are
        probed for the executor-side pump drain histogram (deduped by
        node id — a handful of spread probes covers small clusters).
        BENCH_r09's attribution needs these three series: peak/steady
        push-window per peer, pump messages-per-drain, and frames
        settled per driver recv wakeup. Round 20 adds the driver-loop
        scale-out ledgers: settle_plane / pack_plane snapshots and the
        per-shard pusher table (chunks/tasks per rt-pusher loop) ride
        the driver snapshot; pusher_shard_count is surfaced even when
        the auto knob resolves to 0 shards (small hosts), so an A/B
        over RT_PUSHER_LOOP_SHARDS reads from the bench JSON alone."""
        import ray_tpu

        stats = {"driver": w.transit_stats()}
        stats["driver"]["pusher_shard_count"] = len(w._pusher_loops)

        @ray_tpu.remote
        def _probe(_i):
            from ray_tpu._private.worker import get_global_worker

            gw = get_global_worker()
            return (
                gw.node_id,
                gw.transit_stats(),
                {k: v for k, v in gw._stats.items()
                 if k.startswith("pump_")},
            )

        nodes = {}
        try:
            for nid, ts, ps in ray_tpu.get(
                [_probe.remote(i) for i in range(8)], timeout=60
            ):
                ts["pump_exec"] = ps
                nodes[nid] = ts
        except Exception as e:
            stats["probe_error"] = f"{type(e).__name__}: {e}"
        stats["nodes"] = nodes
        return stats

    out = {"flight": True}
    attrib_all = {}
    legs = (
        ("many_actors_per_s",
         lambda: bench_many_actors(200 if quick else 1000)),
        ("queued_5k_tasks_s" if quick else "queued_1m_tasks_s",
         lambda: bench_queued_tasks(5_000 if quick else 1_000_000)),
    )
    for key, fn in legs:
        drain()  # discard events from the previous leg / warmup
        print(f"[bench --flight] {key}...", file=sys.stderr, flush=True)
        try:
            out[key] = fn()
        except Exception as e:
            out[key + "_error"] = f"{type(e).__name__}: {e}"
            continue
        merged, snaps = drain()
        dropped = sum(int(s.get("dropped") or 0) for s in snaps)
        recorded = sum(int(s.get("recorded") or 0) for s in snaps)
        attrib = flight.attribution(merged)
        transit = transit_stats()
        out.setdefault("transit", {})[key] = transit
        attrib_all[key] = {
            "verbs": attrib,
            "events_recorded": recorded,
            "events_dropped": dropped,
            "transit": transit,
        }
        print(f"--- per-verb attribution: {key} "
              f"({len(merged)} spans) ---", file=sys.stderr)
        if dropped:
            # No silent caps: a 1M-task leg overflows the per-process
            # rings, so the table attributes the TAIL window, not the
            # whole run.
            print(f"NOTE: rings kept the last {len(merged)} of "
                  f"{recorded} events ({dropped} overwritten) — totals "
                  f"are tail-window attribution, not the whole leg "
                  f"(raise RT_FLIGHT_RING_SIZE for full coverage)",
                  file=sys.stderr)
        print(flight.format_attribution(attrib), file=sys.stderr,
              flush=True)
        if phases:
            from ray_tpu.util import state

            # The leg's tail events ride the workers' 0.25s flusher tick:
            # wait for the head's event count to settle before joining
            # names, or the table degrades to the "task" bucket.
            events = state.list_tasks(limit=100_000)
            settle_deadline = time.time() + 3.0
            while time.time() < settle_deadline:
                time.sleep(0.35)
                nxt = state.list_tasks(limit=100_000)
                if len(nxt) == len(events):
                    events = nxt
                    break
                events = nxt
            table = taskpath.phase_table(merged, events)
            out.setdefault("task_phases", {})[key] = table
            attrib_all[key]["task_phases"] = table
            print(f"--- per-function task phases: {key} ---",
                  file=sys.stderr)
            print(taskpath.format_phase_table(table), file=sys.stderr,
                  flush=True)
    path = attrib_path or _attrib_path()
    with open(path, "w") as f:
        json.dump(attrib_all, f, indent=1)
    out["flight_attrib_file"] = path
    return out


def _attrib_path(output_dir: str = None) -> str:
    """Where attribution scratch output lands: --output-dir when given,
    else next to bench.py (gitignored — scratch files must never end up
    committed at the repo root again)."""
    d = output_dir or os.path.dirname(os.path.abspath(__file__))
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, "flight_attrib.json")


def record_peak_object_store(core: dict):
    """Record the cluster's peak object-store watermark into the bench
    JSON (the arena's high-water mark per node, summed): the put/get
    traffic a bench leg actually cost in store memory, alongside its
    throughput numbers. Soft dependency — a summary failure annotates
    instead of failing the run."""
    try:
        from ray_tpu.util import state

        summary = state.memory_summary()
        core["peak_object_store_bytes"] = int(
            summary["totals"]["arena_peak_bytes"]
        )
        core["object_store_leak_candidates"] = int(
            summary["totals"]["leak_candidates"]
        )
    except Exception as e:
        core["peak_object_store_bytes_error"] = f"{type(e).__name__}: {e}"


def run_serve_benchmarks(quick: bool = False) -> dict:
    """Closed-loop + spiky open-loop serve bench over the HTTP ingress
    (ISSUE 6 / ROADMAP "Serving plane under production traffic"):

    - ``serve_qps`` + ``serve_p50_ms``/``serve_p99_ms``: closed-loop
      (W workers, sequential requests) steady-state throughput/latency
      through proxy -> router -> replica and back;
    - ``serve_spike_p99_ms`` + ``serve_spike_shed``: spiky open-loop
      bursts (K concurrent requests at once, idle between bursts) — the
      proxy's admission control may shed with typed 503s, which are
      counted, not failed;
    - ``serve_drain_dropped``: scale 4 -> 1 mid-load; graceful drain
      must complete every in-flight request (the acceptance gate: 0).

    When the flight recorder is enabled (``bench.py --serve --flight``)
    the per-verb attribution table for the serve legs lands in
    flight_attrib.json alongside the RPC-plane legs.
    """
    import http.client
    import statistics
    import sys
    import threading

    from ray_tpu import serve

    @serve.deployment(num_replicas=2, max_ongoing_requests=32)
    class Echo:
        def __call__(self, req):
            return {"ok": True}

    serve.run(Echo.bind(), name="bench_app", route_prefix="/bench")
    port = serve.start_http_proxy(port=0)

    def one_request(lat, errs, sheds, timeout=30):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        t0 = time.perf_counter()
        try:
            conn.request("GET", "/bench")
            status = conn.getresponse().status
            if status == 200:
                lat.append(time.perf_counter() - t0)
            elif status == 503:
                sheds.append(status)  # typed shed: by design under spikes
            else:
                errs.append(status)
        except Exception as e:
            errs.append(f"{type(e).__name__}")
        finally:
            conn.close()

    def pcts(lat):
        if len(lat) < 2:
            return (lat[0] * 1e3, lat[0] * 1e3) if lat else (None, None)
        qs = statistics.quantiles(lat, n=100, method="inclusive")
        return qs[49] * 1e3, qs[98] * 1e3

    out = {}
    # ---- leg 1: closed loop ------------------------------------------
    print("[bench --serve] closed-loop...", file=sys.stderr, flush=True)
    workers, duration = (4, 3.0) if quick else (8, 10.0)
    lat, errs, sheds = [], [], []
    stop_at = time.perf_counter() + duration

    def closed_loop():
        while time.perf_counter() < stop_at:
            one_request(lat, errs, sheds)

    threads = [threading.Thread(target=closed_loop) for _ in range(workers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    p50, p99 = pcts(lat)
    out.update({
        "serve_qps": len(lat) / dt,
        "serve_p50_ms": p50,
        "serve_p99_ms": p99,
        "serve_errors": len(errs),
    })
    # ---- leg 2: spiky open-loop bursts -------------------------------
    print("[bench --serve] spiky bursts...", file=sys.stderr, flush=True)
    bursts, burst_size = (3, 16) if quick else (6, 48)
    lat, errs, sheds = [], [], []
    for _ in range(bursts):
        burst = [
            threading.Thread(target=one_request, args=(lat, errs, sheds))
            for _ in range(burst_size)
        ]
        for t in burst:
            t.start()
        for t in burst:
            t.join()
        time.sleep(0.3)  # open-loop idle gap between spikes
    p50, p99 = pcts(lat)
    out.update({
        "serve_spike_p50_ms": p50,
        "serve_spike_p99_ms": p99,
        "serve_spike_shed": len(sheds),
        "serve_spike_errors": len(errs),
    })
    # ---- leg 3: graceful drain under load ----------------------------
    print("[bench --serve] graceful drain 4->1...", file=sys.stderr,
          flush=True)
    serve.run(Echo.options(num_replicas=4).bind(), name="bench_app",
              route_prefix="/bench")
    lat, errs, sheds = [], [], []
    n_drain = 24 if quick else 80
    drain_threads = [
        threading.Thread(target=one_request, args=(lat, errs, sheds))
        for _ in range(n_drain)
    ]
    for t in drain_threads[: n_drain // 2]:
        t.start()
    serve.run(Echo.options(num_replicas=1).bind(), name="bench_app",
              route_prefix="/bench")  # scale down with the burst in flight
    for t in drain_threads[n_drain // 2:]:
        t.start()
    for t in drain_threads:
        t.join()
    out.update({
        "serve_drain_total": n_drain,
        "serve_drain_dropped": len(errs) + len(sheds),
    })
    serve.shutdown()
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--no-train", action="store_true")
    parser.add_argument("--train-only", action="store_true",
                        help="skip the core cluster benchmarks (debugging)")
    parser.add_argument(
        "--flight", action="store_true",
        help="flight-instrumented run of queued_tasks + many_actors only: "
             "recording ON cluster-wide, per-verb time-attribution table "
             "emitted next to the bench JSON (flight_attrib.json)")
    parser.add_argument(
        "--phases", action="store_true",
        help="implies --flight; after each leg, join the task phase spans "
             "to the task events and record the per-function phase table "
             "(submit/queue/exec/result p50+p99) into the bench JSON under "
             "task_phases — the perf trajectory carries attribution")
    parser.add_argument(
        "--output-dir", default=None, dest="output_dir",
        help="directory for attribution scratch files "
             "(flight_attrib.json); default: next to bench.py — those "
             "paths are gitignored scratch, never committed")
    parser.add_argument(
        "--serve", action="store_true",
        help="closed-loop serve bench only: serve_qps + p50/p99 through "
             "the HTTP ingress, spiky open-loop bursts (admission-control "
             "sheds counted), and a graceful-drain leg (scale 4->1 under "
             "load; dropped must be 0). Combine with --flight for per-verb "
             "attribution of the serving path")
    args = parser.parse_args()

    import os

    # Sentinel, not 0.0: a --train-only line must never read as a real
    # throughput collapse to anything parsing the headline contract.
    core = {"single_client_tasks_async_per_s": None, "core_skipped": True}
    if args.phases:
        args.flight = True
    if args.flight:
        # Recording must be on in every process: workers inherit the env.
        os.environ["RT_FLIGHT_ENABLED"] = "1"
        args.no_train = True  # flight mode measures the RPC plane only
    if args.serve:
        args.no_train = True  # serve mode measures the serving path only
    if not args.train_only:
        import ray_tpu
        from ray_tpu._private.perf import run_core_benchmarks

        # Scale worker processes to the machine: task execution is
        # GIL-bound per process, so on many-core hosts (TPU VMs have ~100
        # vCPUs) throughput comes from multiple node processes. On tiny CI
        # hosts stay small.
        cores = os.cpu_count() or 1
        if args.serve:
            # Serve bench: replicas/proxy/controller are IO-light actors
            # sharing node processes — schedule on virtual CPU slots (the
            # closed loop saturates the proxy event loop, not the cores).
            ray_tpu.init(num_cpus=16, num_nodes=1)
        elif cores >= 8:
            ray_tpu.init(num_cpus=4, num_nodes=min(cores // 4, 8))
        else:
            ray_tpu.init(num_cpus=max(cores, 2), num_nodes=1)
        try:
            if args.serve:
                core = {
                    "single_client_tasks_async_per_s": None,
                    "serve_bench": True,
                    **run_serve_benchmarks(quick=args.quick),
                }
                if args.flight:
                    import sys

                    from ray_tpu._private import flight
                    from ray_tpu._private.worker import get_global_worker

                    w = get_global_worker()
                    h, _ = w.run_sync(
                        w._head_call("flight_snapshot", {}), 60
                    )
                    merged = flight.merge_snapshots(h["snapshots"])
                    attrib = flight.attribution(merged)
                    print("--- per-verb attribution: serve bench ---",
                          file=sys.stderr)
                    print(flight.format_attribution(attrib),
                          file=sys.stderr, flush=True)
                    path = _attrib_path(args.output_dir)
                    # merge: the core legs' attribution (plain --flight
                    # runs) and the serve leg share the file
                    try:
                        with open(path) as f:
                            existing = json.load(f)
                    except (OSError, json.JSONDecodeError):
                        existing = {}
                    existing["serve_bench"] = {"verbs": attrib}
                    with open(path, "w") as f:
                        json.dump(existing, f, indent=1)
                    core["flight_attrib_file"] = path
            elif args.flight:
                core = {
                    "single_client_tasks_async_per_s": None,
                    **run_flight_benchmarks(
                        quick=args.quick, phases=args.phases,
                        attrib_path=_attrib_path(args.output_dir),
                    ),
                }
            else:
                core = run_core_benchmarks(quick=args.quick)
            # Peak store watermark rides every bench JSON: throughput
            # numbers carry their object-plane memory cost.
            record_peak_object_store(core)
        finally:
            ray_tpu.shutdown()

    extra = {}
    if not args.no_train:
        import signal

        def _timeout(*_):
            raise TimeoutError("train bench watchdog expired")

        # Watchdog: a wedged accelerator transport (observed on tunneled
        # TPU plugins) must degrade to train_error, not hang the whole
        # round's bench run.
        signal.signal(signal.SIGALRM, _timeout)
        signal.alarm(1800)
        try:
            extra = bench_train_tokens_per_sec(quick=args.quick)
        except Exception as e:  # keep the headline metric even if jax breaks
            extra = {"train_error": f"{type(e).__name__}: {e}"}
        finally:
            signal.alarm(0)

    value = core["single_client_tasks_async_per_s"]
    result = {
        "metric": "single_client_tasks_async",
        "value": round(value, 1) if value is not None else None,
        "unit": "tasks/s",
        "vs_baseline": (
            round(value / BASELINE_TASKS_ASYNC, 3)
            if value is not None else None
        ),
        **{
            k: (round(v, 2) if isinstance(v, float) else v)
            for k, v in core.items()
        },
        **{
            k: (round(v, 2) if isinstance(v, float) else v)
            for k, v in extra.items()
        },
    }
    gpt2 = extra.get("gpt2_train_tokens_per_sec_per_chip")
    if isinstance(gpt2, (int, float)) and gpt2 > 0:
        result["gpt2_vs_r05_baseline"] = round(
            gpt2 / BASELINE_GPT2_TOKENS_PER_SEC_PER_CHIP, 3
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
