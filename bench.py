"""Round benchmark: prints ONE JSON line with the headline metric.

Headline = single_client_tasks_async vs the reference's checked-in number
(BASELINE.md: 7,096.8 tasks/s on a release CPU node). Extra fields carry the
other core microbenchmarks plus GPT-2 train throughput on the local
accelerator (tokens/sec/chip — the BASELINE.json north star; the reference
publishes no TPU number for it, so vs_baseline stays anchored to tasks/s).

Usage: python bench.py [--quick] [--no-train]
"""
from __future__ import annotations

import argparse
import json
import time

BASELINE_TASKS_ASYNC = 7096.8  # reference release/perf_metrics/microbenchmark.json


def measure_achievable_tflops() -> float:
    """Measured matmul roof of the local accelerator (bf16 8k x 8k).

    MFU against the nominal datasheet peak can be misleading: shared or
    tunneled devices execute well below it (observed: a clean matmul at
    ~28% of nominal on a tunneled v5e). Reporting the measured roof lets
    `gpt2_train_mfu_vs_achievable` say how close the train step is to what
    this device can actually do."""
    import time as _t

    import jax
    import jax.numpy as jnp

    n = 8192
    a = jnp.ones((n, n), jnp.bfloat16)
    mm = jax.jit(lambda a: a @ a)
    out = mm(a)
    float(jnp.sum(out[:1, :1]))  # real device->host sync
    steps = 30
    t0 = _t.perf_counter()
    for _ in range(steps):
        out = mm(out)
    float(jnp.sum(out[:1, :1]))
    dt = _t.perf_counter() - t0
    return 2 * n ** 3 * steps / dt


def bench_train_tokens_per_sec(quick: bool = False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import gpt2
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not quick:
        config = gpt2.GPT2Config(
            vocab_size=50304, max_seq_len=1024, num_layers=12, num_heads=12,
            embed_dim=768,
        )
        # B=32 + vocab-chunked loss + dots-remat: bigger batch amortizes
        # per-step overhead without the old [B,T,V] fp32 logits blowup.
        B, T = 32, 1024
        steps = 20
    else:
        config = gpt2.GPT2Config(
            vocab_size=2048, max_seq_len=256, num_layers=4, num_heads=4,
            embed_dim=256, dtype=jnp.float32,
        )
        B, T = 4, 256
        steps = 5
    opt = OptimizerConfig().build()
    state = create_train_state(config, opt, jax.random.PRNGKey(0))
    step = make_train_step(config, opt)
    rng = np.random.RandomState(0)
    batch = {
        "tokens": jnp.asarray(rng.randint(0, config.vocab_size, (B, T + 1)))
    }
    state, m = step(state, batch)  # compile
    jax.block_until_ready((jax.tree.leaves(state), m["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    # Block on the FULL final state, not just the loss scalar: some remote
    # execution paths report scalar readiness early, which would time
    # dispatch instead of compute.
    jax.block_until_ready((jax.tree.leaves(state), m["loss"]))
    dt = time.perf_counter() - t0
    tokens_per_sec = steps * B * T / dt
    mfu = None
    if on_tpu:
        flops = gpt2.flops_per_token(config) * tokens_per_sec
        peak = 197e12  # v5e bf16 peak; approximate
        mfu = flops / peak
        if mfu > 1.0:
            # physically impossible: async timing leaked through
            # (block_until_ready reported early). Re-time with real
            # device->host value syncs every few steps — a lower bound on
            # the true rate, but honest.
            sync_every = 5
            float(m["loss"])  # drain the un-synced first loop's queue
            t0 = time.perf_counter()
            for i in range(steps):
                state, m = step(state, batch)
                if (i + 1) % sync_every == 0:
                    float(m["loss"])  # forces the whole chain's bytes
            float(m["loss"])
            dt = time.perf_counter() - t0
            tokens_per_sec = steps * B * T / dt
            mfu = gpt2.flops_per_token(config) * tokens_per_sec / peak
    out = {
        "gpt2_train_tokens_per_sec_per_chip": tokens_per_sec,
        "gpt2_train_loss": float(m["loss"]),
        "gpt2_train_mfu_est": mfu,
        "train_backend": jax.default_backend(),
    }
    if on_tpu:
        try:
            roof = measure_achievable_tflops()
            out["tpu_matmul_tflops_measured"] = roof / 1e12
            out["gpt2_train_mfu_vs_achievable"] = (
                gpt2.flops_per_token(config) * tokens_per_sec / roof
            )
        except Exception:
            pass
    return out


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--no-train", action="store_true")
    args = parser.parse_args()

    import os

    import ray_tpu
    from ray_tpu._private.perf import run_core_benchmarks

    # Scale worker processes to the machine: task execution is GIL-bound per
    # process, so on many-core hosts (TPU VMs have ~100 vCPUs) throughput
    # comes from multiple node processes. On tiny CI hosts stay small.
    cores = os.cpu_count() or 1
    if cores >= 8:
        ray_tpu.init(num_cpus=4, num_nodes=min(cores // 4, 8))
    else:
        ray_tpu.init(num_cpus=max(cores, 2), num_nodes=1)
    try:
        core = run_core_benchmarks(quick=args.quick)
    finally:
        ray_tpu.shutdown()

    extra = {}
    if not args.no_train:
        import signal

        def _timeout(*_):
            raise TimeoutError("train bench watchdog expired")

        # Watchdog: a wedged accelerator transport (observed on tunneled
        # TPU plugins) must degrade to train_error, not hang the whole
        # round's bench run.
        signal.signal(signal.SIGALRM, _timeout)
        signal.alarm(1800)
        try:
            extra = bench_train_tokens_per_sec(quick=args.quick)
        except Exception as e:  # keep the headline metric even if jax breaks
            extra = {"train_error": f"{type(e).__name__}: {e}"}
        finally:
            signal.alarm(0)

    value = core["single_client_tasks_async_per_s"]
    result = {
        "metric": "single_client_tasks_async",
        "value": round(value, 1),
        "unit": "tasks/s",
        "vs_baseline": round(value / BASELINE_TASKS_ASYNC, 3),
        **{k: round(v, 2) for k, v in core.items()},
        **{
            k: (round(v, 2) if isinstance(v, float) else v)
            for k, v in extra.items()
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
