"""Task-centric end-to-end tracing: task events join the flight recorder.

Covers the ISSUE-10 acceptance surface:

- unit coverage for the critical-path analyzer (phases sum to wall with
  the residual explicit, queue-phase naming, per-function table);
- a real 2-node run whose task spans + task events merge into one trace
  with cross-process flow links, per-task phase sums within 10% of the
  driver-observed wall time;
- disabled-mode parity: one boolean off → zero task spans recorded,
  zero phase observations (same contract as ``flight.ENABLED``);
- the task-event dict schema is PINNED (both the ``rt timeline`` chrome
  exporter and the state API consumers parse these fields);
- the head's aggregated ``/metrics`` exposes
  ``rt_task_phase_seconds{phase,fn,node_id}`` covering every node from
  ONE scrape on a 2-node cluster;
- ``bench.py --phases`` records the per-function phase table.
"""
import json
import os
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import flight, taskpath
from ray_tpu._private.test_utils import wait_for_condition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _flight_clean():
    flight.disable()
    yield
    flight.disable()


def _span(verb, cid, ts, dur, outcome="ok", kind="task", proc="driver"):
    return {"proc": proc, "pid": 1, "verb": verb, "cid": cid, "kind": kind,
            "ts": ts, "dur": dur, "nbytes": 0, "outcome": outcome,
            "qw": 0.0}


def _synthetic_task(tid="t1", queue_outcome="lease-wait",
                    fn_outcome="kv_get"):
    return [
        _span("task.submit", tid, 0.000, 0.010),
        _span("task.queued", tid, 0.010, 0.050, outcome=queue_outcome),
        _span("task.push", tid, 0.060, 0.100),
        _span("task.fn_load", tid, 0.065, 0.005, outcome=fn_outcome,
              proc="n1"),
        _span("task.arg_pull", tid, 0.070, 0.010, proc="n1"),
        _span("task.exec", tid, 0.080, 0.050, proc="n1"),
        _span("task.result", tid, 0.130, 0.010, proc="n1"),
        _span("task.serve", tid, 0.063, 0.090, proc="n1"),
    ]


# ----------------------------------------------------------- analyzer units
def test_breakdown_phases_sum_to_wall_with_explicit_residual():
    b = taskpath.task_breakdown(_synthetic_task(), "t1")
    assert b is not None
    # wall: submit start (0.0) -> push end (0.160), driver clock
    assert b["wall_s"] == pytest.approx(0.160)
    p = b["phases"]
    assert p["submit"] == pytest.approx(0.010)
    assert p["lease-wait"] == pytest.approx(0.050)
    assert p["kv-get"] == pytest.approx(0.005)
    assert p["arg-pull"] == pytest.approx(0.010)
    assert p["exec"] == pytest.approx(0.050)
    assert p["result-push"] == pytest.approx(0.010)
    # exec-queue = serve envelope minus the instrumented inner legs
    # (0.090 - 0.075): executor-side wait before instrumented work,
    # carved out of reply-ack/residual since round 15
    assert p["exec-queue"] == pytest.approx(0.015)
    # reply-ack = push span minus the executor's serve envelope
    assert p["reply-ack"] == pytest.approx(0.010)
    # residual is an EXPLICIT phase and the total is exact
    assert "residual" in p and p["residual"] >= 0
    assert sum(p.values()) == pytest.approx(b["wall_s"])


def test_queue_and_fn_phase_naming():
    b = taskpath.task_breakdown(
        _synthetic_task(queue_outcome="warm-pool-hit",
                        fn_outcome="push-through"), "t1")
    p = b["phases"]
    assert p["warm-pool-hit"] == pytest.approx(0.050)
    assert p["lease-wait"] == 0.0
    assert p["fn-push"] == pytest.approx(0.005)
    assert p["kv-get"] == 0.0
    b2 = taskpath.task_breakdown(
        _synthetic_task(queue_outcome="submit-queue"), "t1")
    assert b2["phases"]["submit-queue"] == pytest.approx(0.050)


def test_settle_dwell_carved_from_pump_queue_and_phases_pinned():
    """Round 20: the settle plane splits the old pump-queue dwell at the
    handoff stamp — arrival->handoff stays pump-queue, handoff->settle
    is settle-dwell — and BOTH subtract from derived reply-ack. The
    PHASES tuple is pinned exhaustively: a new recorded stage that
    isn't mapped here would silently lump into the residual."""
    assert taskpath.PHASES == (
        "submit", "submit-queue", "lease-wait", "warm-pool-hit",
        "fn-push", "kv-get", "arg-pull", "exec-queue", "exec",
        "result-push", "reply-window", "pump-queue", "settle-dwell",
        "reply-ack", "residual",
    )
    spans = _synthetic_task() + [
        _span("task.pump_queue", "t1", 0.150, 0.004),
        _span("task.settle_dwell", "t1", 0.154, 0.003),
    ]
    b = taskpath.task_breakdown(spans, "t1")
    p = b["phases"]
    assert p["pump-queue"] == pytest.approx(0.004)
    assert p["settle-dwell"] == pytest.approx(0.003)
    # reply-ack = push - serve - reply-window - pump-queue - settle-dwell
    assert p["reply-ack"] == pytest.approx(0.010 - 0.004 - 0.003)
    assert sum(p.values()) == pytest.approx(b["wall_s"])
    # Exhaustiveness: every phase the breakdown emits is a pinned name.
    assert set(p) == set(taskpath.PHASES)


def test_breakdown_unknown_task_is_none():
    assert taskpath.task_breakdown(_synthetic_task(), "nope") is None
    assert taskpath.task_breakdown([], "t1") is None


def test_phase_table_groups_by_fn_and_formats():
    merged = _synthetic_task("t1") + _synthetic_task("t2")
    events = [
        {"task_id": "t1", "name": "work", "state": "FINISHED"},
        {"task_id": "t2", "name": "work", "state": "FINISHED"},
    ]
    table = taskpath.phase_table(merged, events)
    assert "work" in table
    assert table["work"]["exec"]["count"] == 2
    assert table["work"]["exec"]["total_s"] == pytest.approx(0.100)
    text = taskpath.format_phase_table(table)
    assert "work" in text and "exec" in text
    b = taskpath.task_breakdown(merged, "t1", events)
    text2 = taskpath.format_task_timeline(b)
    assert "t1" in text2 and "residual" in text2 and "lease-wait" in text2


def test_task_events_to_merged_schema_and_corr_join():
    events = [
        {"task_id": "aa", "cid": "aa", "name": "f", "type": "NORMAL_TASK",
         "state": "FINISHED", "start_time": 10.0, "end_time": 10.5,
         "node_id": "node1234abcd"},
        {"task_id": "bb", "cid": "bb", "corr": "c0ffee", "name": "m",
         "type": "ACTOR_TASK", "state": "FAILED", "start_time": 11.0,
         "end_time": 11.1, "node_id": "node1234abcd",
         "actor_id": "act1"},
    ]
    merged = taskpath.task_events_to_merged(events)
    # one track entry per event + one corr-join instant for the actor
    assert len(merged) == 3
    assert all(e["kind"] == "task" for e in merged)
    assert {e["cid"] for e in merged} == {"aa", "bb", "c0ffee"}
    assert merged[0]["proc"] == "task:node1234"
    # exporter accepts them directly
    trace = flight.to_chrome_trace(merged, t0=0.0)
    assert all(ev["ph"] in ("X", "s", "f") for ev in trace)


# ------------------------------------------------------------- cluster join
def test_two_node_join_and_phase_sums(monkeypatch):
    """Task spans + task events from a real 2-node run merge into one
    trace with cross-process flow links; per-task phase sums land within
    10% of the driver-observed wall time, residual explicit."""
    monkeypatch.setenv("RT_FLIGHT_ENABLED", "1")
    ray_tpu.init(num_cpus=2, num_nodes=2)
    try:
        flight.enable()

        @ray_tpu.remote
        def work(x):
            time.sleep(0.02)
            return x + 1

        refs = [work.remote(i) for i in range(16)]
        assert sorted(ray_tpu.get(refs, timeout=60)) == list(range(1, 17))
        # A ref argument forces the slow executor path (arg-pull phase).
        assert ray_tpu.get(work.remote(ray_tpu.put(5)), timeout=60) == 6

        from ray_tpu._private.worker import get_global_worker
        from ray_tpu.util import state

        w = get_global_worker()

        def events_ready():
            evs = state.list_tasks(limit=100_000)
            return sum(1 for e in evs if e.get("name") == "work") >= 17

        wait_for_condition(events_ready, timeout=10)
        events = state.list_tasks(limit=100_000)
        h, _ = w.run_sync(w._head_call("flight_snapshot", {}), 60)
        merged = flight.merge_snapshots(h["snapshots"])

        task_spans = [e for e in merged if e["kind"] == "task"]
        assert task_spans, "no task.* spans recorded"
        # Cross-process join: driver-side push + executor-side exec spans
        # share the task id.
        procs_by_tid = {}
        for e in task_spans:
            procs_by_tid.setdefault(str(e["cid"]), set()).add(e["proc"])
        assert any(len(ps) >= 2 for ps in procs_by_tid.values()), (
            "no task id joined across processes")

        checked = 0
        for tid in procs_by_tid:
            b = taskpath.task_breakdown(merged, tid, events)
            if b is None or b["phases"]["exec"] <= 0 or b["wall_s"] <= 0:
                continue
            total = sum(b["phases"].values())
            assert abs(total - b["wall_s"]) <= 0.1 * b["wall_s"] + 1e-6, (
                f"phases sum {total} vs wall {b['wall_s']} for {tid}")
            assert "residual" in b["phases"]
            # named phases (not residual) carry the bulk of the wall
            named = total - b["phases"]["residual"]
            assert named >= 0.5 * b["wall_s"]
            checked += 1
        assert checked >= 8, f"only {checked} tasks had full breakdowns"

        # per-function table joins names from the task events
        table = taskpath.phase_table(merged, events)
        assert "work" in table and "exec" in table["work"]
        assert table["work"]["exec"]["count"] >= 8

        # one chrome trace over BOTH planes: flow links reach the task
        # tracks built from state-API events
        joined = sorted(merged + taskpath.task_events_to_merged(events),
                        key=lambda e: e["ts"])
        trace = flight.to_chrome_trace(joined, t0=0.0)
        flow_pids = {ev["pid"] for ev in trace if ev["ph"] in ("s", "f")}
        assert any(str(p).startswith("task:") for p in flow_pids), (
            "no flow link touches a task-event track")
    finally:
        ray_tpu.shutdown()


def test_disabled_mode_records_zero_task_spans():
    """One-boolean gate parity with flight.ENABLED: recorder off → zero
    task spans anywhere in the cluster and zero phase observations."""
    from ray_tpu.util.metrics import registry

    def _phase_count():
        for m in registry().snapshot():
            if m["name"] == "rt_task_phase_seconds":
                return sum(s["count"] for s in m["samples"])
        return 0

    before = _phase_count()
    assert not flight.ENABLED
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(8)], timeout=60) \
            == [0, 2, 4, 6, 8, 10, 12, 14]

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        h, _ = w.run_sync(w._head_call("flight_snapshot", {}), 60)
        for snap in h["snapshots"]:
            assert snap["events"] == []
        assert _phase_count() == before
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ schema pinning
REQUIRED_EVENT_FIELDS = {
    "task_id", "name", "type", "state", "start_time", "end_time",
    "node_id", "cid",
}
ALLOWED_EVENT_FIELDS = REQUIRED_EVENT_FIELDS | {"actor_id", "corr"}


def test_task_event_schema_is_pinned():
    """The task-event dict fields are a cross-plane contract: the state
    API consumers (`rt summary tasks`, `rt events`-style listings), the
    chrome-trace exporter, and the taskpath join all parse them. A new
    producer field must be added to ALLOWED_EVENT_FIELDS here (and to
    PARITY.md) deliberately."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def f(x):
            return x

        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        assert ray_tpu.get(f.remote(1), timeout=30) == 1
        a = A.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"

        from ray_tpu.util import state

        def both_types():
            evs = state.list_tasks(limit=10_000)
            types = {e.get("type") for e in evs}
            return {"NORMAL_TASK", "ACTOR_TASK"} <= types

        wait_for_condition(both_types, timeout=10)
        events = state.list_tasks(limit=10_000)
        assert events
        for ev in events:
            keys = set(ev)
            assert REQUIRED_EVENT_FIELDS <= keys, (
                f"missing fields {REQUIRED_EVENT_FIELDS - keys} in {ev}")
            assert keys <= ALLOWED_EVENT_FIELDS, (
                f"unpinned fields {keys - ALLOWED_EVENT_FIELDS} in {ev}")
            assert isinstance(ev["task_id"], str)
            assert ev["cid"] == ev["task_id"]
            assert ev["type"] in ("NORMAL_TASK", "ACTOR_TASK")
            assert ev["state"] in ("FINISHED", "FAILED")
            assert isinstance(ev["start_time"], float)
            assert isinstance(ev["end_time"], float)
            assert ev["end_time"] >= ev["start_time"]
            if ev["type"] == "ACTOR_TASK":
                assert "actor_id" in ev
        # both exporters parse every event without loss
        merged = taskpath.task_events_to_merged(events)
        assert len(merged) >= len(events)
        trace = flight.to_chrome_trace(merged, t0=0.0)
        assert sum(1 for e in trace if e["ph"] == "X") == len(merged)
    finally:
        ray_tpu.shutdown()


def test_head_task_event_ring_is_bounded_and_reports_drops():
    """The head buffer is a maxlen deque (O(1) overflow, oldest dropped)
    and the drop count is reported, never silent."""
    from collections import deque

    from ray_tpu._private.gcs import HeadService

    head = HeadService.__new__(HeadService)
    head.task_events = deque(maxlen=5)
    head._task_events_total = 0
    head._task_state_counts = {}
    import asyncio

    async def drive():
        evs = [{"task_id": f"t{i}", "state": "FINISHED",
                "name": "x" * 1000} for i in range(12)]
        await head.rpc_task_events({"events": evs}, [], None)
        return await head.rpc_list_task_events({"limit": 100}, [], None)

    h, _ = asyncio.run(drive())
    assert len(h["events"]) == 5
    assert h["recorded"] == 12 and h["dropped"] == 7
    # newest kept, oldest dropped; oversized names clamped
    assert h["events"][-1]["task_id"] == "t11"
    assert all(len(e["name"]) <= 256 for e in h["events"])
    assert head._task_state_counts["FINISHED"] == 12


# ----------------------------------------------------------- metrics rollup
def test_rollup_histogram_merges_across_workers():
    from ray_tpu.util.metrics import rollup_histogram

    def snap(count):
        return [{
            "name": "rt_task_phase_seconds", "type": "histogram",
            "help": "h", "boundaries": [0.1, 1.0],
            "samples": [{
                "tags": {"phase": "exec", "fn": "f"},
                "buckets": [count, 0, 0], "sum": 0.05 * count,
                "count": count,
            }],
        }]

    text = rollup_histogram(
        {"w1": snap(2), "w2": snap(3), "w3": snap(5)},
        "rt_task_phase_seconds",
        {"w1": "nodeA", "w2": "nodeA", "w3": "nodeB"},
    )
    # same node merges; distinct nodes stay separate
    assert 'node_id="nodeA"' in text and 'node_id="nodeB"' in text
    lines = text.splitlines()
    counts = {
        ln.rsplit(" ", 1)[0]: ln.rsplit(" ", 1)[1]
        for ln in lines if "_count" in ln
    }
    assert any(v == "5" for k, v in counts.items() if "nodeA" in k)
    assert any(v == "5" for k, v in counts.items() if "nodeB" in k)


def test_head_metrics_endpoint_covers_every_node(monkeypatch):
    """Acceptance: one scrape of the head's /metrics exposes
    rt_task_phase_seconds histograms covering every node of a 2-node
    cluster (the per-node series are rolled up head-side)."""
    monkeypatch.setenv("RT_FLIGHT_ENABLED", "1")
    ray_tpu.init(num_cpus=1, num_nodes=2)
    try:
        flight.enable()

        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote
        def burn(i):
            time.sleep(0.05)
            return i

        from ray_tpu._private.worker import get_global_worker
        from ray_tpu.dashboard import DashboardApp

        cluster = ray_tpu._internal_cluster()
        node_ids = {n.node_id[:12] for n in cluster.nodes}
        assert len(node_ids) == 2
        # Pin 4 tasks to EACH node: every node must observe exec phases.
        refs = [
            burn.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    n.node_id
                )
            ).remote(i)
            for n in cluster.nodes for i in range(4)
        ]
        assert sorted(ray_tpu.get(refs, timeout=60)) == sorted(
            list(range(4)) * 2
        )
        w = get_global_worker()
        dash = DashboardApp(cluster.head, "127.0.0.1", 0)
        port = w.run_sync(dash.start(), 30)
        try:
            def scraped():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as r:
                    text = r.read().decode()
                lines = [ln for ln in text.splitlines()
                         if ln.startswith("rt_task_phase_seconds")]
                if not lines:
                    return False
                covered = {nid for nid in node_ids
                           if any(f'node_id="{nid}"' in ln
                                  for ln in lines)}
                # rollup series: phase+fn tags present, per-worker
                # copies excluded (no double counting on sum())
                assert all('worker_id=' not in ln for ln in lines)
                return covered == node_ids and any(
                    'phase="exec"' in ln and 'fn="burn"' in ln
                    for ln in lines
                )

            # workers push metrics every ~2s
            wait_for_condition(scraped, timeout=20)
        finally:
            w.run_sync(dash.stop(), 10)
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ bench --phases
def test_bench_phases_records_per_function_table(monkeypatch, tmp_path):
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench
    import ray_tpu._private.perf as perf

    monkeypatch.setenv("RT_FLIGHT_ENABLED", "1")
    ray_tpu.init(num_cpus=2)
    try:
        def tiny_leg(n=0):
            @ray_tpu.remote
            def bench_tiny(x):
                return x + 1

            assert sorted(ray_tpu.get(
                [bench_tiny.remote(i) for i in range(20)], timeout=60
            )) == list(range(1, 21))
            return 1.0

        monkeypatch.setattr(perf, "bench_many_actors", tiny_leg)
        monkeypatch.setattr(perf, "bench_queued_tasks", tiny_leg)
        out = bench.run_flight_benchmarks(
            quick=True, phases=True,
            attrib_path=str(tmp_path / "flight_attrib.json"),
        )
        assert "task_phases" in out
        tables = out["task_phases"]
        assert set(tables) == {"many_actors_per_s", "queued_5k_tasks_s"}
        merged_fns = set()
        for table in tables.values():
            merged_fns |= set(table)
        assert "bench_tiny" in merged_fns
        # the table rides the attrib json too
        data = json.loads((tmp_path / "flight_attrib.json").read_text())
        assert "task_phases" in data["queued_5k_tasks_s"]
    finally:
        ray_tpu.shutdown()
