"""Reply-plane batching & arg-interning economics (round 15).

Pins the RPC shape of the coalesced reply plane the way
``test_submission_plane.py`` pins the request side:

- a queued single-peer burst settles with O(bursts) coalesced reply
  frames (the executor's ReplyWindow self-clocks on the driver's acks),
  never one reply message per task;
- a repeated small argument frame ships its bytes ONCE per peer
  (digest-only afterwards), and the bytes reaching the executor are
  byte-identical to what the submitter framed — including across
  receiver-LRU eviction, where the typed ``arg_intern_miss`` makes the
  pusher re-send the blob;
- a dropped coalesced reply frame re-arms the per-task deadlines and the
  corr-deduped re-push REPLAYS recorded outcomes (each task executes
  exactly once, no future settles twice);
- ``worker.shutdown()`` flushes results still riding an open window
  (the PR 7 tail-event flush discipline, applied to the reply plane);
- the ``reply_batching`` / ``arg_interning`` gates restore the per-task /
  per-arg wire byte-identically when off.
"""
import time

import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import protocol, specframe
from ray_tpu._private import worker as worker_mod


@pytest.fixture(autouse=True)
def _fp_clean():
    fp.clear()
    yield
    fp.clear()


# ------------------------------------------------------ window mechanics
def test_reply_window_self_clocks_on_acks():
    """First result of an idle window flushes immediately; results
    completing before the ack ride the NEXT frame; an ack over an empty
    buffer returns the window to idle (= the create_actor_batch
    discipline, mirrored onto replies)."""
    sent = []
    w = specframe.ReplyWindow(sent.append, max_items=100, horizon_s=60.0)
    w.add({"i": 1}, [b"x"])
    assert [len(b) for b in sent] == [1]  # opener: a frame of one, NOW
    for i in range(2, 12):
        w.add({"i": i}, [b"x"])
    assert len(sent) == 1  # all ten ride the in-flight ack
    w.on_ack()
    assert len(sent) == 2 and len(sent[1]) == 10  # ONE frame for all ten
    assert [s["i"] for s, _f, _t in sent[1]] == list(range(2, 12))
    w.on_ack()  # nothing buffered: back to idle
    w.add({"i": 99}, [b"y"])
    assert len(sent) == 3 and len(sent[2]) == 1  # idle again => immediate


def test_reply_window_caps_and_horizon():
    """Item/byte caps force a mid-ack flush (bounded memory, frames stay
    under the ring limit); a lapsed ack horizon re-arms the window so a
    lost ack can never buffer results forever."""
    sent = []
    w = specframe.ReplyWindow(sent.append, max_items=4, horizon_s=60.0)
    w.add({"i": 0}, [b"x"])
    for i in range(1, 5):
        w.add({"i": i}, [b"x"])
    assert len(sent) == 2 and len(sent[1]) == 4  # item cap flushed
    sent.clear()
    w = specframe.ReplyWindow(sent.append, max_bytes=100, horizon_s=60.0)
    w.add({"i": 0}, [b"x"])
    w.add({"i": 1}, [b"y" * 200])  # byte cap exceeded while in flight
    assert len(sent) == 2
    sent.clear()
    w = specframe.ReplyWindow(sent.append, horizon_s=0.0)
    for i in range(3):
        w.add({"i": i}, [b"x"])
    assert len(sent) == 3  # horizon 0 = every add re-arms (degenerate)


def test_reply_window_timer_mode_gap_paces_and_tail_flushes():
    """Ring-mode window (gap + defer): a quiet window flushes the first
    result immediately; results inside the gap buffer and go out via the
    deferred tail flush — which re-arms itself while traffic flows and
    quiesces on an empty tick. No acks are involved (on_ack is a no-op:
    ring flushes carry no ``wa``, so there is no mrack traffic to
    contend with the pusher on the ring send lock)."""
    sent = []
    timers = []
    w = specframe.ReplyWindow(
        sent.append, max_items=100, gap_s=60.0,
        defer=lambda delay, cb: timers.append((delay, cb)),
    )
    w.add({"i": 1}, [b"x"])
    assert [len(b) for b in sent] == [1]  # quiet window: immediate
    for i in range(2, 6):
        w.add({"i": i}, [b"x"])
    assert len(sent) == 1  # inside the gap: buffered
    assert len(timers) == 1  # ONE armed tail timer for the whole buffer
    w.on_ack()  # acks are not this mode's clock
    assert len(sent) == 1
    timers.pop()[1]()  # gap elapses
    assert len(sent) == 2 and len(sent[1]) == 4
    assert [s["i"] for s, _f, _t in sent[1]] == [2, 3, 4, 5]
    assert len(timers) == 1  # flushed => re-armed (traffic may continue)
    timers.pop()[1]()  # empty tick: quiesce, no flush, no re-arm
    assert len(sent) == 2 and not timers
    # A batch landing inside the gap of the LAST flush still buffers —
    # quiescing stops the ticker, not the gap clock — and arms a fresh
    # tail timer that delivers it as one frame.
    w.add_many([({"i": 9}, [b"y"], None), ({"i": 10}, [b"y"], None)])
    assert len(sent) == 2 and len(timers) == 1
    timers.pop()[1]()
    assert len(sent) == 3 and len(sent[2]) == 2


def test_reply_window_add_many_matches_add_semantics():
    """The drain loop's batch hand-off obeys the same caps and clock as
    per-result adds (ack mode here): a batch landing on a quiet window
    emits once; batches riding an in-flight frame buffer until the ack,
    with the item cap forcing a mid-ack flush."""
    sent = []
    w = specframe.ReplyWindow(sent.append, max_items=5, horizon_s=60.0)
    w.add_many([({"i": 0}, [b"x"], None)])
    assert len(sent) == 1
    w.add_many([({"i": i}, [b"x"], None) for i in (1, 2)])
    assert len(sent) == 1  # rides the in-flight ack
    w.add_many([({"i": i}, [b"x"], None) for i in (3, 4, 5)])
    assert len(sent) == 2 and len(sent[1]) == 5  # item cap crossed
    w.on_ack()
    assert len(sent) == 2  # nothing left behind the cap flush


def test_shutdown_flushes_open_reply_windows(rt_start):
    """Results buffered behind a lost ack must not die with the process:
    the shutdown step drains every open window (regression for the
    graceful-drain / short-lived-executor path, beside the PR 7
    tail-event flush)."""
    sent = []
    win = specframe.ReplyWindow(sent.append, horizon_s=60.0)
    win.add({"i": 1}, [b"a"])
    win.add({"i": 2}, [b"b"])
    win.add({"i": 3}, [b"c"])
    assert len(sent) == 1  # two results parked behind the unacked opener

    class _Conn:
        _closed = False

    conn = _Conn()
    conn._rt_reply_window = win
    w = worker_mod.global_worker
    w._reply_windows.append(conn)
    try:
        w._flush_reply_windows()
    finally:
        w._reply_windows.remove(conn)
    assert len(sent) == 2
    assert [s["i"] for s, _f, _t in sent[1]] == [2, 3]


# ------------------------------------------------- arg interning mechanics
def test_arg_intern_wire_roundtrip_is_byte_exact(rt_start):
    """Wire-build + executing-side expansion round-trip on real worker
    state: first push ships blobs and asks the peer to intern (``aib``),
    the second carries digests only (``ai``) and reconstructs the EXACT
    bytes; a purged digest raises the typed miss, never garbage."""
    w = worker_mod.global_worker
    peer = ("test-peer", 1)
    header = {"tid": "ab" * 12, "fkey": "f" * 40, "i": 7, "nret": 1}
    frames = [b"meta", b"y" * 500, b"z" * 300]  # meta below min: inline
    h1, w1 = w._arg_intern_wire(peer, header, frames)
    assert "aib" in h1 and "ai" not in h1
    assert w1 == frames  # first push: full bytes still on the wire
    eh1, ef1 = w._expand_task_header(h1, w1)
    assert ef1 == frames and "aib" not in eh1

    h2, w2 = w._arg_intern_wire(peer, header, frames)
    assert "ai" in h2 and "aib" not in h2
    assert w2 == [b"meta"]  # repeated frames stayed home
    eh2, ef2 = w._expand_task_header(h2, w2)
    assert ef2 == frames  # byte-exact reconstruction from the LRU

    # Evict and retry the digest-only wire: typed miss, pusher re-sends.
    w._arg_intern.purge([d for _p, d in h2["ai"]])
    with pytest.raises(protocol.RpcError) as ei:
        w._expand_task_header(h2, w2)
    assert ei.value.code == "arg_intern_miss"
    w._arg_ledger.forget_peer(peer)


def test_gates_off_keep_wire_and_paths_byte_identical(monkeypatch):
    """RT_REPLY_BATCHING=0 / RT_ARG_INTERNING=0 restore the pre-round-15
    behavior exactly: _task_wire is the identity composition (same
    objects, no ai/aib/corr), no window ever opens, no reply frame ever
    coalesces."""
    monkeypatch.setenv("RT_REPLY_BATCHING", "0")
    monkeypatch.setenv("RT_ARG_INTERNING", "0")
    ray_tpu.init(num_cpus=2)
    try:
        w = worker_mod.global_worker
        assert not w._reply_batching and not w._arg_interning
        header = {"tid": "cd" * 12, "fkey": "g" * 40, "nret": 1}
        frames = [b"meta", b"y" * 500]
        h2, f2 = w._arg_intern_wire(("p", 1), header, frames)
        assert h2 is header and f2 is frames  # identity, not a copy

        @ray_tpu.remote
        def f(cfg, i):
            return (cfg["v"], i)

        cfg = {"pad": "x" * 4096, "v": 5}
        n = 60
        assert ray_tpu.get([f.remote(cfg, i) for i in range(n)],
                           timeout=120) == [(5, i) for i in range(n)]
        assert w._stats["arg_frames_interned"] == 0
        assert w._stats["arg_blobs_pushed"] == 0

        @ray_tpu.remote
        def stats():
            return dict(worker_mod.global_worker._stats)

        s = ray_tpu.get(stats.remote(), timeout=60)
        assert s["reply_windows_flushed"] == 0
        assert s["reply_results_coalesced"] == 0
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------- RPC economics
@pytest.mark.parametrize("rt_start", [dict(num_cpus=2)], indirect=True)
def test_queued_burst_reply_frames_are_o_bursts(rt_start):
    """A queued single-peer noop burst settles in far fewer coalesced
    reply frames than tasks: the opener flushes immediately, everything
    completing behind it rides the in-flight ack. (The exact count is
    load-dependent; the invariant is frames << tasks, average batch >= 2
    even on a box where acks return instantly.)"""

    @ray_tpu.remote
    def stats():
        return dict(worker_mod.global_worker._stats)

    @ray_tpu.remote
    def noop(i):
        return i

    ray_tpu.get([noop.remote(i) for i in range(20)], timeout=120)  # warm
    before = ray_tpu.get(stats.remote(), timeout=60)
    n = 400
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    after = ray_tpu.get(stats.remote(), timeout=60)
    coalesced = (after["reply_results_coalesced"]
                 - before["reply_results_coalesced"])
    flushed = (after["reply_windows_flushed"]
               - before["reply_windows_flushed"])
    assert coalesced >= n  # every small result rode a window
    assert flushed <= coalesced // 2, (flushed, coalesced)


def test_arg_blob_ships_once_per_peer(rt_start):
    """The classic "same config dict to N tasks" shape: the serialized
    arg frame crosses the wire ONCE (aib), every later push carries the
    16-byte digest — O(unique args) arg bytes per peer — and the values
    the tasks observe round-trip exactly."""
    w = worker_mod.global_worker
    cfg = {"pad": "x" * 8192, "v": 11}

    @ray_tpu.remote
    def use(c, i):
        return (c, i)

    base_interned = w._stats["arg_frames_interned"]
    base_saved = w._stats["arg_intern_bytes_saved"]
    n = 50
    out = ray_tpu.get([use.remote(cfg, i) for i in range(n)], timeout=120)
    assert out == [(cfg, i) for i in range(n)]  # byte-exact round trip
    interned = w._stats["arg_frames_interned"] - base_interned
    saved = w._stats["arg_intern_bytes_saved"] - base_saved
    assert interned >= n - 2, interned  # the blob shipped at most twice
    assert saved >= (n - 2) * 8000, saved


def test_intern_eviction_miss_resends_byte_exact(monkeypatch):
    """A receiver LRU small enough to thrash forces real evictions: the
    digest-only push surfaces the typed miss, the pusher resets coverage
    and re-sends the blob, and every task still sees exact bytes."""
    monkeypatch.setenv("RT_ARG_INTERN_CACHE_BYTES", "20000")
    ray_tpu.init(num_cpus=2)
    try:
        w = worker_mod.global_worker

        @ray_tpu.remote
        def use(c):
            return c

        cfgs = [{"k": i, "pad": chr(ord("a") + i) * 9000} for i in range(3)]
        # Cover all three (third insert evicts the first), then re-use
        # the first: its digest-only push MUST miss and recover.
        for cfg in cfgs:
            assert ray_tpu.get(use.remote(cfg), timeout=120) == cfg
        for cfg in cfgs:
            assert ray_tpu.get(use.remote(cfg), timeout=120) == cfg
        assert w._stats["arg_intern_miss_retries"] >= 1
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------- drop / replay semantics
def test_dropped_window_frame_replays_without_reexecution(monkeypatch):
    """The first coalesced reply frame is dropped in transit AFTER the
    tasks ran: every rider's per-task deadline re-arms, the re-push hits
    the executor's corr-dedup cache and REPLAYS the recorded outcomes —
    results arrive correct, each task executed exactly once, and no
    future is ever settled twice (a double settle would raise in
    asyncio; a re-execution shows in the executor-side counter)."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "1")
    ray_tpu.init(num_cpus=2)
    cluster = ray_tpu._internal_cluster()
    try:
        cluster.add_node(
            resources={"CPU": 2, "doom": 100},
            env={"RT_FAULT_SPEC": "worker.reply.window:drop:1.0:1:42"},
        )

        @ray_tpu.remote(num_cpus=0)
        def bump(i):
            st = worker_mod.global_worker._stats
            st["_test_execs"] = st.get("_test_execs", 0) + 1
            return i * 3

        n = 24
        refs = [bump.options(resources={"doom": 1}).remote(i)
                for i in range(n)]
        assert ray_tpu.get(refs, timeout=120) == [i * 3 for i in range(n)]

        @ray_tpu.remote(num_cpus=0)
        def probe():
            from ray_tpu._private import faultpoints as fpp

            return (dict(worker_mod.global_worker._stats), fpp.stats())

        s, fstats = ray_tpu.get(
            probe.options(resources={"doom": 1}).remote(), timeout=60
        )
        assert sum(x["injected"] for x in fstats) == 1, fstats  # it fired
        assert s.get("_test_execs") == n  # replay, never re-execution
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------------------- TCP parity
def test_reply_batching_over_tcp(monkeypatch):
    """With the shm ring disabled the slow path serves every push over
    TCP — results must still coalesce (Connection.send_reply_batch, the
    batched-reply unpack, and the mrack ack all exercised) and the wire
    stays correct."""
    monkeypatch.setenv("RT_NATIVE_RING", "0")
    ray_tpu.init(num_cpus=2)
    try:

        @ray_tpu.remote
        def noop(i):
            return i

        n = 100
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))

        @ray_tpu.remote
        def stats():
            return dict(worker_mod.global_worker._stats)

        s = ray_tpu.get(stats.remote(), timeout=60)
        assert s["reply_windows_flushed"] > 0
        assert s["reply_results_coalesced"] >= n
        assert s["reply_windows_flushed"] < s["reply_results_coalesced"]
    finally:
        ray_tpu.shutdown()
