"""RLlib-equivalent layer: learning, estimators, fault tolerance, tune glue.

Reference analog: per-algorithm learning tests under
``rllib/algorithms/*/tests`` (CartPole-learns gates) and env-runner fault
tolerance tests in ``rllib/env/``.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import IMPALAConfig, PPOConfig, make_trainable
from ray_tpu.rllib.learner import compute_gae, vtrace


# ---------------------------------------------------------- pure estimators


def test_gae_matches_numpy_reference():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    T, N = 17, 3
    rewards = rng.randn(T, N).astype(np.float32)
    dones = (rng.rand(T, N) < 0.15).astype(np.float32)
    values = rng.randn(T, N).astype(np.float32)
    bootstrap = rng.randn(N).astype(np.float32)
    gamma, lam = 0.97, 0.9

    advs, targets = compute_gae(
        jnp.asarray(rewards), jnp.asarray(dones), jnp.asarray(values),
        jnp.asarray(bootstrap), gamma, lam,
    )
    # reference: explicit reverse loop
    ref = np.zeros((T, N), np.float32)
    acc = np.zeros(N, np.float32)
    next_v = bootstrap.copy()
    for t in range(T - 1, -1, -1):
        delta = rewards[t] + gamma * next_v * (1 - dones[t]) - values[t]
        acc = delta + gamma * lam * (1 - dones[t]) * acc
        ref[t] = acc
        next_v = values[t]
    np.testing.assert_allclose(np.asarray(advs), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(targets), ref + values, rtol=1e-5, atol=1e-5
    )


def test_vtrace_matches_numpy_reference():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    T, N = 11, 2
    logp_t = rng.randn(T, N).astype(np.float32) * 0.3
    logp_b = rng.randn(T, N).astype(np.float32) * 0.3
    rewards = rng.randn(T, N).astype(np.float32)
    dones = (rng.rand(T, N) < 0.2).astype(np.float32)
    values = rng.randn(T, N).astype(np.float32)
    bootstrap = rng.randn(N).astype(np.float32)
    gamma, rho_c, c_c = 0.99, 1.0, 1.0

    vs, pg = vtrace(
        jnp.asarray(logp_t), jnp.asarray(logp_b), jnp.asarray(rewards),
        jnp.asarray(dones), jnp.asarray(values), jnp.asarray(bootstrap),
        gamma, rho_c, c_c,
    )
    rhos = np.minimum(np.exp(logp_t - logp_b), rho_c)
    cs = np.minimum(np.exp(logp_t - logp_b), c_c)
    disc = gamma * (1 - dones)
    next_v = np.concatenate([values[1:], bootstrap[None]], 0)
    deltas = rhos * (rewards + disc * next_v - values)
    acc = np.zeros(N, np.float32)
    dv = np.zeros((T, N), np.float32)
    for t in range(T - 1, -1, -1):
        acc = deltas[t] + disc[t] * cs[t] * acc
        dv[t] = acc
    vs_ref = values + dv
    np.testing.assert_allclose(np.asarray(vs), vs_ref, rtol=1e-4, atol=1e-4)
    next_vs = np.concatenate([vs_ref[1:], bootstrap[None]], 0)
    pg_ref = rhos * (rewards + disc * next_vs - values)
    np.testing.assert_allclose(np.asarray(pg), pg_ref, rtol=1e-4, atol=1e-4)


def test_vtrace_on_policy_equals_nstep():
    """With identical policies (rhos = 1), vs is the Bellman evaluation of
    the trajectory return — check against discounted rollup on a done-free
    fragment."""
    import jax.numpy as jnp

    T, N = 8, 1
    rewards = np.ones((T, N), np.float32)
    dones = np.zeros((T, N), np.float32)
    values = np.zeros((T, N), np.float32)
    bootstrap = np.zeros(N, np.float32)
    logp = np.zeros((T, N), np.float32)
    vs, _ = vtrace(
        jnp.asarray(logp), jnp.asarray(logp), jnp.asarray(rewards),
        jnp.asarray(dones), jnp.asarray(values), jnp.asarray(bootstrap),
        0.9, 1.0, 1.0,
    )
    expected0 = sum(0.9 ** t for t in range(T))
    assert abs(float(vs[0, 0]) - expected0) < 1e-4


# ------------------------------------------------------------- learning


@pytest.fixture
def rl_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    ray_tpu.shutdown()


def _ppo_config(**training):
    cfg = (PPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=64)
           .debugging(seed=0))
    if training:
        cfg.training(**training)
    return cfg


def test_ppo_cartpole_learns(rl_cluster):
    algo = _ppo_config().build_algo()
    try:
        first, last = None, None
        for _ in range(40):
            r = algo.train()
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
            if last >= 150:
                break
        assert last is not None and first is not None
        assert last >= 120, f"PPO failed to learn: {first} -> {last}"
    finally:
        algo.stop()


def test_impala_cartpole_improves(rl_cluster):
    cfg = (IMPALAConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=32)
           .debugging(seed=0))
    algo = cfg.build_algo()
    try:
        first, last = None, None
        for _ in range(60):
            r = algo.train()
            assert np.isfinite(r.get("total_loss", 0.0))
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
            if last >= 80:
                break
        assert last >= max(40.0, 1.5 * first), (
            f"IMPALA did not improve: {first} -> {last}"
        )
    finally:
        algo.stop()


def test_checkpoint_save_restore(rl_cluster, tmp_path):
    import jax

    algo = _ppo_config().build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
        w0 = algo.get_weights()
        it0 = algo.iteration
    finally:
        algo.stop()

    algo2 = _ppo_config().build_algo()
    try:
        algo2.restore(path)
        assert algo2.iteration == it0
        w1 = algo2.get_weights()
        for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(w1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        algo2.train()  # resumes cleanly
    finally:
        algo2.stop()


def test_env_runner_restart_after_kill(rl_cluster):
    algo = _ppo_config().build_algo()
    try:
        algo.train()
        # kill one runner actor out from under the group
        ray_tpu.kill(algo.runner_group.runners[0])
        r = algo.train()  # dead runner skipped, then respawned
        assert r["training_iteration"] == 2
        r = algo.train()  # respawned runner participates again
        frags = algo.runner_group.sample()
        assert len(frags) == 2
    finally:
        algo.stop()


def test_tune_integration(rl_cluster, tmp_path):
    from ray_tpu import tune

    trainable = make_trainable(
        _ppo_config().env_runners(num_env_runners=1,
                                  num_envs_per_env_runner=4),
        stop_iters=2,
    )
    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([3e-4, 1e-3])},
        tune_config=tune.TuneConfig(
            metric="episode_return_mean", mode="max",
        ),
        run_config=ray_tpu.train.RunConfig(
            storage_path=str(tmp_path), name="rl_tune"
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert "episode_return_mean" in best.metrics


def test_learner_spmd_mesh_update():
    """Learner DP over a device mesh: batch sharded on the data axis, params
    replicated; XLA inserts the gradient psum (no host-loop DDP)."""
    import jax
    from jax.sharding import Mesh

    from ray_tpu.rllib.learner import Learner, LearnerHyperparams
    from ray_tpu.rllib.module import RLModuleConfig

    devices = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devices, ("data",))
    cfg = RLModuleConfig(obs_dim=4, action_dim=2, discrete=True)
    hp = LearnerHyperparams(minibatch_count=2, num_sgd_epochs=2)
    learner = Learner("ppo", cfg, hp, seed=0, mesh=mesh)
    rng = np.random.RandomState(0)
    T, N = 16, 8  # N divides the data axis
    batch = {
        "obs": rng.randn(T, N, 4).astype(np.float32),
        "actions": rng.randint(0, 2, (T, N)).astype(np.int32),
        "rewards": rng.randn(T, N).astype(np.float32),
        "dones": np.zeros((T, N), np.float32),
        "logp": (-np.log(2) * np.ones((T, N))).astype(np.float32),
        "values": rng.randn(T, N).astype(np.float32),
        "bootstrap_value": rng.randn(N).astype(np.float32),
    }
    m1 = learner.update(batch)
    m2 = learner.update(batch)
    assert np.isfinite(m1["total_loss"]) and np.isfinite(m2["total_loss"])


def test_dqn_cartpole_learns(rl_cluster):
    from ray_tpu.rllib import DQNConfig

    cfg = (DQNConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=32)
           .debugging(seed=0))
    algo = cfg.build_algo()
    try:
        first, last = None, None
        for _ in range(120):
            r = algo.train()
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
            if last >= 120:
                break
        assert last >= 100, f"DQN failed to learn: {first} -> {last}"
        assert r["epsilon"] < 0.5  # annealing in effect (broadcast in params)
    finally:
        algo.stop()


def test_dqn_checkpoint_roundtrip(rl_cluster, tmp_path):
    import jax
    import numpy as np

    from ray_tpu.rllib import DQNConfig

    cfg = (DQNConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                        rollout_fragment_length=16)
           .debugging(seed=0))
    algo = cfg.build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "dqn_ckpt"))
        w0 = algo.get_weights()
    finally:
        algo.stop()
    algo2 = cfg.build_algo()
    try:
        algo2.restore(path)
        for a, b in zip(jax.tree.leaves(w0),
                        jax.tree.leaves(algo2.get_weights())):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        algo2.train()
    finally:
        algo2.stop()


def test_appo_cartpole_improves(rl_cluster):
    """APPO (reference: rllib/algorithms/appo): IMPALA-style stale
    sampling + V-trace with the PPO clipped surrogate."""
    from ray_tpu.rllib.algorithms import APPOConfig

    cfg = (APPOConfig()
           .environment("CartPole-v1")
           .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                        rollout_fragment_length=32)
           .debugging(seed=0))
    algo = cfg.build_algo()
    try:
        first, last = None, None
        for _ in range(60):
            r = algo.train()
            assert np.isfinite(r.get("total_loss", 0.0))
            assert "kl" in r  # the clip-surrogate loss reports kl
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
            if last >= 80:
                break
        assert last >= max(40.0, 1.5 * first), (
            f"APPO did not improve: {first} -> {last}"
        )
    finally:
        algo.stop()
