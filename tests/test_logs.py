"""Worker log plane: capture, head buffering, driver echo, state API.

Reference behavior (not code): ``python/ray/_private/log_monitor.py``
(tail redirected worker files, publish over pubsub) and
``python/ray/_private/worker.py`` print_worker_logs (prefixed driver
echo). Here the worker self-tails (process-per-host) — see
``ray_tpu/_private/log_monitor.py``.
"""
import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture()
def rt_logs():
    ray_tpu.init(num_cpus=2, num_nodes=1)
    yield
    ray_tpu.shutdown()


def _wait_for(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    return None


def test_task_print_reaches_driver_and_head(rt_logs, capfd):
    marker = f"log-marker-{os.getpid()}"

    @ray_tpu.remote
    def shout():
        print(marker, flush=True)
        print(f"{marker}-err", file=sys.stderr, flush=True)
        return 1

    assert ray_tpu.get(shout.remote(), timeout=30) == 1

    # Head buffer: the worker's monitor tails its redirected files and
    # publishes; rt logs / dashboard read this back.
    def head_has():
        lines = state.list_logs(tail=5000)
        got = {(r["stream"]) for r in lines if marker in r["line"]}
        return got if {"stdout", "stderr"} <= got else None

    assert _wait_for(head_has), "marker lines never reached the head buffer"

    # Driver echo: the subscribed driver prints the line prefixed with
    # (worker pid=..., node=...).
    def echoed():
        out = capfd.readouterr()
        echoed.buf += out.out + out.err
        return marker in echoed.buf and "(worker pid=" in echoed.buf
    echoed.buf = ""
    assert _wait_for(echoed), "driver never echoed the worker print"


def test_log_files_exist_in_session_dir(rt_logs):
    @ray_tpu.remote
    def hello():
        print("file-marker-xyz", flush=True)
        return None

    ray_tpu.get(hello.remote(), timeout=30)

    # Scan only THIS cluster's session dir — a stale marker left by an
    # earlier run must not mask a broken redirect.
    session = ray_tpu._internal_cluster().session_dir
    assert session, "LocalCluster lost its session dir"

    def file_has():
        d = os.path.join(session, "logs")
        if not os.path.isdir(d):
            return False
        for f in os.listdir(d):
            if f.endswith(".out"):
                with open(os.path.join(d, f)) as fh:
                    if "file-marker-xyz" in fh.read():
                        return True
        return False

    assert _wait_for(file_has), "worker stdout file missing the print"
