"""Collective layer tests (reference test model:
``python/ray/util/collective/tests/``)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@ray_tpu.remote
class Member:
    def __init__(self, world_size: int, rank: int, group_name: str):
        self.rank = rank
        self.world = world_size
        col.init_collective_group(
            world_size, rank, backend="host", group_name=group_name
        )
        self.group = group_name

    def do_allreduce(self):
        x = np.full((4,), float(self.rank + 1), np.float32)
        return col.allreduce(x, self.group)

    def do_broadcast(self):
        x = (
            np.arange(3, dtype=np.float32)
            if self.rank == 0
            else np.zeros(3, np.float32)
        )
        return col.broadcast(x, src_rank=0, group_name=self.group)

    def do_allgather(self):
        return col.allgather(np.array([self.rank], np.int64), self.group)

    def do_reducescatter(self):
        x = np.arange(self.world * 2, dtype=np.float32)
        return col.reducescatter(x, self.group)

    def do_barrier(self):
        col.barrier(self.group)
        return self.rank

    def do_sendrecv(self):
        if self.rank == 0:
            col.send(np.array([42.0]), dst_rank=1, group_name=self.group)
            return None
        return col.recv(src_rank=0, group_name=self.group)

    def rank_info(self):
        return col.get_rank(self.group), col.get_collective_group_size(self.group)


@pytest.fixture
def members(rt_start):
    world = 3
    ms = [Member.remote(world, r, "g1") for r in range(world)]
    yield ms
    for m in ms:
        ray_tpu.kill(m)


def test_allreduce(members):
    outs = ray_tpu.get([m.do_allreduce.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 6.0))


def test_broadcast(members):
    outs = ray_tpu.get([m.do_broadcast.remote() for m in members])
    for o in outs:
        np.testing.assert_allclose(o, np.arange(3, dtype=np.float32))


def test_allgather_and_rank(members):
    outs = ray_tpu.get([m.do_allgather.remote() for m in members])
    for o in outs:
        assert [int(v[0]) for v in o] == [0, 1, 2]
    infos = ray_tpu.get([m.rank_info.remote() for m in members])
    assert infos == [(0, 3), (1, 3), (2, 3)]


def test_reducescatter(members):
    outs = ray_tpu.get([m.do_reducescatter.remote() for m in members])
    full = np.arange(6, dtype=np.float32) * 3
    got = np.concatenate(outs)
    np.testing.assert_allclose(got, full)


def test_barrier_and_sendrecv(members):
    assert sorted(ray_tpu.get([m.do_barrier.remote() for m in members])) == [0, 1, 2]
    outs = ray_tpu.get([m.do_sendrecv.remote() for m in members[:2]])
    assert outs[0] is None
    np.testing.assert_allclose(outs[1], [42.0])


@ray_tpu.remote
class PassiveMember:
    """No init_collective_group call — membership comes from the driver's
    declarative create_collective_group."""

    def do_allreduce(self, value: float):
        return col.allreduce(np.full((2,), value, np.float32), "gdecl")


def test_declarative_create_collective_group(rt_start):
    ms = [PassiveMember.remote() for _ in range(2)]
    col.create_collective_group(ms, 2, [0, 1], backend="host",
                                group_name="gdecl")
    outs = ray_tpu.get([m.do_allreduce.remote(float(i + 1))
                        for i, m in enumerate(ms)])
    for o in outs:
        np.testing.assert_allclose(o, np.full((2,), 3.0))
    col.destroy_collective_group("gdecl")
    for m in ms:
        ray_tpu.kill(m)


def test_world_size_mismatch_detected(rt_start):
    ms = [Member.remote(2, r, "gsize") for r in range(2)]
    ray_tpu.get([m.rank_info.remote() for m in ms])
    # Same group name, different world size, coordinator still alive → the
    # member's init fails loudly (raised from the actor's __init__)
    with pytest.raises(Exception, match="world_size"):
        bad = Member.remote(3, 0, "gsize")
        ray_tpu.get(bad.rank_info.remote())
    for m in ms:
        ray_tpu.kill(m)


def test_ici_product_allreduce_with_negatives():
    """PRODUCT must be exact for negative/zero inputs (no log/exp trick)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from ray_tpu.util.collective.types import ReduceOp

    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    x = jnp.array([[-2.0], [3.0], [-1.0], [0.5]])

    f = shard_map(
        lambda xs: col.ici_allreduce(xs, "x", op=ReduceOp.PRODUCT),
        mesh=mesh, in_specs=P("x", None), out_specs=P("x", None),
        check_rep=False,
    )
    out = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(out), np.full((4, 1), 3.0))


def test_ici_collectives_in_jit():
    """In-jit collectives under shard_map on the 8-device CPU mesh."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("x",))
    x = jnp.arange(8.0).reshape(4, 2)

    def body(xs):
        s = col.ici_allreduce(xs, "x")
        g = col.ici_allgather(xs, "x", axis=0)
        rs = col.ici_reducescatter(g, "x", axis=0)
        b = col.ici_broadcast(xs, "x", root=2)
        return s, g, rs, b

    f = shard_map(
        body, mesh=mesh, in_specs=P("x", None),
        out_specs=(P("x", None), P(None, None), P("x", None), P("x", None)),
        check_rep=False,
    )
    s, g, rs, b = jax.jit(f)(x)
    np.testing.assert_allclose(
        np.asarray(s), np.tile(x.sum(axis=0, keepdims=True), (4, 1))
    )
    np.testing.assert_allclose(np.asarray(g), np.asarray(x))
    np.testing.assert_allclose(np.asarray(rs), 4 * np.asarray(x))
    np.testing.assert_allclose(
        np.asarray(b), np.tile(np.asarray(x[2:3]), (4, 1))
    )
