"""Test fixtures (reference analog: ``python/ray/tests/conftest.py`` —
ray_start_regular :611 / ray_start_cluster :694).

JAX tests run on a virtual 8-device CPU mesh: set before any jax import.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture
def rt_start(request):
    """Start a small cluster; params: dict(num_cpus=..., num_nodes=...)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 4)
    ctx = ray_tpu.init(**kwargs)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def rt_cluster(request):
    """Multi-node cluster fixture: yields (module, LocalCluster)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 2)
    kwargs.setdefault("num_nodes", 2)
    ray_tpu.init(**kwargs)
    yield ray_tpu, ray_tpu._internal_cluster()
    ray_tpu.shutdown()
