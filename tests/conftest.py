"""Test fixtures (reference analog: ``python/ray/tests/conftest.py`` —
ray_start_regular :611 / ray_start_cluster :694).

JAX tests run on a virtual 8-device CPU mesh. NOTE: jax may be preloaded by
the interpreter with JAX_PLATFORMS pointing at real TPU hardware; env vars in
this file would be too late, but backends initialize lazily, so
jax.config.update still wins as long as no jax computation ran yet.
"""
import os

_flags = [
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running matrix tests (tier-1 runs -m 'not slow')",
    )


@pytest.hookimpl(tryfirst=True, hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Expose per-phase reports on the item so fixtures can act on test
    outcome during teardown (the chaos flight-trace dump in
    test_faultpoints.py checks ``item.rep_call.failed``)."""
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devs = jax.devices()
    assert devs[0].platform == "cpu", f"tests must run on CPU, got {devs[0]}"
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    yield


@pytest.fixture
def rt_start(request):
    """Start a small cluster; params: dict(num_cpus=..., num_nodes=...)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 4)
    ctx = ray_tpu.init(**kwargs)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def rt_cluster(request):
    """Multi-node cluster fixture: yields (module, LocalCluster)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 2)
    kwargs.setdefault("num_nodes", 2)
    ray_tpu.init(**kwargs)
    yield ray_tpu, ray_tpu._internal_cluster()
    ray_tpu.shutdown()
