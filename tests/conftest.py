"""Test fixtures (reference analog: ``python/ray/tests/conftest.py`` —
ray_start_regular :611 / ray_start_cluster :694).

JAX tests run on a virtual 8-device CPU mesh. NOTE: jax may be preloaded by
the interpreter with JAX_PLATFORMS pointing at real TPU hardware; env vars in
this file would be too late, but backends initialize lazily, so
jax.config.update still wins as long as no jax computation ran yet.
"""
import os

_flags = [
    f for f in os.environ.get("XLA_FLAGS", "").split()
    if "xla_force_host_platform_device_count" not in f
]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running matrix tests (tier-1 runs -m 'not slow')",
    )


@pytest.hookimpl(tryfirst=True, hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Expose per-phase reports on the item so fixtures can act on test
    outcome during teardown (the chaos flight-trace dump in
    test_faultpoints.py checks ``item.rep_call.failed``)."""
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devs = jax.devices()
    assert devs[0].platform == "cpu", f"tests must run on CPU, got {devs[0]}"
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    yield


@pytest.fixture
def rt_start(request):
    """Start a small cluster; params: dict(num_cpus=..., num_nodes=...)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 4)
    ctx = ray_tpu.init(**kwargs)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def chaos_flight_trace(request, tmp_path):
    """Chaos forensics: record the RPC plane during the test; on assertion
    failure dump the fault-annotated trace as flight_<test>.json into the
    tmp dir. The trace JOINS both observability planes: flight spans
    (faultpoint hits stamp their enclosing spans) AND the task-event
    tracks from the state API, so a matrix failure attributes to a verb
    *and* a task phase out of the box. Prefers a cluster-wide snapshot
    (worker rings + head task events) while the cluster is still up,
    falling back to the local ring."""
    import json as _json

    from ray_tpu._private import flight, taskpath

    flight.enable()
    yield
    rep = getattr(request.node, "rep_call", None)
    try:
        if rep is not None and rep.failed:
            snaps, events = None, []
            try:
                from ray_tpu.util import state as _state

                snaps = _state.flight_snapshot(drain=True)
                events = _state.list_tasks(limit=100_000)
            except Exception as e:
                # Cluster already torn down by the test's finally: the
                # local ring still holds the driver-side story.
                print(f"[chaos] cluster-wide snapshot unavailable ({e}); "
                      f"dumping the local ring only")
            if not snaps:
                snap = flight.drain()
                snap["offset"] = 0.0
                snaps = [snap]
            merged = sorted(
                flight.merge_snapshots(snaps)
                + taskpath.task_events_to_merged(events),
                key=lambda e: e["ts"],
            )
            trace = flight.to_chrome_trace(merged)
            path = tmp_path / f"flight_{request.node.name}.json"
            path.write_text(_json.dumps(trace))
            print(f"\n[chaos] wrote annotated flight trace "
                  f"({len(events)} task events joined) to {path}")
    finally:
        flight.disable()


@pytest.fixture
def rt_cluster(request):
    """Multi-node cluster fixture: yields (module, LocalCluster)."""
    import ray_tpu

    kwargs = getattr(request, "param", None) or {}
    kwargs.setdefault("num_cpus", 2)
    kwargs.setdefault("num_nodes", 2)
    ray_tpu.init(**kwargs)
    yield ray_tpu, ray_tpu._internal_cluster()
    ray_tpu.shutdown()
