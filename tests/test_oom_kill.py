"""Pressure-based OOM task killing (reference behavior:
``src/ray/raylet/worker_killing_policy_group_by_owner.h`` + memory
monitor): a leaky retriable task is killed mid-run when its node crosses
the memory threshold, the kill actually frees the leaked memory (the task
runs in a subprocess executor), and the owner's retry lands on a
non-pressured node added later — the fleet survives."""
import time

import pytest

import ray_tpu
from ray_tpu._private.memory_monitor import get_memory_usage
from ray_tpu._private.test_utils import wait_for_condition


def test_leaky_task_killed_and_retried_elsewhere(tmp_path):
    used, total = get_memory_usage()
    frac = used / max(total, 1)
    leak_bytes = 3 * 1024**3
    if total - used < 4 * leak_bytes:
        pytest.skip("host too full to stage a controlled leak")
    # The first (leaky) node presses once the leak lands (~+2.4% here);
    # the rescue node's threshold sits far above so it never presses.
    thr_leaky = frac + 0.5 * leak_bytes / total
    thr_rescue = min(frac + 10 * leak_bytes / total, 0.98)
    marker = str(tmp_path / "attempts")

    # Only the leaky node exists at submit time, so attempt 1 must land
    # there; the rescue node joins while the leak is in flight.
    ray_tpu.init(num_cpus=1, num_nodes=1,
                 _node_env={"RT_MEMORY_THRESHOLD": f"{thr_leaky:.5f}"})
    try:
        @ray_tpu.remote(num_cpus=1, max_retries=4, runtime_env={"pip": []})
        def leaker(marker_path, leak):
            import os as _os
            import time as _time

            import numpy as np

            with open(marker_path, "a") as f:
                f.write(f"{_os.getpid()}\n")
            attempts = sum(1 for _ in open(marker_path))
            if attempts == 1:
                # leak then linger: the watchdog must kill us mid-run
                hog = [np.ones(leak // 16, np.float64) for _ in range(2)]
                _time.sleep(60)
                return f"leaked-{len(hog)}"  # unreachable if killed
            return "ok"

        ref = leaker.remote(marker, leak_bytes)

        # Attempt 1 has started leaking on the pressured node: bring up
        # the rescue node the retry should land on.
        import os
        wait_for_condition(
            lambda: os.path.exists(marker), timeout=60,
            message="first attempt never started",
        )
        cluster = ray_tpu._internal_cluster()
        cluster.add_node(
            {"CPU": 1},
            env={"RT_MEMORY_THRESHOLD": f"{thr_rescue:.5f}"},
        )

        out = ray_tpu.get(ref, timeout=120)
        assert out == "ok", f"expected the retry to succeed, got {out!r}"
        with open(marker) as f:
            attempts = len(f.readlines())
        assert attempts >= 2, "task was never killed + retried"
    finally:
        ray_tpu.shutdown()
