"""Lint v2: Family C (asyncio/thread concurrency, RT301-RT305) and
Family D (wire/gate/catalog invariants, RT401-RT404).

Mirrors tests/test_lint.py: every rule gets a positive case (minimal
snippet that triggers it) and a negative case (the fixed form passes).
The Family-D liveness tests do exactly what the acceptance criterion
demands: delete a wire flag's receiver branch, or add an uncataloged
``faultpoints.fire`` name, on fixture source — and the scan flips red
through the real CLI.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.lint import (
    FAMILY_CONCURRENCY,
    ModuleContext,
    lint_project,
    lint_source,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_c(src):
    return lint_source(textwrap.dedent(src), "<test>",
                       families=(FAMILY_CONCURRENCY,))


def lint_d(sources, complete=False):
    mods = [ModuleContext(textwrap.dedent(s), f"<mod{i}>")
            for i, s in enumerate(sources)]
    return lint_project(mods, complete=complete)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- RT301
def test_rt301_time_sleep_in_async_def_flagged():
    findings = lint_c("""
        import time

        async def settle(self):
            time.sleep(0.1)
    """)
    assert "RT301" in rule_ids(findings)
    assert "event loop" in findings[0].message


def test_rt301_result_without_timeout_flagged():
    findings = lint_c("""
        async def fetch(self, fut):
            return fut.result()
    """)
    assert "RT301" in rule_ids(findings)


def test_rt301_queue_get_without_timeout_flagged():
    findings = lint_c("""
        async def drain(self):
            return self._queue.get()
    """)
    assert "RT301" in rule_ids(findings)


def test_rt301_awaited_and_guarded_forms_clean():
    findings = lint_c("""
        import asyncio

        async def settle(self, fut, q):
            await asyncio.sleep(0.1)      # parks the coroutine, fine
            item = await q.get()           # asyncio.Queue.get
            if fut.done():
                return fut.result()        # completed-future fast path
            return await fut, item
    """)
    assert "RT301" not in rule_ids(findings)


def test_rt301_executor_thread_allowlist():
    findings = lint_c("""
        import time

        async def offloaded(self):  # raytpu: executor-thread
            time.sleep(0.1)
    """)
    assert "RT301" not in rule_ids(findings)


def test_rt301_nested_sync_def_not_flagged():
    findings = lint_c("""
        import time

        async def submit(self, loop):
            def work():
                time.sleep(0.5)  # runs on the executor thread
            return await loop.run_in_executor(None, work)
    """)
    assert "RT301" not in rule_ids(findings)


# ---------------------------------------------------------------- RT302
def test_rt302_create_task_from_thread_flagged():
    findings = lint_c("""
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._pump, daemon=True).start()

            def _pump(self):
                self.loop.create_task(self._drain())

            async def _drain(self):
                pass
    """)
    assert "RT302" in rule_ids(findings)
    assert "thread" in findings[0].message


def test_rt302_transitive_callee_flagged():
    findings = lint_c("""
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._pump).start()

            def _pump(self):
                self._dispatch()

            def _dispatch(self):
                self.loop.call_soon(self._cb)
    """)
    assert "RT302" in rule_ids(findings)


def test_rt302_threadsafe_bridge_clean():
    findings = lint_c("""
        import asyncio
        import threading

        class Pump:
            def start(self):
                threading.Thread(target=self._pump).start()

            def _pump(self):
                asyncio.run_coroutine_threadsafe(self._drain(), self.loop)
                self.loop.call_soon_threadsafe(self._wake)

            async def _drain(self):
                pass
    """)
    assert "RT302" not in rule_ids(findings)


def test_rt302_loop_thread_code_clean():
    """create_task from a function nothing submits to a thread is fine."""
    findings = lint_c("""
        class Conn:
            def on_reply(self):
                self.loop.create_task(self._settle())  # raytpu: ignore[RT303]

            async def _settle(self):
                pass
    """)
    assert "RT302" not in rule_ids(findings)


# ---------------------------------------------------------------- RT303
def test_rt303_dropped_create_task_flagged():
    findings = lint_c("""
        class W:
            def kick(self):
                self.loop.create_task(self._flush())

            async def _flush(self):
                pass
    """)
    assert "RT303" in rule_ids(findings)
    assert "spawn_logged" in findings[0].message


def test_rt303_lambda_create_task_flagged():
    findings = lint_c("""
        class W:
            def kick(self):
                self.loop.call_soon_threadsafe(
                    lambda: self.loop.create_task(self._flush())
                )
    """)
    assert "RT303" in rule_ids(findings)


def test_rt303_stored_or_logged_clean():
    findings = lint_c("""
        from ray_tpu._private.asyncio_util import spawn_logged

        class W:
            def kick(self):
                self._t = self.loop.create_task(self._flush())
                spawn_logged(self.loop, self._flush(), "w.flush")

            async def _flush(self):
                pass
    """)
    assert "RT303" not in rule_ids(findings)


# ---------------------------------------------------------------- RT304
def test_rt304_await_under_sync_lock_flagged():
    findings = lint_c("""
        class Store:
            async def put(self, data):
                with self._lock:
                    await self._write(data)
    """)
    assert "RT304" in rule_ids(findings)
    assert "threading.Lock" in findings[0].message


def test_rt304_async_lock_clean():
    findings = lint_c("""
        class Store:
            async def put(self, data):
                async with self._lock:
                    await self._write(data)
    """)
    assert "RT304" not in rule_ids(findings)


def test_rt304_await_outside_critical_section_clean():
    findings = lint_c("""
        class Store:
            async def put(self, data):
                with self._lock:
                    self._pending.append(data)
                await self._flush()
    """)
    assert "RT304" not in rule_ids(findings)


# ---------------------------------------------------------------- RT305
def test_rt305_unlocked_shared_write_flagged():
    findings = lint_c("""
        import threading

        class Stats:
            def start(self):
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self.count += 1

            async def observe(self):
                self.count = 0
    """)
    assert "RT305" in rule_ids(findings)
    assert "count" in findings[0].message


def test_rt305_locked_side_clean():
    findings = lint_c("""
        import threading

        class Stats:
            def start(self):
                threading.Thread(target=self._tick).start()

            def _tick(self):
                with self._lock:
                    self.count += 1

            async def observe(self):
                self.count = 0
    """)
    assert "RT305" not in rule_ids(findings)


def test_rt305_single_sided_writes_clean():
    findings = lint_c("""
        import threading

        class Stats:
            def start(self):
                threading.Thread(target=self._tick).start()

            def _tick(self):
                self.count += 1

            async def observe(self):
                return self.count  # read, not write
    """)
    assert "RT305" not in rule_ids(findings)


# ---------------------------------------------------------------- RT401
_SENDER = """
    def pack(header, extra):
        header["wa"] = 1
        header["tid"] = extra
"""
_RECEIVER = """
    def consume(h):
        if h.get("wa"):
            return True
        return "wa" in h
"""


def test_rt401_symmetric_flag_clean():
    findings = lint_d([_SENDER, _RECEIVER])
    assert "RT401" not in rule_ids(findings)


def test_rt401_deleted_receiver_branch_flips_red():
    findings = lint_d([_SENDER])
    msgs = [f.message for f in findings if f.rule == "RT401"]
    assert any("'wa'" in m and "no receiver branch" in m for m in msgs)


def test_rt401_deleted_sender_flips_red():
    findings = lint_d([_RECEIVER])
    msgs = [f.message for f in findings if f.rule == "RT401"]
    assert any("'wa'" in m and "never packed" in m for m in msgs)


def test_rt401_uncataloged_short_key_flagged():
    findings = lint_d(["""
        def pack(header):
            header["zz"] = 1
    """])
    msgs = [f.message for f in findings if f.rule == "RT401"]
    assert any("'zz'" in m and "absent from lint/catalog.py" in m
               for m in msgs)


def test_rt401_base_and_payload_keys_clean():
    findings = lint_d(["""
        def pack(header, payload):
            header["tid"] = 1           # WIRE_BASE envelope key
            payload = {"submission_id": "x"}  # not a header var
            header["long_payload_key"] = payload  # >4 chars: verb field
    """])
    assert "RT401" not in rule_ids(findings)


def test_rt401_cli_liveness(tmp_path):
    """The acceptance check end-to-end: two fixture files are green
    through the real CLI; deleting the receiver file flips it red."""
    sender = tmp_path / "sender.py"
    receiver = tmp_path / "receiver.py"
    sender.write_text(textwrap.dedent(_SENDER))
    receiver.write_text(textwrap.dedent(_RECEIVER))

    def scan(paths):
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.lint", *paths,
             "--select", "RT4", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        return proc.returncode, json.loads(proc.stdout)

    rc, findings = scan([str(sender), str(receiver)])
    assert rc == 0 and findings == []
    rc, findings = scan([str(sender)])
    assert rc == 1
    assert [f["rule"] for f in findings] == ["RT401"]
    assert findings[0]["family"] == "D"


# ---------------------------------------------------------------- RT402
def test_rt402_unbranched_gate_read_flagged():
    findings = lint_d(["""
        from ray_tpu._private.config import rt_config

        def dump():
            print(rt_config.reply_batching)
    """])
    msgs = [f.message for f in findings if f.rule == "RT402"]
    assert any("reply_batching" in m and "never branched" in m
               for m in msgs)


def test_rt402_branched_and_cached_reads_clean():
    findings = lint_d(["""
        from ray_tpu._private.config import rt_config as _rtc

        class W:
            def __init__(self):
                self._reply_batching = bool(_rtc.reply_batching)
                if _rtc.push_window:
                    self._pace = True
    """])
    assert "RT402" not in rule_ids(findings)


def test_rt402_undeclared_catalog_gate_flagged_on_complete_scan():
    findings = lint_d(["""
        rt_config.declare("brand_new_gate", bool, True, "doc")
    """], complete=True)
    msgs = [f.message for f in findings if f.rule == "RT402"]
    assert any("brand_new_gate" in m and "missing from lint/catalog.py"
               in m for m in msgs)


# ---------------------------------------------------------------- RT403
def test_rt403_uncataloged_fire_site_flips_red():
    findings = lint_d(["""
        from ray_tpu._private import faultpoints

        def f():
            faultpoints.fire("rogue.new.point")
    """])
    msgs = [f.message for f in findings if f.rule == "RT403"]
    assert any("rogue.new.point" in m for m in msgs)


def test_rt403_cataloged_and_dynamic_fires_clean():
    findings = lint_d(["""
        from ray_tpu._private import faultpoints

        async def f(method):
            faultpoints.fire("worker.pull")
            await faultpoints.async_fire(f"gcs.dispatch.{method}")
            faultpoints.fire("gcs.dispatch.lease")
    """])
    assert "RT403" not in rule_ids(findings)


def test_rt403_cli_liveness(tmp_path):
    """Acceptance check: an uncataloged fire name on fixture source
    flips the scan red through the real CLI."""
    mod = tmp_path / "firing.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu._private import faultpoints

        def f():
            faultpoints.fire("worker.pull")
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", str(mod),
         "--select", "RT4", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout
    mod.write_text(mod.read_text().replace(
        '"worker.pull"', '"worker.not.in.catalog"'))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", str(mod),
         "--select", "RT4", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert [f["rule"] for f in findings] == ["RT403"]


# ---------------------------------------------------------------- RT404
def test_rt404_unknown_stage_flagged():
    findings = lint_d(["""
        from ray_tpu._private import taskpath

        def f(tid):
            taskpath.record_phase("bogus_stage", tid, 0.0, 1.0)
    """])
    msgs = [f.message for f in findings if f.rule == "RT404"]
    assert any("bogus_stage" in m for m in msgs)


def test_rt404_unknown_phase_label_flagged():
    findings = lint_d(["""
        from ray_tpu._private import taskpath

        def f(tid):
            taskpath.record_phase("exec", tid, 0.0, 1.0,
                                  phase="bogus-phase")
    """])
    msgs = [f.message for f in findings if f.rule == "RT404"]
    assert any("bogus-phase" in m for m in msgs)


def test_rt404_known_stage_and_phase_clean():
    findings = lint_d(["""
        from ray_tpu._private import taskpath, flight

        def f(tid):
            taskpath.record_phase("exec", tid, 0.0, 1.0, phase="exec")
            flight.record("task.serve", tid, "task", 0.0, 1.0)
    """])
    assert "RT404" not in rule_ids(findings)


# ------------------------------------------------------- catalog / regen
def test_catalog_regen_is_noop_on_clean_tree():
    from ray_tpu.lint import catalog_gen

    assert catalog_gen.regen(root=REPO, write=False) is False


def test_catalog_generate_deterministic():
    from ray_tpu.lint import catalog_gen

    assert catalog_gen.generate(REPO) == catalog_gen.generate(REPO)


def test_catalog_regen_cli_reports_up_to_date():
    from ray_tpu.lint import catalog_gen

    before = open(catalog_gen.catalog_path()).read()
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", "--regen"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    assert "up to date" in proc.stdout
    assert open(catalog_gen.catalog_path()).read() == before


def test_catalog_faultpoints_all_matrixed_or_waived():
    """The RT403 contract, asserted directly on the catalog: every
    pinned faultpoint either has a chaos-matrix row or a reason."""
    from ray_tpu.lint import catalog

    bad = [name for name, e in catalog.FAULTPOINTS.items()
           if not e.get("matrixed") and not e.get("waive")]
    assert bad == []


def test_catalog_matrixed_matches_chaos_specs():
    """The catalog's ``matrixed`` bits and the live CHAOS_SPECS list
    cannot drift: regen derives one from the other, and this pins it."""
    from ray_tpu.lint import catalog, catalog_gen

    matrixed = set(catalog_gen.scan_matrixed(REPO))
    for name, e in catalog.FAULTPOINTS.items():
        assert e["matrixed"] == (name in matrixed), name


def test_catalog_phases_match_taskpath():
    from ray_tpu._private import taskpath
    from ray_tpu.lint import catalog

    assert tuple(catalog.PHASES) == tuple(taskpath.PHASES)


# -------------------------------------------------- spawn_logged satellite
def test_spawn_logged_logs_background_failure(caplog):
    import asyncio
    import logging

    async def boom():
        raise RuntimeError("kapow")

    from ray_tpu._private.asyncio_util import spawn_logged

    async def main():
        t = spawn_logged(None, boom(), "test.boom")
        with pytest.raises(RuntimeError):
            await t

    with caplog.at_level(logging.ERROR, "ray_tpu._private.asyncio_util"):
        asyncio.run(main())
    assert any("test.boom" in r.message and "kapow" in r.message
               for r in caplog.records)


def test_spawn_logged_quiet_on_success_and_cancel(caplog):
    import asyncio
    import logging

    from ray_tpu._private.asyncio_util import spawn_logged

    async def ok():
        return 42

    async def forever():
        await asyncio.Event().wait()

    async def main():
        t1 = spawn_logged(None, ok(), "test.ok")
        t2 = spawn_logged(asyncio.get_running_loop(), forever(),
                         "test.cancel")
        assert await t1 == 42
        t2.cancel()
        with pytest.raises(asyncio.CancelledError):
            await t2

    with caplog.at_level(logging.ERROR, "ray_tpu._private.asyncio_util"):
        asyncio.run(main())
    assert caplog.records == []


# ------------------------------------------------------------ CLI surface
def test_list_rules_grouped_by_family():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0
    out = proc.stdout
    for header in ("Family A", "Family B", "Family C", "Family D"):
        assert header in out
    # Family D rules must print under the Family D header.
    assert out.index("RT301") < out.index("Family D") < out.index("RT401")


def test_json_findings_carry_family(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import time

        async def f():
            time.sleep(1.0)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", str(bad), "--framework",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert [f["rule"] for f in findings] == ["RT301"]
    assert findings[0]["family"] == "C"
