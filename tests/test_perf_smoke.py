"""Tier-1 hot-path regression guard.

Runs the ref-path microbenches at tiny k with GENEROUS wall-clock bounds:
this is not a performance measurement (CI machines are noisy), it is a
tripwire for accidental O(refs)-RPC or per-ref-future regressions, which
show up as order-of-magnitude slowdowns, not percentages. A healthy build
finishes each leg ~100x inside the bound."""
import time

import pytest

from ray_tpu._private import perf

# Each leg at these sizes takes well under a second on a healthy build;
# an O(refs) RPC regression puts the wait leg alone into minutes.
WALL_BOUND_S = 30.0


def test_wait_refs_smoke(rt_start):
    t0 = time.perf_counter()
    rate = perf.bench_wait_1k_refs(k=100)
    assert time.perf_counter() - t0 < WALL_BOUND_S
    assert rate > 0


def test_get_nested_refs_smoke(rt_start):
    t0 = time.perf_counter()
    rate = perf.bench_get_10k_refs(k=500)
    assert time.perf_counter() - t0 < WALL_BOUND_S
    assert rate > 0


def test_get_actor_refs_smoke(rt_start):
    t0 = time.perf_counter()
    rate = perf.bench_get_actor_refs(k=100)
    assert time.perf_counter() - t0 < WALL_BOUND_S
    assert rate > 0
