"""Framework train backends beyond torch-gloo/JAX: TensorFlow multi-worker,
gated torch-xla, gated XGBoost/LightGBM, gated Lightning glue.

Reference analog: ``python/ray/train/tensorflow|torch/xla|xgboost|
lightgbm|lightning`` — the backend-config matrix of the reference's train
layer. TF runs for real (it is in the image); the others assert the
import gates raise actionable errors instead of hanging in workers.
"""
import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def rt_cluster():
    ray_tpu.init(num_cpus=2, num_nodes=2)
    yield
    ray_tpu.shutdown()


def test_collective_allgather(rt_cluster):
    """allgather returns every rank's payload rank-ordered on all ranks."""
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.trainer import DataParallelTrainer

    def loop(config):
        from ray_tpu.train.collective import allgather
        from ray_tpu.train.context import get_context, report

        ctx = get_context()
        vals = allgather(f"r{ctx.get_world_rank()}")
        if ctx.get_world_rank() == 0:
            report({"gathered": vals})

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, placement_strategy="SPREAD"
        ),
    ).fit()
    assert result.metrics["gathered"] == ["r0", "r1"]


def test_tensorflow_trainer_multiworker(rt_cluster):
    """TF_CONFIG forms a 2-worker cluster; MultiWorkerMirroredStrategy sees
    both replicas and an allreduce agrees across workers (reference:
    train/tensorflow/config.py _setup_tensorflow_environment)."""
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.tensorflow import TensorflowTrainer

    def loop(config):
        import json
        import os

        import tensorflow as tf

        from ray_tpu.train.context import get_context, report

        ctx = get_context()
        tf_config = json.loads(os.environ["TF_CONFIG"])
        assert len(tf_config["cluster"]["worker"]) == 2
        assert tf_config["task"]["index"] == ctx.get_world_rank()
        strategy = tf.distribute.MultiWorkerMirroredStrategy()
        assert strategy.num_replicas_in_sync == 2
        # cross-worker allreduce: each worker contributes (rank + 1);
        # MEAN over replicas = 1.5 on both workers
        per_replica = strategy.run(
            lambda: tf.constant(float(ctx.get_world_rank() + 1))
        )
        total = strategy.reduce(
            tf.distribute.ReduceOp.MEAN, per_replica, axis=None
        )
        report({"mean": float(total), "rank": ctx.get_world_rank()})

    result = TensorflowTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, placement_strategy="SPREAD"
        ),
    ).fit()
    assert abs(result.metrics["mean"] - 1.5) < 1e-6


def test_torch_xla_gated():
    """Without torch_xla installed, the worker wrapper raises an
    actionable ImportError naming JaxTrainer (it must never hang)."""
    from ray_tpu.train.torch.xla import TorchXLAConfig, _xla_wrapped

    with pytest.raises(ImportError, match="JaxTrainer"):
        _xla_wrapped(lambda c: None, TorchXLAConfig())({})


def test_gbdt_trainers_gated():
    from ray_tpu.train.gbdt import LightGBMTrainer, XGBoostTrainer

    with pytest.raises(ImportError, match="runtime_env"):
        XGBoostTrainer(params={}, label_column="y")
    with pytest.raises(ImportError, match="runtime_env"):
        LightGBMTrainer(params={}, label_column="y")


def test_lightning_gated():
    from ray_tpu.train import lightning

    with pytest.raises(ImportError, match="pytorch_lightning"):
        lightning.RayDDPStrategy()
    with pytest.raises(ImportError, match="pytorch_lightning"):
        lightning.prepare_trainer(object())
