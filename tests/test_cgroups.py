"""cgroup resource isolation for worker processes.

Reference analog: ``src/ray/common/cgroup2/`` (cgroup_manager +
sysfs_cgroup_driver tests) — worker processes land in a dedicated cgroup
with cpu/memory limits when isolation is enabled; unavailable kernels
degrade to disabled, never to an error.
"""
import os

import pytest

from ray_tpu._private.cgroups import CgroupDriver, enabled


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("RT_CGROUP_ISOLATION", raising=False)
    assert not enabled()
    monkeypatch.setenv("RT_CGROUP_ISOLATION", "1")
    assert enabled()


def test_driver_detection_never_raises():
    d = CgroupDriver()
    assert d.mode in ("v1", "v2", None)
    # create on an unavailable driver is a clean no-op
    if not d.available:
        assert d.create("x", cpu_shares=1.0) is None


@pytest.mark.skipif(
    not CgroupDriver().available, reason="no writable cgroup hierarchy"
)
def test_cgroup_create_limit_add_pid_remove():
    d = CgroupDriver(base_name="rt_test")
    handle = d.create(
        "unit", cpu_shares=2.0, memory_limit_bytes=512 * 1024 * 1024
    )
    assert handle, "writable hierarchy advertised but create failed"
    try:
        # the memory limit landed in SOME hierarchy (v2 memory.max or v1
        # memory.limit_in_bytes) — create() now guarantees requested
        # limits applied or returns None, so exactly one must verify
        verified = 0
        for path in handle:
            for fname in ("memory.max", "memory.limit_in_bytes"):
                limit_file = os.path.join(path, fname)
                if os.path.exists(limit_file):
                    with open(limit_file) as f:
                        val = f.read().strip()
                    if val != "max":
                        assert int(val) <= 512 * 1024 * 1024 * 2
                        verified += 1
        assert verified >= 1, f"no memory limit verified in {handle}"
        # a live pid can be moved in and shows membership
        import subprocess
        import sys

        proc = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(30)"])
        try:
            assert d.add_pid(handle, proc.pid)
            cg = "\n".join(CgroupDriver.pid_cgroups(proc.pid))
            assert "rt_test_unit" in cg, cg
        finally:
            proc.kill()
            proc.wait()
    finally:
        d.remove(handle)


@pytest.mark.skipif(
    not CgroupDriver().available, reason="no writable cgroup hierarchy"
)
def test_spawned_node_lands_in_cgroup(monkeypatch):
    """RT_CGROUP_ISOLATION=1: a spawned node process is a member of its
    own ray_tpu_<node> cgroup; shutdown removes the group."""
    monkeypatch.setenv("RT_CGROUP_ISOLATION", "1")
    import ray_tpu

    ray_tpu.init(num_cpus=1, num_nodes=1)
    try:
        cluster = ray_tpu._internal_cluster()
        handle = cluster.nodes[0]
        assert handle.cgroup, "node spawned without a cgroup"
        cg = "\n".join(CgroupDriver.pid_cgroups(handle.proc.pid))
        assert f"ray_tpu_{handle.node_id[:12]}" in cg, cg

        @ray_tpu.remote
        def f():
            return 1

        assert ray_tpu.get(f.remote()) == 1  # still schedules normally
        paths = list(handle.cgroup)
    finally:
        ray_tpu.shutdown()
    for p in paths:
        assert not os.path.exists(p), f"cgroup {p} leaked after shutdown"
