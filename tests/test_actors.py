"""Actor API tests (reference analog: python/ray/tests/test_actor*.py)."""
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, ActorError, TaskError


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def inc(self, k=1):
        self.n += k
        return self.n

    def read(self):
        return self.n

    def fail(self):
        raise RuntimeError("actor method failed")

    def die(self):
        import os
        os._exit(1)

    def leave(self):
        ray_tpu.exit_actor()


def test_actor_basic(rt_start):
    c = Counter.remote(10)
    assert ray_tpu.get(c.inc.remote()) == 11
    assert ray_tpu.get(c.inc.remote(5)) == 16
    assert ray_tpu.get(c.read.remote()) == 16


def test_actor_ordering(rt_start):
    c = Counter.remote()
    refs = [c.inc.remote() for _ in range(50)]
    results = ray_tpu.get(refs)
    assert results == list(range(1, 51))


def test_actor_method_error(rt_start):
    c = Counter.remote()
    with pytest.raises(TaskError, match="actor method failed"):
        ray_tpu.get(c.fail.remote())
    # actor still alive after method error
    assert ray_tpu.get(c.read.remote()) == 0


def test_actor_init_error(rt_start):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("bad init")

        def ping(self):
            return None

    # Round-10 deferred batched creation (rt_config.actor_create_batch):
    # .remote() returns the handle immediately; the __init__ error
    # surfaces on the handle's first use (reference semantics — actor
    # creation is asynchronous).
    h = Bad.remote()
    with pytest.raises(Exception, match="bad init"):
        ray_tpu.get(h.ping.remote())


def test_named_actor(rt_start):
    Counter.options(name="global_counter").remote(5)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.read.remote()) == 5


def test_named_actor_get_if_exists(rt_start):
    a = Counter.options(name="shared", get_if_exists=True).remote(1)
    b = Counter.options(name="shared", get_if_exists=True).remote(99)
    ray_tpu.get(a.inc.remote())
    assert ray_tpu.get(b.read.remote()) == 2  # same actor


def test_actor_handle_passing(rt_start):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle, k):
        return ray_tpu.get(handle.inc.remote(k))

    assert ray_tpu.get(bump.remote(c, 7)) == 7
    assert ray_tpu.get(c.read.remote()) == 7


def test_kill_actor(rt_start):
    c = Counter.remote()
    assert ray_tpu.get(c.read.remote()) == 0
    ray_tpu.kill(c)
    with pytest.raises(ActorError):
        ray_tpu.get(c.read.remote())


def test_exit_actor(rt_start):
    c = Counter.remote()
    ref = c.leave.remote()
    with pytest.raises(ActorError):
        ray_tpu.get(ref)
    with pytest.raises(ActorError):
        ray_tpu.get(c.read.remote())


def test_async_actor(rt_start):
    @ray_tpu.remote
    class AsyncWorkerActor:
        async def work(self, x):
            import asyncio
            await asyncio.sleep(0.01)
            return x * 2

    a = AsyncWorkerActor.options(max_concurrency=8).remote()
    refs = [a.work.remote(i) for i in range(16)]
    assert ray_tpu.get(refs) == [i * 2 for i in range(16)]


def test_max_concurrency_threads(rt_start):
    @ray_tpu.remote
    class Slow:
        def work(self):
            time.sleep(0.3)
            return 1

    a = Slow.options(max_concurrency=4).remote()
    t0 = time.time()
    ray_tpu.get([a.work.remote() for _ in range(4)])
    elapsed = time.time() - t0
    assert elapsed < 1.0, f"4 concurrent calls took {elapsed:.2f}s (not concurrent)"


def test_actor_with_ref_args(rt_start):
    """Regression: ObjectRef passed to an actor constructor must materialize."""
    ref = ray_tpu.put(41)

    @ray_tpu.remote
    class Holder:
        def __init__(self, v):
            self.v = v + 1

        def get(self):
            return self.v

    h = Holder.remote(ref)
    assert ray_tpu.get(h.get.remote()) == 42


def test_actor_bad_method_does_not_wedge(rt_start):
    """Regression: a failed call must not block later calls from same caller."""
    c = Counter.remote()
    bad = c.no_such_method.remote()
    good = c.inc.remote()
    with pytest.raises(Exception, match="no method"):
        ray_tpu.get(bad)
    assert ray_tpu.get(good, timeout=10) == 1


def test_async_actor_blocking_get(rt_start):
    """Regression: blocking ray_tpu.get inside an async method must not
    deadlock the worker's core loop."""

    @ray_tpu.remote
    def produce():
        return 7

    @ray_tpu.remote
    class AsyncGetter:
        async def fetch(self):
            return ray_tpu.get(produce.remote()) + 1

    a = AsyncGetter.remote()
    assert ray_tpu.get(a.fetch.remote(), timeout=30) == 8


def test_owner_disconnect_kills_actor_detached_survives(rt_start):
    """Non-detached actors die when their owner driver disconnects; a
    lifetime="detached" actor survives it (reference: GcsActorManager
    destroys non-detached actors on owner death, gcs_actor_manager.cc)."""
    import subprocess
    import sys

    from ray_tpu._private.worker import get_global_worker

    addr = "%s:%d" % get_global_worker().gcs_addr
    script = f"""
import ray_tpu
ray_tpu.init(address="{addr}")

@ray_tpu.remote
class P:
    def ping(self):
        return "ok"

a = P.options(name="goner").remote()
b = P.options(name="keeper", lifetime="detached").remote()
assert ray_tpu.get(a.ping.remote(), timeout=30) == "ok"
assert ray_tpu.get(b.ping.remote(), timeout=30) == "ok"
import os; os._exit(0)  # hard exit: no clean shutdown, conn just drops
"""
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=120,
    )
    assert r.returncode == 0, r.stderr

    # detached actor is still reachable by name from this (other) driver
    keeper = ray_tpu.get_actor("keeper")
    assert ray_tpu.get(keeper.ping.remote(), timeout=30) == "ok"

    # non-detached actor was destroyed when its owner's connection dropped
    deadline = time.time() + 30
    gone = False
    while time.time() < deadline:
        try:
            g = ray_tpu.get_actor("goner")
            ray_tpu.get(g.ping.remote(), timeout=5)
        except Exception:
            gone = True
            break
        time.sleep(0.2)
    assert gone, "non-detached actor survived owner disconnect"
    ray_tpu.kill(keeper)


def test_concurrency_group_isolation(rt_start):
    """A slow call in one group must not block another group's calls
    (reference: concurrency_group_manager.h — per-group executors; the
    canonical use is Serve isolating health checks from work lanes)."""
    import time as _t

    @ray_tpu.remote(concurrency_groups={"io": 2})
    class A:
        @ray_tpu.method(concurrency_group="io")
        def ping(self):
            return "pong"

        def slow(self):
            _t.sleep(3)
            return "done"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    slow_ref = a.slow.remote()  # occupies the DEFAULT group's single slot
    t0 = _t.perf_counter()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"
    assert _t.perf_counter() - t0 < 2.0, "io ping blocked behind slow call"
    assert ray_tpu.get(slow_ref, timeout=20) == "done"
    ray_tpu.kill(a)


def test_concurrency_group_call_override(rt_start):
    """Per-call .options(concurrency_group=...) beats the method default."""
    import time as _t

    @ray_tpu.remote(concurrency_groups={"fast": 1})
    class A:
        def work(self, n):
            _t.sleep(n)
            return n

    a = A.remote()
    blocker = a.work.remote(3)  # default group busy
    t0 = _t.perf_counter()
    out = ray_tpu.get(
        a.work.options(concurrency_group="fast").remote(0), timeout=10
    )
    assert out == 0
    assert _t.perf_counter() - t0 < 2.0
    assert ray_tpu.get(blocker, timeout=20) == 3

    # Chained .options() preserve earlier overrides symmetrically: setting
    # num_returns later must not silently drop the group override.
    m = a.work.options(concurrency_group="fast").options(num_returns=1)
    assert m._concurrency_group == "fast"
    assert m._num_returns == 1
    ray_tpu.kill(a)


def test_concurrency_group_limit_enforced(rt_start):
    """Within one group, max_concurrency bounds parallelism."""
    import time as _t

    @ray_tpu.remote(concurrency_groups={"g": 2})
    class A:
        @ray_tpu.method(concurrency_group="g")
        def work(self):
            _t.sleep(0.5)
            return 1

    a = A.remote()
    t0 = _t.perf_counter()
    assert sum(ray_tpu.get([a.work.remote() for _ in range(4)])) == 4
    dt = _t.perf_counter() - t0
    # 4 calls, 2-wide group: ~2 batches of 0.5s (not 4 serial, not 1 batch)
    assert dt >= 0.9, f"group limit not enforced ({dt:.2f}s)"
    ray_tpu.kill(a)


def test_concurrency_group_unknown_name_errors(rt_start):
    @ray_tpu.remote(concurrency_groups={"io": 1})
    class A:
        def m(self):
            return 1

    a = A.remote()
    with pytest.raises(Exception, match="unknown concurrency group"):
        ray_tpu.get(
            a.m.options(concurrency_group="nope").remote(), timeout=10
        )
    ray_tpu.kill(a)


def test_concurrency_groups_async_actor(rt_start):
    """Async actors: per-group semaphores isolate coroutine methods too."""
    import time as _t

    @ray_tpu.remote(concurrency_groups={"io": 1})
    class A:
        async def slow(self):
            import asyncio

            await asyncio.sleep(3)
            return "done"

        @ray_tpu.method(concurrency_group="io")
        async def ping(self):
            return "pong"

    a = A.remote()
    slow_ref = a.slow.remote()
    t0 = _t.perf_counter()
    assert ray_tpu.get(a.ping.remote(), timeout=10) == "pong"
    assert _t.perf_counter() - t0 < 2.0
    assert ray_tpu.get(slow_ref, timeout=20) == "done"
    ray_tpu.kill(a)


def test_method_num_returns_declared(rt_start):
    @ray_tpu.remote
    class A:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return 1, 2

    a = A.remote()
    r1, r2 = a.pair.remote()
    assert ray_tpu.get([r1, r2]) == [1, 2]
    ray_tpu.kill(a)


@pytest.mark.parametrize("rt_start", [{"num_cpus": 2}], indirect=True)
def test_killed_client_leases_released(rt_start):
    """A client SIGKILLed while holding cached idle leases must have them
    returned on disconnect — otherwise the head's capacity view leaks and
    later actors are unschedulable (reference: raylet returns a dead
    worker's leased resources, cluster_lease_manager.cc; observed as the
    n_n bench leg dying with 'unschedulable: insufficient resources')."""
    import subprocess
    import sys
    import time as _t

    from ray_tpu._private.worker import get_global_worker

    addr = "%s:%d" % get_global_worker().gcs_addr
    script = f"""
import sys, time
import ray_tpu
ray_tpu.init(address="{addr}")

@ray_tpu.remote
def noop():
    return None

# burst of tasks: finished, but their leases stay CACHED client-side
ray_tpu.get([noop.remote() for _ in range(20)])
print("READY", flush=True)
time.sleep(300)
"""
    p = subprocess.Popen(
        [sys.executable, "-c", script], stdout=subprocess.PIPE, text=True,
    )
    try:
        assert p.stdout.readline().strip() == "READY"
    finally:
        p.kill()
        p.wait(timeout=30)

    # Both CPUs must come back: two 1-CPU actors get placed promptly.
    @ray_tpu.remote
    class A:
        def ping(self):
            return "ok"

    t0 = _t.perf_counter()
    actors = [A.remote() for _ in range(2)]
    assert ray_tpu.get(
        [a.ping.remote() for a in actors], timeout=25
    ) == ["ok", "ok"]
    assert _t.perf_counter() - t0 < 25
    for a in actors:
        ray_tpu.kill(a)


def test_method_num_returns_via_options_and_inheritance(rt_start):
    """options() must not reset a declared num_returns; @method tags on
    base classes are honored through the MRO."""

    class Base:
        @ray_tpu.method(num_returns=2)
        def pair(self):
            return 1, 2

    @ray_tpu.remote(concurrency_groups={"g": 1})
    class Sub(Base):
        pass

    a = Sub.remote()
    r1, r2 = a.pair.remote()
    assert ray_tpu.get([r1, r2]) == [1, 2]
    q1, q2 = a.pair.options(concurrency_group="g").remote()
    assert ray_tpu.get([q1, q2]) == [1, 2]
    ray_tpu.kill(a)
