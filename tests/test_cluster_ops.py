"""Cluster ops: state API, job submission, CLI, dashboard, autoscaler.

Reference analogs: ``python/ray/tests/test_state_api*``, job manager tests
under ``dashboard/modules/job/tests``, ``autoscaler/v2/tests``.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state


# ------------------------------------------------------------- state API


@pytest.fixture
def ops_cluster():
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def test_state_listings(ops_cluster):
    @ray_tpu.remote
    def f(x):
        return x * 2

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote()) == "pong"
    assert ray_tpu.get([f.remote(i) for i in range(4)]) == [0, 2, 4, 6]

    nodes = state.list_nodes()
    assert len(nodes) >= 1 and all("resources" in n for n in nodes)
    actors = state.list_actors()
    assert any(x["state"] == "ALIVE" for x in actors)
    alive_only = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert all(x["state"] == "ALIVE" for x in alive_only)
    status = state.cluster_status()
    assert status["nodes_alive"] >= 1
    assert status["resources_total"].get("CPU", 0) >= 2


def test_task_summary(ops_cluster):
    @ray_tpu.remote
    def tracked():
        return 1

    ray_tpu.get([tracked.remote() for _ in range(3)])
    time.sleep(0.5)  # task events flush asynchronously
    summary = state.summarize_tasks()
    assert summary["cluster"]["total_tasks"] >= 1


# ----------------------------------------------------- standalone head ops


@pytest.fixture(scope="module")
def standalone_head(tmp_path_factory):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Self-sufficient auth: clients in this module authenticate with the
    # same token as the head regardless of test-file ordering (stdout info
    # is redacted, so the env is the distribution channel here).
    tok = env.get("RT_AUTH_TOKEN") or "standalone-head-test-token"
    env["RT_AUTH_TOKEN"] = tok
    prev = os.environ.get("RT_AUTH_TOKEN")
    os.environ["RT_AUTH_TOKEN"] = tok
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.head_main",
         "--num-cpus", "2", "--dashboard-port", "0"],
        stdout=subprocess.PIPE, text=True, env=env, cwd="/root/repo",
    )
    line = proc.stdout.readline().strip()
    info = json.loads(line)
    yield info
    if prev is None:
        os.environ.pop("RT_AUTH_TOKEN", None)
    else:
        os.environ["RT_AUTH_TOKEN"] = prev
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5)


def test_job_submission_end_to_end(standalone_head, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    script = tmp_path / "job.py"
    script.write_text(
        "import ray_tpu\n"
        "ray_tpu.init()\n"  # RAY_TPU_ADDRESS is set by the job manager
        "@ray_tpu.remote\n"
        "def f():\n"
        "    return 42\n"
        "print('job result:', ray_tpu.get(f.remote()))\n"
        "ray_tpu.shutdown()\n"
    )
    client = JobSubmissionClient(standalone_head["address"])
    sub_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    status = client.wait_until_status(sub_id, timeout=120)
    logs = client.get_job_logs(sub_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "job result: 42" in logs
    jobs = client.list_jobs()
    assert any(j.get("submission_id") == sub_id for j in jobs)


def test_job_stop(standalone_head):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(standalone_head["address"])
    sub_id = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'"
    )
    time.sleep(0.5)
    assert client.stop_job(sub_id)
    status = client.wait_until_status(sub_id, timeout=30)
    assert status == JobStatus.STOPPED


def test_dashboard_endpoints(standalone_head):
    port = standalone_head["dashboard_port"]
    base = f"http://127.0.0.1:{port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return json.loads(r.read())

    assert "ray_tpu" in get("/api/version")
    # the fixture's colocated node registers asynchronously: poll briefly
    deadline = time.time() + 15
    nodes = []
    while time.time() < deadline:
        nodes = get("/api/nodes")["nodes"]
        if nodes:
            break
        time.sleep(0.2)
    assert len(nodes) >= 1
    status = get("/api/cluster_status")
    assert "pending" in status and "nodes" in status
    evs = get("/api/events?source_type=NODE")["events"]
    assert evs and evs[0]["event_type"] == "NODE_ALIVE"
    # REST job submit + status + logs
    req = urllib.request.Request(
        base + "/api/jobs",
        data=json.dumps({
            "entrypoint": f"{sys.executable} -c 'print(7*6)'"
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        sub_id = json.loads(r.read())["submission_id"]
    deadline = time.time() + 60
    while time.time() < deadline:
        job = get(f"/api/jobs/{sub_id}")
        if job["status"] != "RUNNING":
            break
        time.sleep(0.2)
    assert job["status"] == "SUCCEEDED"
    assert "42" in get(f"/api/jobs/{sub_id}/logs")["logs"]


def test_cli_status_and_summary(standalone_head, capsys):
    from ray_tpu import cli

    cli.main(["status", "--address", standalone_head["address"]])
    out = capsys.readouterr().out
    parsed = json.loads(out)
    assert parsed["nodes_alive"] >= 1
    cli.main(["summary", "nodes", "--address", standalone_head["address"]])
    out = capsys.readouterr().out
    assert json.loads(out)["nodes"] >= 1


def test_cli_job_submit_wait(standalone_head, capsys):
    from ray_tpu import cli

    with pytest.raises(SystemExit) as e:
        cli.main([
            "job", "submit", "--address", standalone_head["address"],
            "--wait", "--",
            sys.executable, "-c", "print('cli job ok')",
        ])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "cli job ok" in out


# ------------------------------------------------------------- autoscaler


def test_autoscaler_bin_packs_mixed_demand(monkeypatch):
    """Mixed demand shapes pack into the fewest nodes (reference:
    v2/scheduler.py try_schedule): launched nodes' leftover capacity
    absorbs later demands, and first-fit-decreasing places big bundles
    before small ones — no node-per-demand overprovisioning."""
    from ray_tpu._private import sync_client as sc_mod
    from ray_tpu.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        NodeTypeConfig,
    )

    class FakeClient:
        def __init__(self, *_a, **_k):
            pass

        def call(self, method, _h):
            assert method == "cluster_load"
            return {
                "nodes": [],
                # small demands FIRST: the unsorted order would place them
                # before the big bundle (worst case for first-fit)
                "pending": [{"resources": {"CPU": 1.0}, "count": 4}],
                "pending_pgs": [{"bundles": [{"CPU": 4.0}]}],
            }, []

        def close(self):
            pass

    class FakeProvider:
        def __init__(self):
            self.created = []

        def create_node(self, tname, resources, labels):
            self.created.append(tname)

        def non_terminated_nodes(self):
            return []

        def terminate_node(self, _):
            pass

    monkeypatch.setattr(sc_mod, "SyncHeadClient", FakeClient)
    provider = FakeProvider()
    config = AutoscalerConfig(
        node_types={
            "cpu8": NodeTypeConfig(resources={"CPU": 8.0}, max_workers=10),
        },
    )
    scaler = Autoscaler("x:1", config, provider)
    report = scaler.update()
    # 4x1 CPU + 1x4 CPU = 8 CPUs: exactly ONE cpu8 node, not one per demand.
    assert report["launched"] == {"cpu8": 1}, report
    assert provider.created == ["cpu8"]


def test_autoscaler_scales_up_and_down():
    from ray_tpu.autoscaler import (
        Autoscaler,
        AutoscalerConfig,
        LocalNodeProvider,
        NodeTypeConfig,
    )

    ray_tpu.init(num_cpus=1)
    try:
        from ray_tpu._private.worker import get_global_worker
        w = get_global_worker()
        address = f"{w.gcs_addr[0]}:{w.gcs_addr[1]}"
        config = AutoscalerConfig(
            node_types={
                "cpu4": NodeTypeConfig(resources={"CPU": 4.0}, max_workers=2),
            },
            idle_timeout_s=1.0,
        )
        provider = LocalNodeProvider(address)
        scaler = Autoscaler(address, config, provider)

        @ray_tpu.remote(num_cpus=4)
        def big():
            return "scaled"

        ref = big.remote()  # cannot fit on the 1-CPU node -> pending demand
        result_box = {}

        def getter():
            result_box["v"] = ray_tpu.get(ref, timeout=90)

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(1.0)  # let the lease wait register as pending demand
        report = scaler.update()
        assert report["launched"].get("cpu4") == 1
        t.join(timeout=90)
        assert result_box.get("v") == "scaled"

        # idle scale-down after the timeout
        deadline = time.time() + 30
        terminated = []
        while time.time() < deadline and not terminated:
            time.sleep(0.5)
            terminated = scaler.update()["terminated"]
        assert terminated, "idle node was not scaled down"
        scaler.close()
    finally:
        ray_tpu.shutdown()


def test_head_state_survives_restart(tmp_path, monkeypatch):
    """Durable head state (KV, job records) persists across a head restart
    (reference: GCS fault tolerance via Redis-backed store + init replay)."""
    state_file = str(tmp_path / "head_state.bin")
    # fixed token: standalone runs have no ambient cluster token, and the
    # redacted stdout info cannot carry one to this client
    monkeypatch.setenv("RT_AUTH_TOKEN", "statetest" * 3)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"

    def start_head():
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.head_main",
             "--num-cpus", "1", "--state-file", state_file,
             "--state-save-interval", "0.5"],
            stdout=subprocess.PIPE, text=True, env=env, cwd="/root/repo",
        )
        return proc, json.loads(proc.stdout.readline().strip())

    proc, info = start_head()
    try:
        from ray_tpu._private.sync_client import SyncHeadClient

        client = SyncHeadClient(info["address"])
        client.call("kv_put", {"ns": "user", "key": "alpha"})
        # kv_put stores frames; use the framed call path
        from ray_tpu.job_submission import JobSubmissionClient

        jc = JobSubmissionClient(info["address"])
        sub_id = jc.submit_job(
            entrypoint=f"{sys.executable} -c 'print(\"persist me\")'"
        )
        jc.wait_until_status(sub_id, timeout=60)
        time.sleep(1.0)  # let the persist loop flush
        client.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    proc, info = start_head()
    try:
        from ray_tpu.job_submission import JobSubmissionClient

        jc = JobSubmissionClient(info["address"])
        jobs = jc.list_jobs()
        assert any(j.get("submission_id") == sub_id for j in jobs)
        assert jc.get_job_status(sub_id).value in ("SUCCEEDED", "FAILED")
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_gce_tpu_node_provider_fake_gcloud():
    """GCE TPU-VM provider drives gcloud through an injected runner
    (reference: the GCP provider + tpu_command_runner.py); slices are the
    atomic scaling unit and new VMs join the head via startup script."""
    from ray_tpu.autoscaler import GCETPUNodeProvider

    calls, vms = [], {}

    def fake_gcloud(args):
        calls.append(args)
        cmd = args[4]
        if cmd == "create":
            vms[args[5]] = {
                "name": f"projects/p/z/nodes/{args[5]}", "state": "READY",
            }
            return ""
        if cmd == "delete":
            vms.pop(args[5], None)
            return ""
        assert cmd == "list"
        return json.dumps(list(vms.values()))

    p = GCETPUNodeProvider(
        "10.0.0.2:6379", project="proj", zone="us-central2-b",
        node_types={"v5e-16": {"accelerator_type": "v5litepod-16"}},
        runner=fake_gcloud,
    )
    pid = p.create_node("v5e-16", {"TPU": 16.0})
    create = calls[0]
    assert "v5litepod-16" in create
    assert any(
        "ray_tpu.cli start --address 10.0.0.2:6379" in a for a in create
    )
    assert len(p.non_terminated_nodes()) == 1
    vms.clear()  # VM deleted out-of-band: drops from the provider view
    assert p.non_terminated_nodes() == []
    pid2 = p.create_node("v5e-16", {"TPU": 16.0})
    p.terminate_node(pid2)
    assert p.non_terminated_nodes() == []


def test_dashboard_ui_and_builtin_metrics(standalone_head):
    """The dashboard serves a web UI at / and head-derived cluster series
    on /metrics (reference: dashboard client + metrics_head provisioning)."""
    port = standalone_head["dashboard_port"]
    base = f"http://127.0.0.1:{port}"
    with urllib.request.urlopen(base + "/", timeout=30) as r:
        html = r.read().decode()
    assert "ray_tpu dashboard" in html and "/api/nodes" in html
    with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert "rt_nodes_alive" in text
    assert "rt_tasks_finished_total" in text


def test_metrics_provisioning_files(tmp_path):
    from ray_tpu.dashboard.provision import write_provision_files

    paths = write_provision_files(
        str(tmp_path), ["127.0.0.1:8265"], cluster_name="c1"
    )
    prom = open(paths["prometheus"]).read()
    assert "127.0.0.1:8265" in prom and "ray_tpu" in prom
    import json as _json

    dash = _json.load(open(paths["grafana_dashboard"]))
    exprs = [t["expr"] for p in dash["panels"] for t in p["targets"]]
    assert any("rt_nodes_alive" in e for e in exprs)
    assert open(paths["grafana_datasource"]).read().startswith("apiVersion")


def test_head_restart_live_rejoin(tmp_path):
    """Kill -9 the head mid-workload; restart it on the same port from its
    state file. Live nodes reconnect and re-report hosted actors, the
    driver's handle keeps working (actor calls ride direct worker
    connections even while the head is down), and NEW work schedules after
    the head returns (reference: GCS fault tolerance — gcs_init_data.cc
    replay + raylet reconnect)."""
    import signal as _signal

    state_file = str(tmp_path / "head_state.bin")
    # fixed token: --no-address-file + redacted stdout means the env is
    # the only channel to this driver (standalone runs have no ambient
    # cluster token)
    prev_tok = os.environ.get("RT_AUTH_TOKEN")
    os.environ["RT_AUTH_TOKEN"] = "rejoin-test-token"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"

    def start_head():
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.head_main",
             "--num-cpus", "2", "--state-file", state_file,
             "--state-save-interval", "0.5", "--no-address-file"],
            stdout=subprocess.PIPE, text=True, env=env, cwd="/root/repo",
        )
        return proc, json.loads(proc.stdout.readline().strip())

    proc, info = start_head()
    import ray_tpu

    try:
        ray_tpu.init(address=info["address"])

        @ray_tpu.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

        c = Counter.options(name="survivor").remote()
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 1

        # hard-kill the head mid-workload
        proc.send_signal(_signal.SIGKILL)
        proc.wait(timeout=10)

        # actor calls ride the direct worker channel: still served
        assert ray_tpu.get(c.incr.remote(), timeout=30) == 2

        # restart the head on the SAME port from its snapshot
        proc, info2 = start_head()
        assert info2["address"] == info["address"], "head must rebind port"

        # the node reconnects and re-reports the actor; state survived
        deadline = time.time() + 60
        ok = False
        while time.time() < deadline:
            try:
                if ray_tpu.get(c.incr.remote(), timeout=10) >= 3:
                    ok = True
                    break
            except Exception:
                time.sleep(0.5)
        assert ok, "actor unreachable after head restart"

        # head-side state: the name resolves again (re-adopted). The
        # driver's own head connection re-establishes asynchronously, so
        # retry like a real client.
        deadline = time.time() + 60
        h = None
        while time.time() < deadline:
            try:
                h = ray_tpu.get_actor("survivor")
                break
            except Exception:
                time.sleep(0.5)
        assert h is not None, "named actor not re-adopted by restarted head"
        assert ray_tpu.get(h.incr.remote(), timeout=30) >= 4

        # NEW work schedules through the restarted head
        @ray_tpu.remote
        def probe():
            return "alive"

        deadline = time.time() + 60
        out = None
        while time.time() < deadline:
            try:
                out = ray_tpu.get(probe.remote(), timeout=15)
                break
            except Exception:
                time.sleep(0.5)
        assert out == "alive", "new tasks don't schedule after head restart"
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            if prev_tok is None:
                os.environ.pop("RT_AUTH_TOKEN", None)
            else:
                os.environ["RT_AUTH_TOKEN"] = prev_tok
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def test_failed_init_cleans_up_and_next_init_works(monkeypatch):
    """A failed start (e.g. node-registration timeout) must not strand
    half-initialized global state: the next init() must work, not die on
    'called twice' (this cascade once took out 140 suite tests)."""
    from ray_tpu._private import node as node_mod

    def boom(self, count, timeout=30.0):
        raise TimeoutError("forced registration timeout")

    monkeypatch.setattr(node_mod.LocalCluster, "wait_for_nodes", boom)
    with pytest.raises(TimeoutError):
        ray_tpu.init(num_cpus=1, num_nodes=1)
    assert not ray_tpu.is_initialized()
    monkeypatch.undo()
    ray_tpu.init(num_cpus=1, num_nodes=1)
    try:
        assert ray_tpu.get(ray_tpu.put(7)) == 7
    finally:
        ray_tpu.shutdown()


def test_kubernetes_node_provider_fake_kubectl():
    """K8s pod-per-node provider drives kubectl through an injected runner
    (reference: the in-tree kubernetes NodeProvider / KubeRay pod
    templates): pods carry cluster labels + TPU resource requests, the
    token rides a Secret ref, and list/terminate track pod phase."""
    from ray_tpu.autoscaler import KubernetesNodeProvider

    calls, pods = [], {}

    def fake_kubectl(args, stdin=None):
        calls.append((args, stdin))
        if args[3] == "apply":
            manifest = json.loads(stdin)
            pods[manifest["metadata"]["name"]] = {
                "metadata": manifest["metadata"],
                "status": {"phase": "Pending"},
                "spec": manifest["spec"],
            }
            return ""
        if args[3] == "delete":
            pods.pop(args[5], None)
            return ""
        if args[3] == "get":
            return json.dumps({"items": list(pods.values())})
        raise AssertionError(args)

    prov = KubernetesNodeProvider(
        "10.0.0.1:6379", namespace="ml", cluster_name="rt",
        node_types={"v5e-8": {
            "resources": {"TPU": 8},
            "pod_resources": {"google.com/tpu": "8",
                              "cpu": "8", "memory": "32Gi"},
            "node_selector": {
                "cloud.google.com/gke-tpu-topology": "2x4"},
        }},
        runner=fake_kubectl,
    )
    pid = prov.create_node("v5e-8", {"TPU": 8})
    manifest = json.loads(calls[0][1])
    assert manifest["metadata"]["labels"]["raytpu.io/cluster"] == "rt"
    c = manifest["spec"]["containers"][0]
    assert c["resources"]["requests"]["google.com/tpu"] == "8"
    assert manifest["spec"]["nodeSelector"][
        "cloud.google.com/gke-tpu-topology"] == "2x4"
    assert "--address" in c["command"] and "10.0.0.1:6379" in c["command"]
    # token arrives via Secret ref, never inline
    assert c["env"][0]["valueFrom"]["secretKeyRef"]["name"] == "rt-auth"
    assert "RT_AUTH_TOKEN" not in json.dumps(manifest["spec"]).replace(
        '"name": "RT_AUTH_TOKEN"', "")

    live = prov.non_terminated_nodes()
    assert [n["provider_node_id"] for n in live] == [pid]
    # running pods stay; succeeded/failed pods drop off
    pods[pid]["status"]["phase"] = "Running"
    assert len(prov.non_terminated_nodes()) == 1
    pods[pid]["status"]["phase"] = "Failed"
    assert prov.non_terminated_nodes() == []
    # terminal pods are reclaimed (restartPolicy=Never leaves objects)
    assert pid not in pods
    assert sum(1 for a, _ in calls if a[3] == "delete") == 1
    # terminate is idempotent and kubectl-backed
    prov2_pid = prov.create_node("v5e-8", {"TPU": 8})
    prov.terminate_node(prov2_pid)
    assert prov2_pid not in pods
    prov.terminate_node(prov2_pid)  # no second kubectl call for unknown id
    assert sum(1 for a, _ in calls if a[3] == "delete") == 2
