"""Driver event-loop scale-out (round 20).

Pins the three driver planes the way ``test_transit_plane.py`` pins the
transit plane:

- the settle plane (``specframe.PlaneQueue`` / ``SettlePlane``) drains
  whole backlogs per worker wakeup and re-enters each owning event loop
  with ONE ``call_soon_threadsafe`` per drain — wakeups are O(drains),
  never O(frames);
- the bounded handoff queue REJECTS when full (producers settle inline,
  frames are never lost) and counts every reject;
- cross-thread settling preserves per-loop FIFO order and routes every
  future to the loop that owns it — the invariant sharded pusher loops
  lean on;
- pusher-shard slot affinity: every slot of one peer address lands on
  ONE shard loop, for the slot's whole life
  (``pusher_shard_affinity_breaks == 0``);
- the ``driver_settle_thread`` / ``submit_pack_thread`` /
  ``pusher_loop_shards`` gates restore the single-loop pre-round-20
  driver byte-identically when off;
- the ``driver.settle.handoff`` / ``driver.submit.pack`` faultpoints
  degrade a handoff to the inline path, never correctness.
"""
import asyncio
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import specframe
from ray_tpu._private import worker as worker_mod


@pytest.fixture(autouse=True)
def _fp_clean():
    fp.clear()
    yield
    fp.clear()


# ------------------------------------------------------ plane queue units
def test_plane_queue_drains_whole_backlog_per_wakeup():
    """Items that accumulate while the worker is busy ride the NEXT
    drain together: worker calls are O(drains), not O(items)."""
    hold = threading.Event()
    seen = []

    def worker(batch):
        seen.append(list(batch))
        hold.wait(5.0)

    q = specframe.PlaneQueue("t-drain", worker=worker, maxsize=64)
    try:
        assert q.offer("a")  # wakes the thread; worker blocks on hold
        deadline = time.monotonic() + 5.0
        while not seen and time.monotonic() < deadline:
            time.sleep(0.005)
        assert seen == [["a"]]
        # Backlog accumulates behind the blocked worker...
        for item in ("b", "c", "d"):
            assert q.offer(item)
        assert q.depth() == 3
        hold.set()
        deadline = time.monotonic() + 5.0
        while len(seen) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        # ...and drains as ONE batch: 4 items, 2 worker calls.
        assert seen == [["a"], ["b", "c", "d"]]
        snap = q.snapshot()
        assert snap["handoffs"] == 4
        assert snap["items"] == 4
        assert snap["drains"] == 2
        assert snap["max_drain"] == 3
        assert snap["rejects"] == 0
        assert snap["depth"] == 0
    finally:
        hold.set()
        q.close()


def test_plane_queue_bounded_handoff_rejects_when_full():
    """A full queue refuses the offer (the producer must settle inline)
    instead of blocking or dropping; rejects are counted and the items
    that DID hand off all drain."""
    hold = threading.Event()
    drained = []

    def worker(batch):
        hold.wait(5.0)
        drained.extend(batch)

    q = specframe.PlaneQueue("t-full", worker=worker, maxsize=2)
    try:
        assert q.offer(0)  # taken by the worker thread, which blocks
        deadline = time.monotonic() + 5.0
        while q.depth() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert q.offer(1)
        assert q.offer(2)
        assert not q.offer(3)  # bound hit: reject, never block/drop
        assert not q.offer(4)
        snap = q.snapshot()
        assert snap["rejects"] == 2
        assert snap["peak_depth"] == 2
        hold.set()
        deadline = time.monotonic() + 5.0
        while len(drained) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert drained == [0, 1, 2]  # every accepted item settled
    finally:
        hold.set()
        q.close()


def test_plane_queue_close_rejects_further_offers():
    q = specframe.PlaneQueue("t-close", worker=lambda b: None, maxsize=8)
    assert q.offer("x")
    q.close()
    assert not q.offer("y")


# ------------------------------------------------- settle plane mechanics
class _FakeLoop:
    """Counts call_soon_threadsafe re-entries and runs them inline —
    the wakeup ledger for the O(drains) contract."""

    def __init__(self):
        self.wakeups = 0
        self.applied = []

    def call_soon_threadsafe(self, fn, *args):
        self.wakeups += 1
        fn(*args)


class _FakeOwner:
    """Owner whose _settle_prepare fans its payload items out to the
    loop each item names — the shape Connection/RingConnection return."""

    def __init__(self):
        self.prepared = 0

    def _settle_prepare(self, payload):
        self.prepared += 1
        ops = []
        for loop, record, value in payload:
            ops.append((loop, record.append, value))
        return ops


def test_settle_plane_wakeups_are_o_drains_not_o_frames():
    """N frames offered while the plane worker is busy settle with ONE
    loop re-entry for the whole drain: call_soon_threadsafe counts stay
    O(drains), never O(frames)."""
    loop = _FakeLoop()
    owner = _FakeOwner()
    record = []
    sp = specframe.SettlePlane(maxsize=64)
    try:
        # Stall the plane thread with a gate payload so a burst piles up
        # behind it, then release: the burst must drain as one batch.
        gate = threading.Event()

        class _GateOwner:
            def _settle_prepare(self, payload):
                gate.wait(5.0)
                return []

        assert sp.offer(_GateOwner(), None)
        time.sleep(0.05)  # plane thread is now parked in the gate
        n = 32
        for i in range(n):
            assert sp.offer(owner, [(loop, record, i)])
        gate.set()
        deadline = time.monotonic() + 5.0
        while len(record) < n and time.monotonic() < deadline:
            time.sleep(0.005)
        assert record == list(range(n))  # all frames, in offer order
        assert owner.prepared == n  # every frame prepared off-loop
        # The whole burst re-entered the loop in O(drains) wakeups —
        # with one stalled handoff ahead of it, that is a handful of
        # drains for 32 frames, never one wakeup per frame.
        snap = sp.snapshot()
        assert loop.wakeups == snap["applies"]
        assert loop.wakeups < n / 2, (loop.wakeups, snap)
        assert snap["items"] == n + 1
    finally:
        sp.close()


def test_settle_plane_routes_futures_to_their_owning_loop_in_order():
    """One drain carrying futures homed on TWO loops settles each on
    its own loop, preserving per-loop FIFO — the invariant that lets
    sharded pusher futures ride the same settle plane as driver-loop
    futures."""
    loops, threads = [], []
    for i in range(2):
        ready = threading.Event()
        holder = {}

        def runner(ready=ready, holder=holder):
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            holder["loop"] = loop
            ready.set()
            loop.run_forever()

        t = threading.Thread(target=runner, daemon=True)
        t.start()
        assert ready.wait(5.0)
        loops.append(holder["loop"])
        threads.append(t)

    settled = {0: [], 1: []}

    class _TwoLoopOwner:
        def _settle_prepare(self, payload):
            ops = []
            for which, value in payload:
                ops.append((loops[which], settled[which].append, value))
            return ops

    sp = specframe.SettlePlane(maxsize=64)
    try:
        owner = _TwoLoopOwner()
        # Interleave the two loops' items across several offers.
        for i in range(10):
            assert sp.offer(owner, [(0, f"a{i}"), (1, f"b{i}")])
        deadline = time.monotonic() + 5.0
        while ((len(settled[0]) < 10 or len(settled[1]) < 10)
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert settled[0] == [f"a{i}" for i in range(10)]
        assert settled[1] == [f"b{i}" for i in range(10)]
    finally:
        sp.close()
        for loop in loops:
            loop.call_soon_threadsafe(loop.stop)
        for t in threads:
            t.join(timeout=5)


def test_settle_plane_faultpoint_degrades_offer_to_inline():
    """driver.settle.handoff error/drop = the offer returns False (the
    producer settles inline); nothing reaches the plane queue."""
    sp = specframe.SettlePlane(maxsize=8)
    try:
        fp.configure("driver.settle.handoff:drop:1.0")
        assert not sp.offer(_FakeOwner(), [])
        fp.configure("driver.settle.handoff:error:1.0")
        assert not sp.offer(_FakeOwner(), [])
        fp.clear()
        assert sp.offer(_FakeOwner(), [])
        assert sp.snapshot()["handoffs"] == 1
    finally:
        sp.close()


# --------------------------------------------------- end-to-end behavior
def test_driver_planes_carry_the_workload(monkeypatch):
    """Gates pinned on (RT_DRIVER_SETTLE_THREAD=1 overrides the
    single-core auto stand-down): the settle and pack planes exist,
    every submitted task flows THROUGH the pack plane, TCP reply frames
    flow through the settle plane queue, ring replies settle under the
    same discipline on the pump thread, and loop re-entries stay
    O(drains)."""
    monkeypatch.setenv("RT_DRIVER_SETTLE_THREAD", "1")
    monkeypatch.setenv("RT_SUBMIT_PACK_THREAD", "1")
    ray_tpu.init(num_cpus=4)
    try:
        w = worker_mod.global_worker
        assert w._settle_plane is not None
        assert w._pack_plane is not None
        names = {t.name for t in threading.enumerate()}
        assert "rt-settle" in names and "rt-submit-pack" in names

        @ray_tpu.remote
        def noop(i):
            return i

        n = 300
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))
        ts = w.transit_stats()
        pk = ts["pack_plane"]
        assert pk["items"] >= n and pk["rejects"] == 0
        # Batched handoff: the loop saw far fewer drains than tasks.
        assert pk["drains"] < pk["items"]
        # TCP replies (GCS registration, leases) ride the plane queue;
        # ring task replies settle IN PLACE on the pump thread (already
        # off-loop) under the same per-loop-bucketed discipline.
        st = ts["settle_plane"]
        assert st["items"] > 0 and st["depth"] == 0
        assert ts["settle"]["frames"] >= n
        # O(drains) loop re-entries: one apply per (drain, loop), and
        # with sharding off every future homes on the one driver loop.
        assert st["applies"] <= st["drains"] * max(1, len(w._pusher_loops))
    finally:
        ray_tpu.shutdown()


def test_gates_off_restore_single_loop_driver(monkeypatch):
    """RT_DRIVER_SETTLE_THREAD=0 / RT_SUBMIT_PACK_THREAD=0 /
    RT_PUSHER_LOOP_SHARDS=0: no plane objects, no plane threads, no
    shard loops — and a burst completes identically with no _sq stamp
    ever carved out of pump-queue."""
    monkeypatch.setenv("RT_DRIVER_SETTLE_THREAD", "0")
    monkeypatch.setenv("RT_SUBMIT_PACK_THREAD", "0")
    monkeypatch.setenv("RT_PUSHER_LOOP_SHARDS", "0")
    ray_tpu.init(num_cpus=2)
    try:
        w = worker_mod.global_worker
        assert w._settle_plane is None
        assert w._pack_plane is None
        assert w._pusher_loops == []
        names = {t.name for t in threading.enumerate()}
        assert not any(
            n.startswith(("rt-settle", "rt-submit-pack", "rt-pusher"))
            for n in names
        ), names
        for c in list(w.peers.values()) + [w.gcs]:
            assert getattr(c, "settle_plane", None) is None

        @ray_tpu.remote
        def noop(i):
            return i

        n = 150
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))
        ts = w.transit_stats()
        assert "settle_plane" not in ts
        assert "pack_plane" not in ts
        assert "pusher_shards" not in ts
        assert w._stats["pusher_shard_affinity_breaks"] == 0
    finally:
        ray_tpu.shutdown()


def test_pusher_shards_slot_affinity(monkeypatch):
    """RT_PUSHER_LOOP_SHARDS=2: shard loops exist, every chunk was
    pushed from a shard (the per-shard ledger accounts every task), and
    slot affinity NEVER broke — one peer's slots live on one loop, so
    its push window and rendezvous event stay single-loop."""
    monkeypatch.setenv("RT_PUSHER_LOOP_SHARDS", "2")
    ray_tpu.init(num_cpus=2)
    try:
        w = worker_mod.global_worker
        assert len(w._pusher_loops) == 2
        names = {t.name for t in threading.enumerate()}
        assert {"rt-pusher-0", "rt-pusher-1"} <= names

        @ray_tpu.remote
        def noop(i):
            return i

        n = 300
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))
        shards = w.transit_stats()["pusher_shards"]
        assert len(shards) == 2
        assert sum(s["tasks"] for s in shards) >= n
        assert sum(s["chunks"] for s in shards) > 0
        # Chunk batching survived the move off the driver loop.
        assert sum(s["chunks"] for s in shards) < n
        assert w._stats["pusher_shard_affinity_breaks"] == 0
        # Live slots are pinned to a real shard loop, consistently by
        # peer address.
        by_addr = {}
        for ls in w.leases.values():
            for s in ls.slots:
                if s.shard_loop is None:
                    continue
                assert s.shard_loop in w._pusher_loops
                prev = by_addr.setdefault(s.addr, s.shard_loop)
                assert prev is s.shard_loop
    finally:
        ray_tpu.shutdown()


def test_submit_pack_faultpoint_degrades_inline(rt_start):
    """driver.submit.pack error/drop = THAT submission packs inline on
    the caller thread; every task still completes and none is lost."""
    w = worker_mod.global_worker
    assert w._pack_plane is not None

    @ray_tpu.remote
    def noop(i):
        return i

    ray_tpu.get([noop.remote(i) for i in range(10)], timeout=120)  # warm
    fp.configure("driver.submit.pack:error:0.5:0:11")
    n = 120
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    st = fp.stats()
    assert sum(s["injected"] for s in st) > 0, st


def test_settle_handoff_faultpoint_degrades_inline(monkeypatch):
    """driver.settle.handoff drop at 1.0 = EVERY TCP reply frame
    settles inline on the event loop (pre-round-20 path) while the gate
    stays on; no frame is lost, no future hangs."""
    monkeypatch.setenv("RT_DRIVER_SETTLE_THREAD", "1")
    ray_tpu.init(num_cpus=4)
    try:
        w = worker_mod.global_worker
        assert w._settle_plane is not None

        @ray_tpu.remote
        def noop(i):
            return i

        ray_tpu.get([noop.remote(i) for i in range(10)],
                    timeout=120)  # warm
        before = w._settle_plane.snapshot()["handoffs"]
        fp.configure("driver.settle.handoff:drop:1.0")
        n = 120
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))
        fp.clear()
        # Every offer was refused: the plane ledger did not advance.
        assert w._settle_plane.snapshot()["handoffs"] == before
    finally:
        ray_tpu.shutdown()
