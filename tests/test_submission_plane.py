"""Submission-plane batching & caching economics (round 10).

Pins the RPC shape of the batched/cached submission plane by counting
verb executions at the head and template builds on the submitting worker
(style of ``test_batched_refs.py``):

- a K-task burst of one (function, options) pair serializes the spec
  template ONCE (everything else is per-call deltas spliced into the
  wire buffer);
- function-table traffic is O(unique functions), not O(fresh slots):
  push-through piggybacks the blob on the first push to each peer
  (zero head ``kv_get``s for pushed functions), and concurrent
  ``_load_function`` misses coalesce into one ``kv_get_batch``;
- an N-actor anonymous burst issues O(bursts) ``create_actor_batch``
  head RPCs (zero per-actor ``create_actor`` calls), and a dropped batch
  reply is replayed from the corr-dedup cache without double-creating a
  single actor;
- the warm worker pool turns add_node / demand growth into standby
  activation instead of a cold process spawn;
- the ``worker.spec.frame`` faultpoint degrades framing to the inline
  header path without losing a task.
"""
import time

import cloudpickle
import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.test_utils import wait_for_condition
from ray_tpu._private.worker import FN_NS


@pytest.fixture(autouse=True)
def _fp_clean():
    fp.clear()
    yield
    fp.clear()


class _HeadVerbCounter:
    """Counts head verb EXECUTIONS by shadowing ``rpc_<verb>`` on the
    in-process HeadService (dispatch resolves the handler per call, so an
    instance attribute wins). Corr-dedup replays answer from the reply
    cache without re-entering the handler — exactly the distinction the
    no-double-create assertions need. ``ns`` restricts counting to one
    KV namespace."""

    def __init__(self, head, verbs, ns=None):
        self.counts = {}
        for v in verbs:
            fn = getattr(head, "rpc_" + v)

            async def counted(h, frames, conn, _v=v, _fn=fn):
                if ns is None or h.get("ns") == ns:
                    self.counts[_v] = self.counts.get(_v, 0) + 1
                return await _fn(h, frames, conn)

            setattr(head, "rpc_" + v, counted)


# ------------------------------------------------------- spec templates
def test_spec_template_serialized_once_per_burst(rt_start):
    """K tasks of one cached function build exactly ONE spec template;
    a distinct options combination builds its own, then also caches."""
    w = worker_mod.global_worker

    @ray_tpu.remote
    def f(i):
        return i

    before = w._stats["spec_templates_built"]
    assert ray_tpu.get([f.remote(i) for i in range(200)],
                       timeout=120) == list(range(200))
    assert w._stats["spec_templates_built"] - before == 1
    # second burst of the same function: template cache hit, zero builds
    assert ray_tpu.get([f.remote(i) for i in range(50)],
                       timeout=120) == list(range(50))
    assert w._stats["spec_templates_built"] - before == 1


def test_function_push_through_zero_head_kv_gets(rt_start):
    """The function blob rides the first push to each worker (wire flag
    ``fb``): a burst on fresh workers costs ZERO function-table fetches
    at the head — O(unique functions) coverage comes from the pushes
    themselves, not kv_get round trips."""
    head = ray_tpu._internal_cluster().head
    counter = _HeadVerbCounter(head, ["kv_get", "kv_get_batch"], ns=FN_NS)

    @ray_tpu.remote
    def g(i):
        return i * 2

    assert ray_tpu.get([g.remote(i) for i in range(100)],
                       timeout=120) == [i * 2 for i in range(100)]
    fn_fetches = (counter.counts.get("kv_get", 0)
                  + counter.counts.get("kv_get_batch", 0))
    assert fn_fetches == 0, counter.counts


def test_load_function_misses_coalesce_into_one_batch(rt_start):
    """Concurrent function-table misses for K distinct keys issue ONE
    kv_get_batch (not K kv_gets): the fallback path a piggyback-less
    worker takes is itself batched."""
    w = worker_mod.global_worker
    head = ray_tpu._internal_cluster().head
    keys = []
    for i in range(8):
        key = f"subplane-test-fn-{i}"
        blob = cloudpickle.dumps(i)  # _load_function just unpickles
        w.run_sync(w.gcs.call("kv_put", {"ns": FN_NS, "key": key}, [blob]))
        keys.append(key)
    counter = _HeadVerbCounter(head, ["kv_get", "kv_get_batch"], ns=FN_NS)

    async def load_all():
        import asyncio

        return await asyncio.gather(*(w._load_function(k) for k in keys))

    assert w.run_sync(load_all(), timeout=30) == list(range(8))
    assert counter.counts.get("kv_get_batch", 0) == 1
    assert counter.counts.get("kv_get", 0) == 0
    for k in keys:
        w.fn_cache.pop(k, None)


# ------------------------------------------------------- batched actors
def test_actor_burst_is_o_bursts_head_rpcs(rt_start):
    """An N-actor anonymous burst costs O(bursts) create_actor_batch
    executions at the head — never a per-actor create_actor RPC. The
    first batch is gated at the head until the whole burst is enqueued,
    so the self-clocking flush is deterministic: exactly 2 batch RPCs
    (the 1-item opener, then everything that accumulated behind it)."""
    import asyncio

    w = worker_mod.global_worker
    head = ray_tpu._internal_cluster().head
    counter = _HeadVerbCounter(head, ["create_actor"])
    gate = w.run_sync(_make_event(), timeout=10)
    executions = []
    orig = head.rpc_create_actor_batch

    async def gated(h, frames, conn):
        executions.append(len(h.get("items", ())))
        await gate.wait()
        return await orig(h, frames, conn)

    head.rpc_create_actor_batch = gated

    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return 1

    n = 100
    # Enqueue the opener and WAIT for its 1-item batch to reach the
    # (gated) head before bursting the rest: whether the opener's drain
    # callback wins the race against a tight enqueue loop is GIL
    # preemption luck, and this test pins the batching invariant, not
    # that race.
    actors = [A.remote()]
    wait_for_condition(lambda: len(executions) == 1, timeout=10)
    actors += [A.remote() for _ in range(n - 1)]
    w.loop.call_soon_threadsafe(gate.set)
    assert ray_tpu.get([a.ping.remote() for a in actors],
                       timeout=120) == [1] * n
    assert counter.counts.get("create_actor", 0) == 0
    assert len(executions) == 2, executions
    assert sum(executions) == n
    for a in actors:
        ray_tpu.kill(a)


async def _make_event():
    import asyncio

    return asyncio.Event()


def test_dropped_batch_reply_replays_without_double_create(
        rt_start, monkeypatch):
    """The FIRST create_actor_batch reply is dropped after the head
    applied every item; the client's deadline re-issues under the same
    corr id and the dedup cache replays the original outcomes — the
    handler runs once per batch, each actor exists exactly once, and the
    placements it reserved all come back after the kill."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "1")
    head = ray_tpu._internal_cluster().head
    counter = _HeadVerbCounter(head, ["create_actor_batch"])
    before_ids = set(head.actors)
    fp.configure("gcs.dispatch.create_actor_batch:drop:1.0:1:42")

    @ray_tpu.remote(num_cpus=0.01)
    class B:
        def ping(self):
            return 2

    n = 16
    actors = [B.remote() for _ in range(n)]
    assert ray_tpu.get([a.ping.remote() for a in actors],
                       timeout=120) == [2] * n
    s = fp.stats()[0]
    assert s["injected"] == 1, s  # the drop really happened
    fp.clear()
    new_ids = set(head.actors) - before_ids
    assert len(new_ids) == n  # every actor exactly once, none doubled
    # dedup replay answered the retry: executions == distinct batches,
    # strictly fewer than client attempts (which include the retry)
    assert counter.counts.get("create_actor_batch", 0) <= n
    for a in actors:
        ray_tpu.kill(a)

    def _placements_returned():
        return all(
            all(node.available.get(k, 0.0) >= v - 1e-9
                for k, v in node.resources.items())
            for node in head.nodes.values() if node.alive
        )

    wait_for_condition(_placements_returned, timeout=20,
                       message="replayed batch leaked actor placements")


# ---------------------------------------------------- faultpoint degrade
def test_spec_frame_fault_degrades_to_inline_path(rt_start):
    """Template-build failure must cost nothing but the optimization:
    every submission still completes via the inline full-header path."""
    fp.configure("worker.spec.frame:error:1.0:0:7")

    @ray_tpu.remote
    def h(i):
        return i + 10

    assert ray_tpu.get([h.remote(i) for i in range(20)],
                       timeout=120) == [i + 10 for i in range(20)]
    s = fp.stats()[0]
    assert s["injected"] >= 1, s


# --------------------------------------------------------- warm pool
@pytest.mark.parametrize(
    "rt_start", [dict(num_cpus=1, num_nodes=1)], indirect=True)
def test_warm_pool_add_node_consumes_standby(rt_start):
    """add_node with the pool's resource spec activates a preforked
    standby (same node id) instead of cold-spawning a process, and the
    head flips it schedulable."""
    cluster = ray_tpu._internal_cluster()
    cluster.start_warm_pool(1)
    assert len(cluster.warm) == 1
    warm_id = cluster.warm[0].node_id
    nh = cluster.add_node({"CPU": 1})
    assert nh.node_id == warm_id
    assert not cluster.warm
    info = cluster.head.nodes.get(warm_id)
    assert info is not None and info.alive and not info.standby


@pytest.mark.parametrize(
    "rt_start", [dict(num_cpus=1, num_nodes=1)], indirect=True)
def test_warm_pool_auto_activates_on_demand(rt_start):
    """When demand outgrows schedulable capacity the head activates a
    standby on its own: two 1-CPU actors on a 1-CPU cluster means the
    second creation lands on the (activated) warm node."""
    cluster = ray_tpu._internal_cluster()
    cluster.start_warm_pool(1)

    @ray_tpu.remote(num_cpus=1)
    class C:
        def ping(self):
            return 3

    a, b = C.remote(), C.remote()
    assert ray_tpu.get([a.ping.remote(), b.ping.remote()],
                       timeout=120) == [3, 3]
    active = [n for n in cluster.head.nodes.values()
              if n.alive and not n.standby]
    assert len(active) == 2  # the standby joined the schedulable set
    for x in (a, b):
        ray_tpu.kill(x)


def test_standby_nodes_invisible_until_activated(rt_start):
    """A registered standby neither counts toward wait_for_nodes nor
    receives work while capacity suffices elsewhere (sequential
    submissions: demand never outgrows the active node, so the head has
    no reason to burn the reserve)."""
    cluster = ray_tpu._internal_cluster()
    cluster.start_warm_pool(1)

    def _standby_registered():
        return any(n.standby and n.alive
                   for n in cluster.head.nodes.values())

    wait_for_condition(_standby_registered, timeout=60,
                       message="warm standby never registered")
    standby_ids = {n.node_id for n in cluster.head.nodes.values()
                   if n.standby}
    # wait_for_nodes counts only schedulable nodes: satisfied at 1 even
    # though two processes are registered
    assert len(cluster._head_active_nodes()) == 1

    @ray_tpu.remote
    def where():
        return worker_mod.global_worker.node_id

    spots = {ray_tpu.get(where.remote(), timeout=60) for _ in range(8)}
    assert not (spots & standby_ids)
    assert any(n.standby for n in cluster.head.nodes.values())
