"""Flight recorder: ring semantics, zero-cost-when-off, cross-process
merge, Chrome trace-event schema, /metrics histograms, actor-push dedup.

The recorder (``_private/flight.py``) is the Dapper-style always-on verb
tracer under the task layer; its contracts tested here:

- fixed preallocated ring: wraparound keeps the NEWEST events and counts
  drops;
- disabled mode records nothing (a full cluster workload leaves the ring
  empty);
- the head's ``flight_snapshot`` fan-out merges per-process rings into one
  clock-aligned event list whose RPC spans join across processes on the
  correlation id and whose head spans carry queue-wait separately;
- the Chrome trace-event export validates against the schema Perfetto /
  chrome://tracing load;
- per-verb latency/queue-wait histograms land in the metrics registry and
  render on the Prometheus exposition;
- the push_actor_task correlation dedup replays (never re-applies) a
  duplicated delivery.
"""
import json
import time

import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import flight


@pytest.fixture(autouse=True)
def _flight_clean():
    flight.disable()
    fp.clear()
    yield
    flight.disable()
    fp.clear()


# ------------------------------------------------------------------- ring
def test_ring_wraparound_keeps_newest_and_counts_drops():
    flight.enable(ring_size=8)
    for i in range(20):
        t = time.monotonic()
        flight.record(f"v{i}", None, "client", t, t, 0, "ok")
    snap = flight.drain()
    assert len(snap["events"]) == 8
    assert snap["dropped"] == 12
    assert snap["recorded"] == 20
    assert [e[0] for e in snap["events"]] == [f"v{i}" for i in range(12, 20)]
    # drained: the ring is empty again
    assert flight.drain()["events"] == []


def test_ring_is_preallocated_tuples():
    flight.enable(ring_size=4)
    t = time.monotonic()
    flight.record("a", "c1", "client", t, t + 0.001, 7, "ok", qw=0.0)
    ev = flight.snapshot()["events"][0]
    assert isinstance(ev, tuple) and len(ev) == 8
    assert ev[0] == "a" and ev[1] == "c1" and ev[5] == 7


def test_disabled_record_is_noop():
    assert flight.ENABLED is False
    t = time.monotonic()
    flight.record("x", None, "client", t, t, 0, "ok")
    assert flight.drain()["events"] == []


def test_disabled_cluster_workload_records_zero_events(rt_start):
    assert flight.ENABLED is False

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=60) == 2
    assert flight.drain()["events"] == []
    # and the cluster-wide drain agrees for every process
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker()
    h, _ = w.run_sync(w._head_call("flight_snapshot", {}))
    assert all(not s["events"] for s in h["snapshots"])


# -------------------------------------------------------------- sampling
def test_sampling_records_exact_deterministic_fraction():
    """flight_sample_n=N keeps exactly 1/N spans at counter-determined
    indices (every Nth call, 1-based) — two identical runs sample the
    same spans, so sampled traces diff meaningfully."""
    flight.enable(ring_size=256)
    flight.set_sample_n(4)
    for i in range(100):
        t = time.monotonic()
        flight.record(f"v{i}", None, "client", t, t, 0, "ok")
    events = flight.drain()["events"]
    assert len(events) == 25
    assert [e[0] for e in events] == [f"v{i}" for i in range(3, 100, 4)]
    # counter restart: the kept indices are a pure function of N
    flight.set_sample_n(4)
    for i in range(8):
        t = time.monotonic()
        flight.record(f"w{i}", None, "client", t, t, 0, "ok")
    assert [e[0] for e in flight.drain()["events"]] == ["w3", "w7"]


def test_sampling_off_records_all_and_never_touches_counter():
    """N=0 (and N=1) disables sampling: every span records and the
    shared counter is never even bumped — the always-on cost of the
    disabled mode is one falsy comparison."""
    flight.enable(ring_size=64)
    flight.set_sample_n(0)

    class _Boom:
        def __next__(self):
            raise AssertionError("sample counter touched at N=0")

    flight._sample_count = _Boom()
    for i in range(10):
        t = time.monotonic()
        flight.record(f"v{i}", None, "client", t, t, 0, "ok")
    assert len(flight.drain()["events"]) == 10
    flight.set_sample_n(1)
    flight._sample_count = _Boom()
    t = time.monotonic()
    flight.record("one", None, "client", t, t, 0, "ok")
    assert len(flight.drain()["events"]) == 1


def test_sampling_always_keeps_fault_instants():
    """Chaos forensics must not lose injection evidence: fault instants
    bypass the sampling divisor entirely."""
    flight.enable(ring_size=64)
    flight.set_sample_n(1000)
    fp.configure("worker.pull:error:1.0:0:1")
    with pytest.raises(ConnectionError):
        fp.fire("worker.pull")
    events = flight.drain()["events"]
    assert any(e[0] == "fault.worker.pull" and e[2] == "fault"
               for e in events)


def test_enable_reads_sample_n_from_config(monkeypatch):
    monkeypatch.setenv("RT_FLIGHT_SAMPLE_N", "2")
    flight.enable(ring_size=64)
    assert flight.SAMPLE_N == 2
    for i in range(10):
        t = time.monotonic()
        flight.record(f"v{i}", None, "client", t, t, 0, "ok")
    assert len(flight.drain()["events"]) == 5


# ------------------------------------------------------------ fault stamp
def test_faultpoint_hit_stamps_active_event_and_logs_instant():
    flight.enable()
    fp.configure("worker.pull:error:1.0:0:1")
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        fp.fire("worker.pull")
    # the enclosing span (completed after the hit) picks up the stamp
    flight.record("worker.pull", None, "worker", t0, time.monotonic(),
                  0, "ok")
    events = flight.drain()["events"]
    assert any(e[0] == "fault.worker.pull" and e[2] == "fault"
               for e in events)
    stamped = [e for e in events if e[0] == "worker.pull"]
    assert stamped and stamped[0][6] == "fault_injected:worker.pull:error"


# ------------------------------------------------------------- histograms
def test_per_verb_histograms_reach_metrics_registry():
    from ray_tpu.util.metrics import registry, render_prometheus

    flight.enable()
    t = time.monotonic()
    flight.record("gcs.lease", "c1", "head", t, t + 0.01, 0, "ok", qw=0.002)
    flight.record("gcs.lease", "c2", "head", t, t + 0.02, 0, "ok", qw=0.001)
    snap = registry().snapshot()
    names = {m["name"] for m in snap}
    assert "rt_rpc_latency_s" in names
    assert "rt_rpc_queue_wait_s" in names
    lat = next(m for m in snap if m["name"] == "rt_rpc_latency_s")
    samples = [s for s in lat["samples"]
               if s["tags"].get("verb") == "gcs.lease"]
    assert samples and samples[0]["count"] >= 2
    text = render_prometheus({"worker1": snap})
    assert "rt_rpc_latency_s_bucket" in text
    assert 'verb="gcs.lease"' in text
    assert "rt_rpc_queue_wait_s_count" in text


# -------------------------------------------------------- merge machinery
def test_merge_applies_anchor_and_offset():
    snaps = [
        {"proc": "a", "pid": 1, "anchor_wall": 1000.0, "anchor_mono": 50.0,
         "offset": 0.0, "events": [("x", "c", "client", 51.0, 51.5, 0,
                                    "ok", 0.0)]},
        {"proc": "b", "pid": 2, "anchor_wall": 2000.0, "anchor_mono": 10.0,
         "offset": -999.0, "events": [("y", "c", "server", 10.2, 10.4, 0,
                                       "ok", 0.0)]},
    ]
    merged = flight.merge_snapshots(snaps)
    assert [e["verb"] for e in merged] == ["x", "y"]  # sorted by ts
    assert merged[0]["ts"] == pytest.approx(1001.0)
    assert merged[1]["ts"] == pytest.approx(2000.0 + 0.2 - 999.0)
    assert merged[1]["dur"] == pytest.approx(0.2)


def test_attribution_table():
    merged = flight.merge_snapshots([{
        "proc": "a", "pid": 1, "anchor_wall": 0.0, "anchor_mono": 0.0,
        "events": [
            ("gcs.lease", None, "head", 0.0, 0.5, 10, "ok", 0.1),
            ("gcs.lease", None, "head", 1.0, 1.5, 10, "ok", 0.2),
            ("worker.pull", None, "worker", 0.0, 0.1, 0, "ok", 0.0),
        ],
    }])
    attrib = flight.attribution(merged)
    assert attrib["gcs.lease"]["count"] == 2
    assert attrib["gcs.lease"]["total_s"] == pytest.approx(1.0)
    assert attrib["gcs.lease"]["queue_wait_s"] == pytest.approx(0.3)
    table = flight.format_attribution(attrib)
    assert "gcs.lease" in table and "worker.pull" in table


# --------------------------------------------------- cluster: full plane
def _chrome_trace_schema_ok(trace):
    for ev in trace:
        assert ev["ph"] in ("X", "s", "f"), ev
        assert isinstance(ev["name"], str) and ev["name"]
        assert "pid" in ev and "tid" in ev
        assert ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
            assert "cid" in ev["args"] and "outcome" in ev["args"]
    json.dumps(trace)  # must be JSON-serializable end to end


def test_cross_process_merge_and_chrome_trace(monkeypatch):
    # Workers inherit the env at spawn; the driver's module already
    # imported, so enable it explicitly too.
    monkeypatch.setenv("RT_FLIGHT_ENABLED", "1")
    ray_tpu.init(num_cpus=2, num_nodes=2)
    try:
        flight.enable()

        @ray_tpu.remote
        def nest(i):
            return ray_tpu.put(i)

        inners = ray_tpu.get([nest.remote(i) for i in range(8)], timeout=60)
        assert sorted(ray_tpu.get(inners, timeout=60)) == list(range(8))

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        h, _ = w.run_sync(w._head_call("flight_snapshot", {}))
        snaps = h["snapshots"]
        # head/driver process + both node processes answered the drain
        assert len(snaps) >= 3
        assert snaps[0]["proc"] == "driver" and snaps[0]["offset"] == 0.0

        merged = flight.merge_snapshots(snaps)
        assert merged
        # monotone, clock-aligned timeline
        ts = [e["ts"] for e in merged]
        assert ts == sorted(ts)
        # RPC spans join across processes on the correlation id: at least
        # one cid was recorded by two distinct processes (e.g. a worker's
        # head.<verb> client span + the head's gcs.<verb> dispatch span)
        procs_by_cid = {}
        for e in merged:
            if e["cid"]:
                procs_by_cid.setdefault(str(e["cid"]), set()).add(e["proc"])
        joined = [c for c, ps in procs_by_cid.items() if len(ps) >= 2]
        assert joined, "no RPC span joined across processes"
        # head dispatch spans carry queue-wait separately from handler time
        gcs_spans = [e for e in merged if e["verb"].startswith("gcs.")]
        assert gcs_spans
        assert all(e["qw"] >= 0.0 for e in gcs_spans)
        # chrome trace export: schema-valid, with flow events for joins
        trace = flight.to_chrome_trace(merged)
        _chrome_trace_schema_ok(trace)
        assert any(ev["ph"] == "s" for ev in trace)
        assert any(ev["ph"] == "f" for ev in trace)
        # spans parented per process: every X event's pid is a known proc
        proc_labels = {s["proc"] for s in snaps}
        assert all(ev["pid"] in proc_labels
                   for ev in trace if ev["ph"] == "X")
    finally:
        ray_tpu.shutdown()


# ------------------------------------------- actor push dedup (exactly once)
def test_duplicate_actor_push_is_replayed_not_reapplied(rt_start):
    from ray_tpu._private.ids import ActorID, TaskID
    from ray_tpu._private.worker import get_global_worker

    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.n = 0

        def add(self, v):
            self.n += v
            return self.n

        def get_n(self):
            return self.n

    a = Acc.remote()
    assert ray_tpu.get(a.add.remote(1), timeout=60) == 1
    w = get_global_worker()
    ch = w.get_actor_channel(a._actor_id_hex)
    frames, ref_ids, borrow_ids, _an = w._serialize_args((5,), {})
    tid = TaskID.of(ActorID.from_hex(a._actor_id_hex))
    header = {
        "tid": tid.hex(), "aid": a._actor_id_hex, "method": "add",
        "nret": 1, "argrefs": ref_ids, "borrows": borrow_ids,
        "owner": list(w.addr), "caller": "dup-test:1", "seq": 0,
        "corr": "dup-corr-0001",
    }

    async def deliver_twice():
        conn = await w.get_peer(ch.addr)
        h1, _ = await conn.call("push_actor_task", dict(header),
                                list(frames))
        h2, _ = await conn.call("push_actor_task", dict(header),
                                list(frames))
        return h1, h2

    h1, h2 = w.run_sync(deliver_twice(), timeout=30)
    # the duplicate got the ORIGINAL reply back...
    assert h1.get("rets") == h2.get("rets")
    # ...and the method ran exactly once
    assert ray_tpu.get(a.get_n.remote(), timeout=60) == 6


def test_actor_push_drop_is_retried_exactly_once(rt_start, monkeypatch):
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "1")

    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.n = 0

        def add(self, v):
            self.n += v
            return self.n

    a = Acc.remote()
    assert ray_tpu.get(a.add.remote(1), timeout=60) == 1
    # the next push never reaches the worker; the reply deadline fires and
    # the corr-tagged retry re-delivers — applied exactly once
    fp.configure("worker.actor.push:drop:1.0:1:3")
    assert ray_tpu.get(a.add.remote(5), timeout=60) == 6
    assert fp.stats()[0]["injected"] == 1
    fp.clear()
    assert ray_tpu.get(a.add.remote(1), timeout=60) == 7
