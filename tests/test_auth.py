"""Cluster auth token (reference: ``src/ray/rpc/authentication/`` token
auth): minted at head start, required as the first message on every
control-plane TCP connection; wrong or missing tokens are rejected before
any request dispatches."""
import asyncio
import os

import pytest

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private import worker as worker_mod


@pytest.fixture
def rt_auth():
    ray_tpu.init(num_cpus=2, num_nodes=1)
    yield worker_mod.get_global_worker()
    ray_tpu.shutdown()


def test_token_minted_and_cluster_works(rt_auth):
    assert os.environ.get("RT_AUTH_TOKEN"), "init must mint a cluster token"

    @ray_tpu.remote
    def f():
        return os.environ.get("RT_AUTH_TOKEN")

    # workers inherited the same token and the authed planes carry tasks
    assert ray_tpu.get(f.remote(), timeout=30) == os.environ["RT_AUTH_TOKEN"]


def test_wrong_or_missing_token_rejected(rt_auth, monkeypatch):
    w = rt_auth
    addr = tuple(w.gcs_addr)
    good = os.environ["RT_AUTH_TOKEN"]

    async def attempt():
        conn = await protocol.connect(addr, None, name="auth-probe")
        try:
            h, _ = await asyncio.wait_for(
                conn.call("get_nodes", {}), timeout=5
            )
            return "ok" if "nodes" in h else "bad-reply"
        except (protocol.ConnectionLost, asyncio.TimeoutError) as e:
            return type(e).__name__
        finally:
            await conn.close()

    monkeypatch.setenv("RT_AUTH_TOKEN", "deadbeef" * 4)
    assert w.run_sync(attempt()) in ("ConnectionLost", "TimeoutError")

    monkeypatch.setenv("RT_AUTH_TOKEN", "")
    assert w.run_sync(attempt()) in ("ConnectionLost", "TimeoutError")

    monkeypatch.setenv("RT_AUTH_TOKEN", good)
    assert w.run_sync(attempt()) == "ok"
