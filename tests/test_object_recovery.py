"""Object spilling + lineage reconstruction (reference:
``raylet/local_object_manager.h`` spill/restore,
``core_worker/object_recovery_manager.h`` lineage resubmit)."""
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions as rt_exc
from ray_tpu._private import worker as worker_mod


@pytest.fixture
def small_arena_cluster(monkeypatch, tmp_path):
    """Cluster whose arena is small enough to force spilling, with the
    spill dir under tmp_path."""
    from ray_tpu.native import arena as arena_mod

    monkeypatch.setattr(arena_mod, "DEFAULT_CAPACITY", 48 * 1024 * 1024)
    monkeypatch.setenv("RT_ARENA_BYTES", str(48 * 1024 * 1024))
    monkeypatch.setenv("RT_SPILL_DIR", str(tmp_path / "spill"))
    ray_tpu.init(num_cpus=2, num_nodes=1)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def rt_two_nodes():
    ray_tpu.init(num_cpus=2, num_nodes=2)
    yield
    ray_tpu.shutdown()


def test_spill_under_pressure_and_restore(small_arena_cluster):
    """Puts beyond arena capacity spill old objects to disk; gets read them
    back (restore-on-get)."""
    w = worker_mod.global_worker
    if not w.shm.native_enabled:
        pytest.skip("native arena unavailable")
    chunks = [np.full(1_000_000, i, np.float64) for i in range(12)]  # 8MB ea
    refs = [ray_tpu.put(c) for c in chunks]  # ~96MB > 48MB arena
    spill_root = w.shm.spill.root
    assert os.path.isdir(spill_root) and os.listdir(spill_root), (
        "expected spilled objects on disk"
    )
    for i, r in enumerate(refs):
        got = ray_tpu.get(r)
        assert np.array_equal(got, chunks[i]), f"object {i} corrupted"


def test_spilled_object_readable_by_worker_task(small_arena_cluster):
    """A task arg whose object was spilled is restored transparently."""
    w = worker_mod.global_worker
    if not w.shm.native_enabled:
        pytest.skip("native arena unavailable")
    first = ray_tpu.put(np.full(1_000_000, 7.0))
    # Push enough data through to force `first` out to disk.
    pressure = [ray_tpu.put(np.random.rand(1_000_000)) for _ in range(10)]

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert total.remote(first) is not None
    assert ray_tpu.get(total.remote(first)) == pytest.approx(7e6)
    del pressure


@pytest.fixture
def memory_backend_cluster(monkeypatch):
    """Cluster spilling to the in-process memory:// backend — the mocked
    remote object store (same scheme-routing path a gs:// bucket takes)."""
    from ray_tpu._private.spill import MemorySpillStorage
    from ray_tpu.native import arena as arena_mod

    monkeypatch.setattr(arena_mod, "DEFAULT_CAPACITY", 48 * 1024 * 1024)
    monkeypatch.setenv("RT_ARENA_BYTES", str(48 * 1024 * 1024))
    monkeypatch.setenv("RT_SPILL_DIR", "memory://mock-bucket/session1")
    ray_tpu.init(num_cpus=2, num_nodes=1)
    yield
    ray_tpu.shutdown()
    MemorySpillStorage._stores.clear()


def test_spill_to_external_backend_and_restore(memory_backend_cluster):
    """Pressure spills land in the external (memory://, standing in for
    gs://) backend; gets restore through the same scheme routing; spill
    metrics count the traffic (reference: external_storage.py +
    local_object_manager spill stats)."""
    from ray_tpu._private.spill import MemorySpillStorage

    w = worker_mod.global_worker
    if not w.shm.native_enabled:
        pytest.skip("native arena unavailable")
    chunks = [np.full(1_000_000, i, np.float64) for i in range(12)]
    refs = [ray_tpu.put(c) for c in chunks]  # ~96MB > 48MB arena
    store = MemorySpillStorage._stores.get("memory://mock-bucket/session1")
    assert store, "expected spilled objects in the external backend"
    assert all(u.startswith("memory://mock-bucket/session1/") for u in store)
    stats = w.shm.spill.stats
    assert stats["spilled_objects"] >= 1 and stats["spilled_bytes"] > 0
    for i, r in enumerate(refs):
        got = ray_tpu.get(r)
        assert np.array_equal(got, chunks[i]), f"object {i} corrupted"
    assert stats["restored_objects"] >= 1


def test_unknown_spill_scheme_fails_loudly(monkeypatch):
    """A scheme with no registered backend must error, not silently spill
    to local disk (gs://-style schemes raise ImportError the same way
    when their fsspec driver is absent)."""
    from ray_tpu._private.spill import SpillManager

    with pytest.raises(ValueError, match="weirdfs"):
        SpillManager(root="weirdfs://some-bucket/spill")


def test_custom_spill_scheme_registration(tmp_path):
    """register_spill_storage plugs a deployment's own backend in."""
    from ray_tpu._private import spill as spill_mod

    calls = {}

    class Fake(spill_mod.FileSpillStorage):
        def __init__(self, uri):
            calls["root"] = uri
            super().__init__(str(tmp_path / "fake"))

    spill_mod.register_spill_storage("fakefs", Fake)
    try:
        mgr = spill_mod.SpillManager(root="fakefs://bucket/x")
        meta = mgr.spill("a" * 56, [b"hello", b"world"])
        assert calls["root"] == "fakefs://bucket/x"
        assert mgr.read(meta) == [b"hello", b"world"]
    finally:
        spill_mod.STORAGE_SCHEMES.pop("fakefs", None)


def test_lineage_reconstruction_on_loss(rt_two_nodes, tmp_path):
    """Losing the only copy of a task output is repaired by re-executing the
    producing task (deterministic ObjectIDs)."""
    marker = tmp_path / "runs"

    @ray_tpu.remote
    def produce():
        with open(marker, "a") as f:
            f.write("x")
        return np.arange(200_000, dtype=np.float64)  # >INLINE: shm-backed

    ref = produce.remote()
    got = ray_tpu.get(ref)
    assert got.shape == (200_000,)
    first = np.array(got)  # materialized copy: the original is a zero-copy
    del got                # arena view whose pin would block real deletion
    import gc

    gc.collect()
    assert marker.read_text() == "x"

    # Simulate node loss of the only copy: force-delete the backing object
    # (on one machine the arena outlives simulated nodes, so deletion is the
    # honest stand-in for a remote node death).
    w = worker_mod.global_worker
    hex_ = ref.id().hex()
    h, _ = w.run_sync(w.gcs.call("object_lookup", {"oid": hex_}))
    assert h.get("found")
    w.shm.free(hex_, h["meta"])
    entry = w.memory_store.get(hex_)
    assert entry is not None and entry[0] == "shm"

    got = ray_tpu.get(ref)
    assert np.array_equal(got, first)
    assert marker.read_text() == "xx", "producing task should run again"


def test_get_survives_node_death(rt_two_nodes):
    """Kill a node mid-workload; outstanding refs still resolve (arena
    survival or reconstruction — either way the user sees the value)."""
    cluster = ray_tpu._internal_cluster()
    node = cluster.add_node({"CPU": 2, "pin": 1})
    time.sleep(0.5)

    @ray_tpu.remote(resources={"pin": 0.1}, max_retries=3)
    def produce(i):
        return np.full(100_000, float(i))

    refs = [produce.remote(i) for i in range(4)]
    ray_tpu.get(refs[0])
    cluster.kill_node(node)
    # refs either completed (value survives in the machine-wide arena) or
    # retry on other nodes... but "pin" only existed on the dead node, so
    # in-flight ones fail over only after it returns. Give the retry path a
    # moment, then expect either values or a clean WorkerCrashedError.
    try:
        vals = ray_tpu.get(refs, timeout=30)
        for i, v in enumerate(vals):
            assert np.array_equal(v, np.full(100_000, float(i)))
    except rt_exc.RayTpuError:
        pass  # acceptable: no capacity remained for the pinned resource
