"""Aux subsystems: metrics, tracing, runtime envs, chaos killers.

Reference analogs: ``python/ray/tests/test_metrics_agent.py``,
``test_tracing.py``, ``test_runtime_env*``, chaos suites under
``release/nightly_tests``.
"""
import time

import pytest

import ray_tpu
from ray_tpu.util import metrics as metrics_mod
from ray_tpu.util.metrics import Counter, Gauge, Histogram, render_prometheus


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics_mod.registry().clear()
    yield
    metrics_mod.registry().clear()


# --------------------------------------------------------------- metrics


def test_metric_primitives():
    c = Counter("rt_test_total", "a counter", ("k",))
    c.inc(2, tags={"k": "a"})
    c.inc(3, tags={"k": "a"})
    c.inc(1, tags={"k": "b"})
    g = Gauge("rt_test_gauge")
    g.set(7.5)
    h = Histogram("rt_test_hist", boundaries=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        h.observe(v)
    snap = {m["name"]: m for m in metrics_mod.registry().snapshot()}
    samples = {tuple(sorted(s["tags"].items())): s["value"]
               for s in snap["rt_test_total"]["samples"]}
    assert samples[(("k", "a"),)] == 5.0
    assert samples[(("k", "b"),)] == 1.0
    assert snap["rt_test_gauge"]["samples"][0]["value"] == 7.5
    hs = snap["rt_test_hist"]["samples"][0]
    assert hs["buckets"] == [1, 1, 1] and hs["count"] == 3


def test_counter_rejects_negative():
    c = Counter("rt_test_neg")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_prometheus_rendering():
    c = Counter("rt_render_total", "help text")
    c.inc(4)
    h = Histogram("rt_render_seconds", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = render_prometheus({"w1": metrics_mod.registry().snapshot()})
    assert "# TYPE rt_render_total counter" in text
    assert 'rt_render_total{worker_id="w1"} 4.0' in text
    assert 'le="0.1"' in text and 'le="+Inf"' in text
    assert "rt_render_seconds_count" in text


def test_metrics_flow_to_head_and_scrape():
    """Worker-side metric -> head snapshot (the dashboard /metrics source)."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote
        def emit():
            from ray_tpu.util.metrics import Counter

            c = Counter("rt_user_metric_total", "from a task")
            c.inc(9)
            return True

        assert ray_tpu.get(emit.remote())
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        deadline = time.time() + 15
        found = {}
        while time.time() < deadline:
            found = w.run_sync(w.gcs.call("metrics_snapshot", {}))[0][
                "snapshots"
            ]
            if any(
                m["name"] == "rt_user_metric_total"
                for snap in found.values() for m in snap
            ):
                break
            time.sleep(0.3)
        text = render_prometheus(found)
        assert "rt_user_metric_total" in text
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------- tracing


def test_tracing_spans_propagate():
    from ray_tpu.util.tracing import setup_tracing, teardown_tracing

    exporter = setup_tracing(in_memory=True)
    if exporter is None:
        pytest.skip("opentelemetry SDK unavailable")
    try:
        ray_tpu.init(num_cpus=2)
        try:
            @ray_tpu.remote
            def traced(x):
                return x + 1

            assert ray_tpu.get(traced.remote(1)) == 2
            # The submit-side context was injected into the task header;
            # driver-side spans appear in this process's exporter.
            from ray_tpu.util.tracing import span

            with span("driver::section"):
                pass
            names = [s.name for s in exporter.get_finished_spans()]
            assert "driver::section" in names
        finally:
            ray_tpu.shutdown()
    finally:
        teardown_tracing()


def test_task_header_carries_trace_context():
    from ray_tpu.util.tracing import (
        enabled,
        inject_context,
        setup_tracing,
        teardown_tracing,
    )

    assert not enabled()
    assert inject_context() is None  # disabled -> zero-cost path
    exporter = setup_tracing(in_memory=True)
    if exporter is None:
        pytest.skip("opentelemetry SDK unavailable")
    try:
        from ray_tpu.util.tracing import span

        with span("parent"):
            carrier = inject_context()
        assert carrier and "traceparent" in carrier
    finally:
        teardown_tracing()


# ------------------------------------------------------------ runtime env


def test_runtime_env_working_dir(tmp_path):
    marker = tmp_path / "marker.txt"
    marker.write_text("found me")
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
        def read_marker():
            import os

            with open("marker.txt") as f:
                return os.path.basename(os.getcwd()), f.read()

        base, content = ray_tpu.get(read_marker.remote())
        assert content == "found me"
        assert base == tmp_path.name
    finally:
        ray_tpu.shutdown()


def test_runtime_env_unknown_plugin_fails_loudly():
    """Round-2 contract change: unknown plugins raise instead of being
    silently dropped (pip/uv/py_modules are now real — test_runtime_env)."""
    ray_tpu.init(num_cpus=2)
    try:
        @ray_tpu.remote(runtime_env={"container": {"image": "x"}})
        def f():
            return "should not run"

        with pytest.raises(ray_tpu.exceptions.RayTpuError):
            ray_tpu.get(f.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------------------------- chaos


def test_tasks_survive_node_killer():
    """Retriable tasks complete while a killer takes out nodes mid-run
    (reference: RayletKiller chaos)."""
    from ray_tpu._private.test_utils import NodeKiller

    ray_tpu.init(num_cpus=2, num_nodes=3)
    try:
        cluster = ray_tpu._internal_cluster()

        @ray_tpu.remote(max_retries=5)
        def work(i):
            import time as _t

            _t.sleep(0.05)
            return i * i

        killer = NodeKiller(cluster, interval_s=0.3, min_alive=1).start()
        try:
            refs = [work.remote(i) for i in range(120)]
            results = ray_tpu.get(refs, timeout=120)
            assert results == [i * i for i in range(120)]
        finally:
            killer.stop()
        assert killer.killed, "chaos killer never fired"
    finally:
        ray_tpu.shutdown()


def test_metric_reregistration_accumulates():
    """Re-constructing a metric with the same name must keep accumulating
    into the same series (task bodies re-run on the same worker)."""
    c1 = Counter("rt_reuse_total")
    c1.inc(2)
    c2 = Counter("rt_reuse_total")
    c2.inc(3)
    snap = {m["name"]: m for m in metrics_mod.registry().snapshot()}
    assert snap["rt_reuse_total"]["samples"][0]["value"] == 5.0
    with pytest.raises(ValueError):
        Gauge("rt_reuse_total")  # type change is an error
    h1 = Histogram("rt_reuse_hist", boundaries=(1.0,))
    h1.observe(0.5)
    with pytest.raises(ValueError):
        Histogram("rt_reuse_hist", boundaries=(2.0,))


# ------------------------------------------------------------ memory/OOM


def test_memory_monitor_reads_usage():
    from ray_tpu._private.memory_monitor import MemoryMonitor, get_memory_usage

    used, total = get_memory_usage()
    assert total > 0 and 0 <= used <= total
    assert not MemoryMonitor(threshold=1.0).is_pressing()
    assert MemoryMonitor(threshold=0.0).is_pressing()


def test_oom_rejection_is_retriable_and_surfaces():
    """A node over its memory threshold rejects tasks; the submitter
    retries and finally surfaces OutOfMemoryError (reference: memory
    monitor + worker-killing policy + task retries)."""
    ray_tpu.init(num_cpus=2, _node_env={"RT_MEMORY_THRESHOLD": "0.0"})
    try:
        @ray_tpu.remote(max_retries=1)
        def f():
            return 1

        with pytest.raises(ray_tpu.exceptions.OutOfMemoryError):
            ray_tpu.get(f.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


def test_oom_retry_lands_on_healthy_node():
    """With one pressured node and one healthy node, retries land the task
    (slot eviction + fresh lease)."""
    ray_tpu.init(num_cpus=2)
    try:
        cluster = ray_tpu._internal_cluster()
        cluster.add_node({"CPU": 2}, env={"RT_MEMORY_THRESHOLD": "0.0"})
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(max_retries=8)
        def f(i):
            return i + 1

        assert ray_tpu.get([f.remote(i) for i in range(20)], timeout=120) == [
            i + 1 for i in range(20)
        ]
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------- debugging / profiling


def test_cluster_stack_dump():
    """Per-node all-thread stack dumps via the head fan-out (reference:
    ``ray stack`` / reporter-agent py-spy hooks — util/debug.py)."""
    import ray_tpu
    from ray_tpu.util.debug import dump_local_stacks, get_cluster_stacks

    local = dump_local_stacks()
    assert "--- thread MainThread" in local
    assert "test_cluster_stack_dump" in local  # sees this very frame

    ray_tpu.init(num_cpus=2, num_nodes=2)
    try:
        stacks = get_cluster_stacks()
        assert "driver" in stacks
        node_entries = [k for k in stacks if k != "driver"]
        assert len(node_entries) == 2
        for nid in node_entries:
            assert "--- thread" in stacks[nid], stacks[nid][:200]
    finally:
        ray_tpu.shutdown()


def test_node_memory_profile():
    """tracemalloc-backed memory profiling on a remote node (memray
    analog): start -> allocate in a task -> snapshot shows sites."""
    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.debug import node_memory_profile

    ray_tpu.init(num_cpus=2, num_nodes=1)
    try:
        node_id = state.list_nodes()[0]["node_id"]
        out = node_memory_profile(node_id, "start")
        assert out["tracing"] is True

        @ray_tpu.remote
        def alloc():
            keep = [bytearray(64_000) for _ in range(20)]
            return len(keep)

        assert ray_tpu.get(alloc.remote()) == 20
        snap = node_memory_profile(node_id, "snapshot", top=5)
        assert snap["tracing"] is True
        assert len(snap["top"]) >= 1
        assert all("size_bytes" in s for s in snap["top"])
        out = node_memory_profile(node_id, "stop")
        assert out["tracing"] is False
    finally:
        ray_tpu.shutdown()


def test_sampling_cpu_profile_local():
    """Pure-stdlib sampling profiler (py-spy record analog) emits folded
    flamegraph stacks that include a busy thread's frames."""
    import threading
    import time

    from ray_tpu.util.debug import sample_cpu_profile

    stop = threading.Event()

    def spin_with_marker_frame():
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=spin_with_marker_frame, daemon=True)
    t.start()
    try:
        folded = sample_cpu_profile(duration_s=0.8, hz=80)
    finally:
        stop.set()
        t.join(timeout=5)
    assert folded, "no samples collected"
    assert "spin_with_marker_frame" in folded
    # folded format: "a;b;c N" per line
    line = next(ln for ln in folded.splitlines()
                if "spin_with_marker_frame" in ln)
    assert line.rsplit(" ", 1)[1].isdigit()


def test_node_cpu_profile_rpc():
    """The sampler runs on a remote node through the head fan-out and sees
    an executing task's frames."""
    import threading
    import time

    import ray_tpu
    from ray_tpu.util import state
    from ray_tpu.util.debug import node_cpu_profile

    ray_tpu.init(num_cpus=2, num_nodes=1)
    try:
        node_id = state.list_nodes()[0]["node_id"]

        @ray_tpu.remote
        def burn_cpu_marker(sec):
            import time as _t
            end = _t.monotonic() + sec
            while _t.monotonic() < end:
                sum(i * i for i in range(400))
            return "done"

        ref = burn_cpu_marker.remote(4.0)
        time.sleep(0.5)
        folded = node_cpu_profile(node_id, duration_s=1.5)
        assert "burn_cpu_marker" in folded, folded[:400]
        assert ray_tpu.get(ref, timeout=30) == "done"
    finally:
        ray_tpu.shutdown()


def test_xla_profile_capture_smoke():
    """XLA trace capture produces a TensorBoard-readable trace dir (CPU
    backend in CI; the same call captures TPU timelines on hardware)."""
    import os

    import pytest as _pt

    from ray_tpu.util.debug import xla_profile_capture

    res = xla_profile_capture(duration_s=0.3)
    if not res.get("ok"):
        _pt.skip(f"jax profiler unavailable here: {res.get('error')}")
    assert os.path.isdir(res["logdir"])
    # the trace writer lays down plugins/profile/<ts>/ under the logdir
    found = []
    for root, _dirs, files in os.walk(res["logdir"]):
        found.extend(files)
    assert found, "trace dir is empty"


def test_cli_stack_command(capsys):
    import ray_tpu
    from ray_tpu import cli

    ray_tpu.init(num_cpus=2, num_nodes=1)
    try:
        addr = ray_tpu._internal_cluster().gcs_addr
        cli.main(["stack", "--address", f"{addr[0]}:{addr[1]}"])
        out = capsys.readouterr().out
        assert "===== node" in out
        assert "--- thread" in out
    finally:
        ray_tpu.shutdown()


def test_config_registry_resolution(monkeypatch):
    """Declared default < _system_config < env var (reference:
    ray_config_def.h RAY_CONFIG + _system_config override)."""
    from ray_tpu._private.config import ConfigRegistry

    reg = ConfigRegistry()
    reg.declare("probe_knob", int, 7, "test knob")
    assert reg.get("probe_knob") == 7
    reg.apply_system_config({"probe_knob": 11})
    assert reg.get("probe_knob") == 11
    monkeypatch.setenv("RT_PROBE_KNOB", "13")
    assert reg.get("probe_knob") == 13
    assert reg.system_config_env() == {"RT_PROBE_KNOB": "11"}
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unknown _system_config"):
        reg.apply_system_config({"nope": 1})


def test_system_config_propagates_to_workers(tmp_path):
    """init(_system_config=...) reaches spawned worker processes as RT_*
    env (the raylet-cmdline propagation analog)."""
    import ray_tpu

    ray_tpu.init(
        num_cpus=2, num_nodes=1,
        _system_config={"lineage_bytes": 123456789},
    )
    try:
        @ray_tpu.remote
        def probe():
            import os

            from ray_tpu._private.config import rt_config

            return os.environ.get("RT_LINEAGE_BYTES"), rt_config.lineage_bytes

        env_val, resolved = ray_tpu.get(probe.remote(), timeout=30)
        assert env_val == "123456789"
        assert resolved == 123456789
    finally:
        ray_tpu.shutdown()
