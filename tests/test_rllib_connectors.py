"""ConnectorV2 pipelines and the TQC algorithm.

Reference analog: ``rllib/connectors/`` (ConnectorV2 / ConnectorPipelineV2 /
MeanStdFilter state merge) and the reference's TQC (truncated quantile
critics) roster entry — unit transforms, state-merge math, runner
integration, and a short TQC learning run.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import TQCConfig
from ray_tpu.rllib.connectors import (
    ClipObs,
    ConnectorPipelineV2,
    FlattenObs,
    FrameStack,
    MeanStdFilter,
    RescaleActions,
)


# ----------------------------------------------------------- unit transforms


def test_pipeline_applies_in_order():
    p = ConnectorPipelineV2([FlattenObs(), ClipObs(-1.0, 1.0)])
    out = p({"obs": np.full((2, 3, 4), 5.0, np.float32)})
    assert out["obs"].shape == (2, 12)
    assert out["obs"].max() == 1.0


def test_mean_std_filter_normalizes():
    f = MeanStdFilter()
    rng = np.random.RandomState(0)
    data = rng.normal(3.0, 2.0, (4096, 5)).astype(np.float32)
    f({"obs": data})
    out = f({"obs": data}, training=False)["obs"]
    assert abs(out.mean()) < 0.05
    assert abs(out.std() - 1.0) < 0.05
    # training=False must not touch statistics
    count = f.count
    f({"obs": data * 100}, training=False)
    assert f.count == count


def test_mean_std_merge_matches_pooled_moments():
    rng = np.random.RandomState(1)
    a = rng.normal(0.0, 1.0, (500, 3))
    b = rng.normal(5.0, 3.0, (1500, 3))
    fa, fb = MeanStdFilter(), MeanStdFilter()
    fa({"obs": a})
    fb({"obs": b})
    merged = MeanStdFilter.merge_states([fa.get_state(), fb.get_state()])
    pooled = np.concatenate([a, b])
    assert np.allclose(merged["mean"], pooled.mean(0), atol=1e-8)
    assert np.allclose(
        merged["m2"] / merged["count"], pooled.var(0), atol=1e-8
    )


def test_frame_stack_resets_on_done():
    fs = FrameStack(k=3)
    o1 = np.array([[1.0, 1.0]], np.float32)
    o2 = np.array([[2.0, 2.0]], np.float32)
    o3 = np.array([[9.0, 9.0]], np.float32)
    assert fs({"obs": o1})["obs"].shape == (1, 6)
    out = fs({"obs": o2})["obs"]
    assert out[0, 0] == 1.0 and out[0, -1] == 2.0  # oldest..newest
    # done resets the column: history becomes [o3, o3, o3]
    out = fs({"obs": o3}, dones=np.array([1.0]))["obs"]
    assert np.all(out == 9.0)
    # stateless probe does not touch history
    probe = fs({"obs": o1}, training=False)["obs"]
    assert np.all(probe == 1.0)
    out = fs({"obs": o2})["obs"]
    assert out[0, 0] == 9.0 and out[0, -1] == 2.0


def test_rescale_actions():
    r = RescaleActions(low=[-2.0], high=[6.0])
    out = r({"actions": np.array([[-1.0], [0.0], [1.0]], np.float32)})
    assert np.allclose(out["actions"].ravel(), [-2.0, 2.0, 6.0])


# ------------------------------------------------------- runner integration


class ShiftedObsEnv:
    """1-step env whose observations sit at mean ~100: PPO-style learners
    choke on unnormalized inputs; MeanStdFilter centers them."""

    class _Space:
        def __init__(self, low, high, shape):
            self.low = np.full(shape, low, np.float32)
            self.high = np.full(shape, high, np.float32)
            self.shape = shape

    def __init__(self):
        self.observation_space = self._Space(-200, 200, (3,))
        self.action_space = self._Space(-1, 1, (1,))
        self._rng = np.random.RandomState(0)

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        return self._obs(), {}

    def _obs(self):
        return (100.0 + self._rng.randn(3)).astype(np.float32)

    def step(self, action):
        a = np.asarray(action, np.float32).ravel()
        reward = -float(np.sum((a - 0.5) ** 2))
        return self._obs(), reward, True, False, {}

    def close(self):
        pass


@pytest.fixture
def rl_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    ray_tpu.shutdown()


def test_runner_applies_and_syncs_connector_state(rl_cluster):
    cfg = (
        TQCConfig()
        .environment(env_creator=ShiftedObsEnv)
        .env_runners(
            num_env_runners=2, num_envs_per_env_runner=2,
            rollout_fragment_length=16,
            env_to_module_connector=lambda: ConnectorPipelineV2(
                [MeanStdFilter()]
            ),
        )
        .debugging(seed=0)
    )
    cfg.min_replay_size = 10_000_000  # sampling only; no updates needed
    algo = cfg.build_algo()
    try:
        algo.train()
        merged = algo.runner_group.sync_connector_states()
        # both runners contributed: 2 runners x 2 envs x 16 steps
        assert merged and merged[0]["count"] == 2 * 2 * 16
        assert np.allclose(merged[0]["mean"], 100.0, atol=2.0)
        # runners saw normalized observations (stored in the batch)
        frags = algo.runner_group.sample()
        obs = np.concatenate([f["obs"] for f in frags], axis=1)
        assert abs(float(obs.mean())) < 3.0
    finally:
        algo.stop()


def test_frame_stack_integration_in_runner():
    """FrameStack changes the module obs dim, gets episode-boundary resets
    from the runner's dones, and the bootstrap value rides the transformed
    obs (it would shape-crash on raw obs)."""
    from ray_tpu.rllib.env_runner import SingleAgentEnvRunner

    class CountingEnv:
        """obs = [step_count]; episodes end after 3 steps."""

        class _Space:
            def __init__(self, n):
                self.low = np.full((n,), -100, np.float32)
                self.high = np.full((n,), 100, np.float32)
                self.shape = (n,)

        def __init__(self):
            self.observation_space = self._Space(1)
            self.action_space = self._Space(1)
            self._t = 0

        def reset(self, seed=None):
            self._t = 0
            return np.array([0.0], np.float32), {}

        def step(self, action):
            self._t += 1
            done = self._t >= 3
            return (
                np.array([float(self._t)], np.float32), 0.0, done, False, {}
            )

        def close(self):
            pass

    k = 2
    runner = SingleAgentEnvRunner(
        CountingEnv, num_envs=1, fragment_len=8,
        module_config={"obs_dim": k, "action_dim": 1, "discrete": False},
        env_to_module=lambda: FrameStack(k=k),
    )
    import jax

    from ray_tpu.rllib import module as rl_module

    runner.set_weights(rl_module.init_params(
        rl_module.RLModuleConfig(obs_dim=k, action_dim=1, discrete=False),
        jax.random.PRNGKey(0),
    ))
    frag = runner.sample()
    obs = frag["obs"][:, 0, :]              # [T, k]
    assert obs.shape == (8, k)
    # env obs: 0,1,2,(done)->0,1,2,(done)->0,...; stacked pairs
    # step 3 is the first frame after a reset: history must be [0, 0],
    # not [2, 0] (episode bleed)
    done_steps = np.nonzero(frag["dones"][:, 0])[0]
    first_after = int(done_steps[0]) + 1
    assert np.allclose(obs[first_after], 0.0), obs
    assert frag["bootstrap_value"].shape == (1,)

    # episode ending exactly on a fragment's LAST step: the reset must
    # still reach the connector at the next fragment's first step
    runner2 = SingleAgentEnvRunner(
        CountingEnv, num_envs=1, fragment_len=3,
        module_config={"obs_dim": k, "action_dim": 1, "discrete": False},
        env_to_module=lambda: FrameStack(k=k),
    )
    runner2.set_weights(runner.params)
    f1 = runner2.sample()
    assert f1["dones"][-1, 0] == 1.0  # done on the fragment edge
    f2 = runner2.sample()
    # fresh episode: stacked history is [0, 0], not [2, 0]
    assert np.allclose(f2["obs"][0, 0], 0.0), f2["obs"][0, 0]


# ----------------------------------------------------------------- TQC algo


class TargetReachEnv:
    """1-step continuous env: reward = -(a - 0.5)^2 per dim (same shape as
    the SAC test target)."""

    class _Space:
        def __init__(self, low, high, shape):
            self.low = np.full(shape, low, np.float32)
            self.high = np.full(shape, high, np.float32)
            self.shape = shape

    def __init__(self):
        self.observation_space = self._Space(-1, 1, (3,))
        self.action_space = self._Space(-1, 1, (1,))

    def reset(self, seed=None):
        return np.zeros(3, np.float32), {}

    def step(self, action):
        a = np.asarray(action, np.float32).ravel()
        reward = -float(np.sum((a - 0.5) ** 2))
        return np.zeros(3, np.float32), reward, True, False, {}

    def close(self):
        pass


def _tqc_config():
    return (
        TQCConfig()
        .environment(env_creator=TargetReachEnv)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .debugging(seed=0)
        .training(lr=3e-3)
    )


def test_tqc_learns_target(rl_cluster):
    cfg = _tqc_config()
    cfg.min_replay_size = 200
    cfg.updates_per_step = 32
    algo = cfg.build_algo()
    try:
        last = None
        for _ in range(20):
            r = algo.train()
            last = r["episode_return_mean"]
        # optimal return is 0; random tanh actions average about -0.58
        assert last > -0.25, f"TQC did not improve: last={last}"
        assert "alpha" in r and r["alpha"] > 0
        assert np.isfinite(r["critic_loss"])
    finally:
        algo.stop()


def test_tqc_truncation_drops_top_atoms():
    """The pooled-sort-truncate target keeps the N*M - N*d smallest atoms."""
    import jax.numpy as jnp

    N, M, d = 2, 5, 2
    z = jnp.asarray(
        [[[10.0, 1.0, 7.0, 3.0, 5.0], [2.0, 8.0, 4.0, 6.0, 9.0]]]
    )  # [1, N, M]
    pooled = jnp.sort(z.reshape(1, N * M), -1)
    kept = pooled[:, : N * M - N * d]
    assert kept.shape == (1, 6)
    assert float(kept.max()) == 6.0  # 7,8,9,10 dropped


def test_tqc_checkpoint_roundtrip(rl_cluster, tmp_path):
    cfg = _tqc_config()
    cfg.min_replay_size = 50
    cfg.updates_per_step = 4
    algo = cfg.build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "ck"))
        w_before = algo.get_weights()

        algo2 = _tqc_config().build_algo()
        try:
            algo2.restore(path)
            w_after = algo2.get_weights()
            import jax

            for a, b in zip(jax.tree.leaves(w_before),
                            jax.tree.leaves(w_after)):
                assert np.allclose(a, b)
            assert algo2.iteration == algo.iteration
        finally:
            algo2.stop()
    finally:
        algo.stop()


def test_tqc_rejects_all_atoms_dropped(rl_cluster):
    cfg = _tqc_config()
    cfg.n_critics = 2
    cfg.n_quantiles = 3
    cfg.top_quantiles_to_drop_per_net = 3
    with pytest.raises(ValueError, match="drops every atom"):
        cfg.build_algo()
