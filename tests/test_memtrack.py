"""Object & memory observability plane (ISSUE-11 acceptance surface).

- analyzer units: ``build_summary`` reconciliation/leak join and
  ``rollup_gauge`` on synthetic input (no cluster);
- a real 2-node run where ``memory_summary`` reconciles: per-node
  directory-accounted bytes equal owner-accounted arena bytes exactly,
  every row carries owner/creating-task/ref-state/spill-state, and the
  memory_summary row schema is PINNED;
- accounting correctness: put/get/del reconciliation, borrow
  registration keeping a freed owner's object alive, a spill transition
  flipping the ``kind`` gauge, the ``rt memory --leaks`` exit-code
  contract, and disabled-mode parity (one boolean off ⇒ no enrichment,
  no gauges, no rows — mirroring the flight/taskpath gates);
- the head's single ``/metrics`` scrape serving
  ``rt_object_store_bytes{node_id,kind}`` / ``rt_object_count{node_id,
  state}`` covering every node of a 2-node cluster;
- ``rpc_list_objects`` server-side filters + honest truncation
  ({recorded, dropped}, never a silent slice).
"""
import gc
import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import memtrack
from ray_tpu._private.test_utils import wait_for_condition

BIG = 200_000  # comfortably over INLINE_OBJECT_MAX (100 KiB)


@pytest.fixture(autouse=True)
def _memtrack_on():
    """The plane defaults on; tests that toggled it must not bleed."""
    memtrack.enable()
    yield
    memtrack.enable()


# ----------------------------------------------------------- analyzer units
def _raw(snapshots, directory, tasks=None, now=100.0):
    return {"snapshots": snapshots, "directory": directory,
            "tasks": tasks or {}, "now": now, "recorded": len(directory),
            "dropped": 0, "enabled": bool(snapshots)}


def _snap(node, addr, objects=(), borrowed=(), store_oids=(), arena=None):
    return {"worker": f"w-{node}", "node": node, "addr": list(addr),
            "is_driver": False, "objects": list(objects),
            "borrowed": list(borrowed), "store_oids": list(store_oids),
            "arena": arena, "fallback": {"objects": 0, "bytes": 0},
            "graveyard": {"segments": 0, "bytes": 0}, "spill": {},
            "mem_used_ratio": 0.5, "now": 100.0}


def _obj(oid, nbytes, kind="shm", state="owned", node="n1", borrows=0):
    return {"oid": oid, "bytes": nbytes, "kind": kind, "state": state,
            "count": 1, "borrows": borrows, "node": node}


def test_build_summary_reconciles_and_joins_names():
    tid = "t" * 48
    oid = tid + "00000001"
    raw = _raw(
        [_snap("n1", ("h", 1), objects=[_obj(oid, 1000)],
               store_oids=[oid],
               arena={"bytes_in_use": 1024, "capacity": 4096,
                      "peak_bytes": 2048, "num_objects": 1})],
        [{"oid": oid, "meta": {"arena": "a", "size": 1000, "node": "n1",
                               "owner": ["h", 1], "_t": 10.0}}],
        tasks={tid: "maker"},
    )
    s = memtrack.build_summary(raw, grace_s=5.0)
    assert s["enabled"] is True
    row = s["rows"][0]
    assert row["fn"] == "maker" and row["task"] == tid
    rec = s["reconcile"]["n1"]
    assert rec["owner_shm_bytes"] == 1000
    assert rec["directory_shm_bytes"] == 1000
    assert rec["delta_shm_bytes"] == 0
    assert rec["arena_peak_bytes"] == 2048
    assert s["totals"]["arena_peak_bytes"] == 2048
    assert s["leaks"] == []


def test_build_summary_flags_orphans_past_grace_only():
    dead = "d" * 56
    directory = [{"oid": dead, "meta": {"seg": "x", "size": 64,
                                        "owner": ["gone", 9],
                                        "_t": 90.0}}]
    live_snap = [_snap("n1", ("h", 1))]
    s = memtrack.build_summary(_raw(live_snap, directory), grace_s=5.0)
    assert len(s["leaks"]) == 1
    assert s["leaks"][0]["reason"] == "owner-gone"
    assert s["leaks"][0]["age_s"] == pytest.approx(10.0)
    # young entries sit inside the grace window
    s = memtrack.build_summary(_raw(live_snap, directory), grace_s=60.0)
    assert s["leaks"] == []
    # a borrower keeps the entry alive (borrow IS the liveness)
    borrower = [_snap("n1", ("h", 1),
                      borrowed=[{"oid": dead, "count": 1,
                                 "owner": ["gone", 9]}])]
    s = memtrack.build_summary(_raw(borrower, directory), grace_s=0.0)
    assert s["leaks"] == []
    # a live store mapping keeps it alive too (put_raw_frames lifetime)
    holder = [_snap("n1", ("h", 1), store_oids=[dead])]
    s = memtrack.build_summary(_raw(holder, directory), grace_s=0.0)
    assert s["leaks"] == []
    # no snapshots at all (plane off): detection is a no-op, not noise
    s = memtrack.build_summary(_raw([], directory), grace_s=0.0)
    assert s["leaks"] == [] and s["enabled"] is False


class _FakeWorker:
    """Just enough CoreWorker surface for local_snapshot units."""

    class _WID:
        @staticmethod
        def hex():
            return "w" * 12

    def __init__(self, n_pending):
        self.owned = {f"{i:056x}": {"count": 1, "borrows": 0}
                      for i in range(n_pending)}
        self.memory_store = {}
        self.borrowed = {}
        self.node_id = "n" * 32
        self.worker_id = self._WID()
        self.addr = ("h", 1)
        self.is_driver = True
        self._shm = None


def test_local_snapshot_row_cap_is_honest_and_aggregates_stay_exact():
    """A burst-sized owned map must not ship a row per object: the
    listing truncates at max_rows with a reported drop, counts stay
    exact, and a truncated cluster summary disarms leak detection
    (an unlisted owner row would read as an orphan) while saying so."""
    fw = _FakeWorker(1000)
    snap = memtrack.local_snapshot(fw, max_rows=10)
    assert len(snap["objects"]) == 10
    assert snap["objects_total"] == 1000
    assert snap["objects_dropped"] == 990
    assert snap["counts_by_state"]["pending"] == 1000
    # aggregate-only mode builds zero rows in the same exact pass
    snap0 = memtrack.local_snapshot(fw, max_rows=0)
    assert snap0["objects"] == [] and snap0["objects_dropped"] == 1000
    # a truncated snapshot joined with an orphan directory entry: no
    # leak flagged, but the summary admits detection was skipped
    orphan = [{"oid": "e" * 56, "meta": {"seg": "x", "size": 9,
                                         "_t": 0.0}}]
    s = memtrack.build_summary(_raw([snap], orphan, now=1000.0),
                               grace_s=0.0)
    assert s["leaks"] == [] and s["leaks_truncated"] is True
    assert s["totals"]["objects"] == 1000
    assert "truncated" in memtrack.format_summary(s)
    # same directory with a complete snapshot: the leak IS flagged
    full = memtrack.local_snapshot(fw)
    s = memtrack.build_summary(_raw([full], orphan, now=1000.0),
                               grace_s=0.0)
    assert len(s["leaks"]) == 1 and s["leaks_truncated"] is False


def test_group_rows_and_format():
    rows = [
        {"oid": "a" * 56, "bytes": 10, "kind": "shm", "state": "owned",
         "node": "n1", "owner": ["h", 1], "owner_node": "n1",
         "task": "a" * 48, "fn": "f", "count": 1, "borrows": 0},
        {"oid": "b" * 56, "bytes": 30, "kind": "shm", "state": "pinned",
         "node": "n1", "owner": ["h", 1], "owner_node": "n1",
         "task": "b" * 48, "fn": "g", "count": 0, "borrows": 2},
    ]
    g = memtrack.group_rows(rows, "node")
    assert g["n1"] == {"objects": 2, "bytes": 40, "pinned": 1}
    with pytest.raises(ValueError):
        memtrack.group_rows(rows, "nope")
    s = memtrack.build_summary(_raw([], []))
    s["rows"] = rows
    text = memtrack.format_summary(s)
    assert "leak-candidates=0" in text


def test_rollup_gauge_sum_max_and_node_tag():
    from ray_tpu.util.metrics import rollup_gauge

    def snap(value, tags):
        return [{"name": "rt_object_store_bytes", "type": "gauge",
                 "help": "h",
                 "samples": [{"tags": tags, "value": value}]}]

    # sample-level "node" tag wins over the pushing worker's node and
    # same-key values SUM across workers
    text = rollup_gauge(
        {"w1": snap(5, {"kind": "shm", "node": "nodeB"}),
         "w2": snap(7, {"kind": "shm", "node": "nodeB"})},
        "rt_object_store_bytes", {"w1": "nodeA", "w2": "nodeA"},
    )
    assert 'node_id="nodeB"' in text and "12.0" in text
    assert 'node_id="nodeA"' not in text
    # max agg for node-shared readings
    text = rollup_gauge(
        {"w1": snap(5, {}), "w2": snap(7, {})},
        "rt_object_store_bytes", {"w1": "nodeA", "w2": "nodeA"},
        agg="max",
    )
    assert text.strip().endswith("7.0")
    assert rollup_gauge({}, "missing") == ""
    with pytest.raises(ValueError):
        rollup_gauge({}, "x", agg="median")


# ----------------------------------------------------- schema pinning
REQUIRED_ROW_FIELDS = set(memtrack.ROW_FIELDS)


def test_memory_summary_row_schema_is_pinned(rt_start):
    """The row dict is a cross-surface contract (`rt memory`, the
    dashboard objects page, the chaos leak SLO all parse it): a new
    field means updating memtrack.ROW_FIELDS (and PARITY.md)
    deliberately."""
    from ray_tpu.util import state

    ref = ray_tpu.put(np.zeros(BIG, dtype=np.uint8))
    small = ray_tpu.put(b"tiny")
    s = state.memory_summary(grace_s=0.0)
    assert s["rows"], "no accounting rows for live objects"
    for row in s["rows"]:
        keys = set(row) - {"locations"}  # optional, directory-joined
        assert REQUIRED_ROW_FIELDS <= keys, (
            f"missing {REQUIRED_ROW_FIELDS - keys} in {row}")
        assert keys <= REQUIRED_ROW_FIELDS, (
            f"unpinned fields {keys - REQUIRED_ROW_FIELDS} in {row}")
        assert row["kind"] in ("inline", "shm", "spilled", "pending",
                               "error")
        assert row["state"] in ("owned", "pinned")
        assert row["task"] == row["oid"][:48]
    kinds = {r["kind"] for r in s["rows"]}
    assert {"inline", "shm"} <= kinds
    del ref, small


# ------------------------------------------------- put/get/del reconcile
@pytest.mark.parametrize("rt_cluster", [dict(num_cpus=2, num_nodes=2)],
                         indirect=True)
def test_two_node_reconciliation_put_get_del(rt_cluster):
    """Acceptance: on a 2-node cluster the summary reconciles — per-node
    directory-accounted bytes equal owner-accounted store bytes exactly,
    rows carry owner/creating-task/ref-state, and after every ref dies
    the directory drains to zero with zero leak candidates."""
    rt, cluster = rt_cluster
    from ray_tpu.util import state

    @rt.remote
    def make(n):
        return np.ones(n, dtype=np.uint8)

    put_ref = rt.put(np.zeros(BIG, dtype=np.uint8))
    refs = [make.remote(BIG) for _ in range(4)]
    vals = rt.get(refs, timeout=60)
    assert all(v.nbytes == BIG for v in vals)

    def settled():
        s = state.memory_summary(grace_s=0.0)
        shm_rows = [r for r in s["rows"] if r["kind"] == "shm"]
        if len(shm_rows) < 5:
            return False
        for rec in s["reconcile"].values():
            if abs(rec["delta_shm_bytes"]) > 8:  # one alignment quantum
                return False
        # fn attribution joins the task-event plane (0.25s flusher tick)
        return any(r["fn"] == "make" for r in shm_rows)

    wait_for_condition(settled, timeout=15,
                       message="directory vs owner bytes never reconciled "
                               "(or task-name join never landed)")
    s = state.memory_summary(grace_s=0.0)
    # rows attribute: owner address present on every shm row
    shm_rows = [r for r in s["rows"] if r["kind"] == "shm"]
    assert all(r["owner"] for r in shm_rows)
    assert s["leaks"] == []
    # both nodes' arenas hold live bytes (tasks spread over 2 nodes is
    # not guaranteed — but SOME node-attributed store bytes must exist)
    assert sum(rec["owner_shm_bytes"]
               for rec in s["reconcile"].values()) >= 5 * BIG

    del put_ref, refs, vals
    gc.collect()

    def drained():
        s = state.memory_summary(grace_s=0.0)
        return (s["totals"]["directory_entries"] == 0
                and s["totals"]["shm_bytes"] == 0
                and s["leaks"] == [])

    wait_for_condition(drained, timeout=15,
                       message="freed objects left directory entries")


def test_borrow_keeps_freed_owners_object_alive(rt_start):
    """Deserialize-time borrow registration (the PR-1 batch hook) must
    keep an object alive after its owner drops every local ref: the
    owner record stays pinned (borrows>0), the summary reports state
    ``pinned``, the leak detector stays silent, and the borrower can
    still read the value."""
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    @ray_tpu.remote
    class Holder:
        def keep(self, boxed):
            self.ref = boxed[0]
            return True

        def read(self):
            return ray_tpu.get(self.ref, timeout=30).nbytes

    w = get_global_worker()
    h = Holder.remote()
    big = ray_tpu.put(np.full(BIG, 7, dtype=np.uint8))
    oid = big.id().hex()
    assert ray_tpu.get(h.keep.remote([big]), timeout=30)

    def borrowed():
        rec = w.owned.get(oid)
        return rec is not None and rec["borrows"] >= 1

    wait_for_condition(borrowed, timeout=10,
                       message="borrow never registered at the owner")
    del big
    gc.collect()
    time.sleep(0.3)  # release drain window
    rec = w.owned.get(oid)
    assert rec is not None and rec["count"] <= 0 and rec["borrows"] >= 1
    s = state.memory_summary(grace_s=0.0)
    row = [r for r in s["rows"] if r["oid"] == oid]
    assert row and row[0]["state"] == "pinned"
    assert s["leaks"] == []
    assert ray_tpu.get(h.read.remote(), timeout=30) == BIG


def test_spill_transition_flips_kind_gauge(rt_start):
    """A spill must flip the object's accounting kind shm→spilled (the
    ``rt_object_store_bytes{kind}`` gauge dimension) while the value
    stays readable through the restore path."""
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    w = get_global_worker()
    a = ray_tpu.put(np.zeros(BIG, dtype=np.uint8))
    b = ray_tpu.put(np.ones(BIG, dtype=np.uint8))
    time.sleep(0.2)
    freed = w._spill_for_space(1)  # oldest sealed object(s) go to disk
    assert freed > 0

    def spilled_row():
        s = state.memory_summary(grace_s=0.0)
        return any(r["kind"] == "spilled" and r["bytes"] > 0
                   for r in s["rows"])

    wait_for_condition(spilled_row, timeout=10,
                       message="spill never flipped an accounting row")
    # gauge dimension flips with it
    memtrack.push_gauges(w)
    from ray_tpu.util.metrics import registry

    sample_kinds = {}
    for m in registry().snapshot():
        if m["name"] == "rt_object_store_bytes":
            for smp in m["samples"]:
                k = smp["tags"].get("kind")
                sample_kinds[k] = sample_kinds.get(k, 0) + smp["value"]
    assert sample_kinds.get("spilled", 0) > 0
    # restore path still serves both values
    assert ray_tpu.get(a, timeout=30).nbytes == BIG
    assert ray_tpu.get(b, timeout=30).nbytes == BIG
    del a, b


# -------------------------------------------------------- CLI contract
def test_rt_memory_cli_leaks_exit_code(rt_start, capsys):
    """``rt memory --leaks`` is a CI gate: exit 0 (and say so) when the
    directory is clean, exit 1 when a leak candidate exists."""
    from ray_tpu import cli
    from ray_tpu._private.worker import get_global_worker

    addr = ray_tpu._internal_cluster().gcs_addr
    a = f"{addr[0]}:{addr[1]}"
    live = ray_tpu.put(np.zeros(BIG, dtype=np.uint8))  # a held ref: not a leak
    time.sleep(0.3)
    cli.main(["memory", "--address", a, "--leaks", "--grace", "0"])
    out = capsys.readouterr().out
    assert "no leaked objects" in out

    w = get_global_worker()
    w.run_sync(w.gcs.call("object_register", {
        "oid": "cd" * 28,
        "meta": {"seg": "gone", "size": 64, "owner": ["10.0.0.1", 2]},
    }))
    time.sleep(0.3)
    with pytest.raises(SystemExit) as ei:
        cli.main(["memory", "--address", a, "--leaks", "--grace", "0.1"])
    assert ei.value.code == 1
    out = capsys.readouterr()
    assert "LEAK CANDIDATES" in out.out
    # --group-by aggregates instead of listing
    cli.main(["memory", "--address", a, "--group-by", "node"])
    assert "group (node)" in capsys.readouterr().out
    # --json is machine-readable
    cli.main(["memory", "--address", a, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert "rows" in data and "reconcile" in data
    del live


# --------------------------------------------------- disabled-mode parity
def test_disabled_mode_zero_overhead_paths(monkeypatch):
    """RT_MEMTRACK_ENABLED=0 mirrors the flight/taskpath gates: no meta
    enrichment, no memstat payloads anywhere in the cluster, no
    object-plane gauge samples pushed, empty summary."""
    from ray_tpu.util.metrics import registry

    def _obj_gauge_samples():
        n = 0
        for m in registry().snapshot():
            if m["name"] in ("rt_object_store_bytes", "rt_object_count"):
                n += len(m["samples"])
        return n

    before = _obj_gauge_samples()
    monkeypatch.setenv("RT_MEMTRACK_ENABLED", "0")
    memtrack.disable()
    ray_tpu.init(num_cpus=2)
    try:
        ref = ray_tpu.put(np.zeros(BIG, dtype=np.uint8))
        from ray_tpu.util import state

        def registered():
            return state.list_objects()

        wait_for_condition(lambda: len(registered()) == 1, timeout=10)
        rows = registered()
        # no enrichment on the directory meta
        assert rows[0]["meta"].get("owner") is None
        assert rows[0]["meta"].get("node") is None
        s = state.memory_summary(grace_s=0.0)
        assert s["enabled"] is False
        assert s["rows"] == [] and s["leaks"] == []
        assert _obj_gauge_samples() == before
        del ref
    finally:
        ray_tpu.shutdown()
        memtrack.enable()


# -------------------------------------------------- /metrics acceptance
def test_head_metrics_serves_object_gauges_for_every_node(monkeypatch):
    """Acceptance: ONE scrape of the head's /metrics serves
    rt_object_store_bytes{node_id,kind} and rt_object_count{node_id,
    state} covering every node of a 2-node cluster, bytes attributed to
    the node whose arena holds the segment, per-worker copies excluded."""
    ray_tpu.init(num_cpus=1, num_nodes=2)
    try:
        from ray_tpu._private.worker import get_global_worker
        from ray_tpu.dashboard import DashboardApp
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote
        def make(n):
            return np.ones(n, dtype=np.uint8)

        cluster = ray_tpu._internal_cluster()
        node_ids = {n.node_id[:12] for n in cluster.nodes}
        assert len(node_ids) == 2
        refs = [
            make.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    n.node_id
                )
            ).remote(BIG)
            for n in cluster.nodes for _ in range(2)
        ]
        vals = ray_tpu.get(refs, timeout=60)
        w = get_global_worker()
        dash = DashboardApp(cluster.head, "127.0.0.1", 0)
        port = w.run_sync(dash.start(), 30)
        try:
            def scraped():
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ) as r:
                    text = r.read().decode()
                byte_lines = [ln for ln in text.splitlines()
                              if ln.startswith("rt_object_store_bytes")]
                count_lines = [ln for ln in text.splitlines()
                               if ln.startswith("rt_object_count")]
                if not byte_lines or not count_lines:
                    return False
                # rollup series only: per-worker copies excluded
                assert all("worker_id=" not in ln
                           for ln in byte_lines + count_lines)
                covered = {
                    nid for nid in node_ids
                    if any(f'node_id="{nid}"' in ln
                           and 'kind="shm"' in ln
                           and not ln.endswith(" 0.0")
                           for ln in byte_lines)
                }
                owned = any('state="owned"' in ln
                            and not ln.endswith(" 0.0")
                            for ln in count_lines)
                ratio = any(ln.startswith("rt_node_memory_used_ratio")
                            for ln in text.splitlines())
                return covered == node_ids and owned and ratio

            # workers push metrics every ~2s
            wait_for_condition(scraped, timeout=25)
        finally:
            w.run_sync(dash.stop(), 10)
        del refs, vals
    finally:
        ray_tpu.shutdown()


# ------------------------------------------- list_objects filters/truncation
def test_list_objects_server_side_filters_and_truncation(rt_start):
    """The directory listing filters server-side and reports
    {recorded, dropped} instead of silently slicing at the limit."""
    from ray_tpu._private.worker import get_global_worker
    from ray_tpu.util import state

    refs = [ray_tpu.put(np.zeros(BIG, dtype=np.uint8)) for _ in range(3)]

    def registered():
        return len(state.list_objects()) == 3

    wait_for_condition(registered, timeout=10)
    w = get_global_worker()
    node = w.node_id

    # server-side filter: only this node's entries come back
    rows = state.list_objects(filters=[("node", "=", node)])
    assert len(rows) == 3
    assert state.list_objects(filters=[("node", "=", "nope")]) == []
    assert state.list_objects(filters=[("spilled", "=", "True")]) == []

    # honest truncation on the raw verb
    h, _ = w.run_sync(w.gcs.call("list_objects", {"limit": 2}))
    assert len(h["objects"]) == 2
    assert h["recorded"] == 3 and h["dropped"] == 1

    # unsupported ops are loud, not ignored
    with pytest.raises(Exception):
        w.run_sync(w.gcs.call("list_objects", {
            "limit": 10, "filters": [["node", "~", "x"]],
        }))
    del refs


def test_memory_monitor_used_ratio_and_import_order():
    """Satellite: the interleaved import block is gone (time is a module
    attribute at header level) and used_ratio() reports a sane
    fraction — the rt_node_memory_used_ratio gauge input."""
    import inspect

    from ray_tpu._private import memory_monitor

    src = inspect.getsource(memory_monitor)
    assert src.index("import time") < src.index("def _rt_config")
    r = memory_monitor.used_ratio()
    assert 0.0 <= r <= 1.0
