"""Framework self-analysis stays clean (Families B+C+D over ray_tpu/).

This is the tier-1 wiring for ``python -m ray_tpu.lint ray_tpu/``: a new
blocking-call-under-lock, lock-order inversion, silent RPC swallow,
constant-sleep retry loop (Family B), event-loop concurrency hazard
(Family C, tests/test_lint_concurrency.py holds the unit cases), or
wire/gate/chaos/phase catalog drift (Family D) in the framework fails
fast here, plus unit coverage for each Family-B rule on minimal
snippets.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

from ray_tpu.lint import FAMILY_FRAMEWORK, lint_paths, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_fw(src):
    return lint_source(textwrap.dedent(src), "<test>",
                       families=(FAMILY_FRAMEWORK,))


def rule_ids(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------ self-scan
def test_faultpoints_module_is_family_b_clean():
    """The injection plane itself must honor the framework rules: the
    exact CLI invocation ``raytpu lint --framework`` over faultpoints.py
    (a chaos tool that silently swallows RPC failures or constant-sleeps
    would be the most ironic Family-B regression possible)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "faultpoints.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_flight_module_is_family_b_clean():
    """The flight recorder must honor the framework rules it observes
    everyone else breaking: no blocking work under its ring lock, no
    silent except-pass on the drain/merge paths (``raytpu lint
    --framework`` over flight.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "flight.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_specframe_module_is_family_b_clean():
    """The round-10 submission-plane cache (spec templates + function
    push-through ledger) and its round-15 reply-plane siblings
    (ReplyWindow, ArgLedger, ArgInternCache) all hold locks on push/reply
    hot paths: blocking work or silent swallows under them would be
    exactly the regression Family B exists to catch (``raytpu lint
    --framework`` over specframe.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "specframe.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_taskpath_module_is_family_b_clean():
    """The round-12 task-tracing plane records on the submit/exec hot
    paths and aggregates on the head's /metrics rollup: a silent RPC
    swallow or blocking work added there would be exactly the Family-B
    regression class (``raytpu lint --framework`` over taskpath.py, the
    exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "taskpath.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_ringconn_module_is_family_b_clean():
    """The round-16 batched pump handoff runs on the ring pump thread
    and touches the connection's send lock from several threads: a
    blocking call under that lock or a silent except-pass on the
    drain/dispatch path is exactly the Family-B regression class
    (``raytpu lint --framework`` over ringconn.py, the exact CI
    invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "ringconn.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_protocol_module_is_family_b_clean():
    """The round-16 multi-frame settle drain parses wire messages
    straight off the recv loop's reader buffer: a silent swallow there
    (or a constant-sleep retry anywhere in the RPC core) would be the
    costliest Family-B regression in the tree (``raytpu lint
    --framework`` over protocol.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "protocol.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_memtrack_module_is_family_b_clean():
    """The round-13 object-accounting plane snapshots refcount state and
    talks to the head's fan-out verb: a silent RPC swallow on the drain
    path or blocking work added under a lock there is exactly the
    Family-B regression class (``raytpu lint --framework`` over
    memtrack.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "memtrack.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_devstore_module_is_family_b_clean():
    """The round-14 device-plane object store retries shard pulls and
    fans device→host copies onto executor threads: a constant-sleep
    retry loop or a silent RPC swallow on the pull path is exactly the
    Family-B regression class (``raytpu lint --framework`` over
    devstore.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "_private", "devstore.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_xla_backend_module_is_family_b_clean():
    """The registered "xla" collective backend caches jitted shard_map
    programs and falls back to host staging on mesh failure: a silent
    except-pass there would hide real lowering breakage (``raytpu lint
    --framework`` over xla_backend.py, the exact CI invocation)."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "util", "collective",
                      "collective_group", "xla_backend.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_metrics_rollup_module_is_family_b_clean():
    """util/metrics.py now carries the head-side rollup the aggregated
    /metrics endpoint serves; it holds per-metric locks on hot observe
    paths, so Family B must stay clean over it."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint",
         os.path.join(REPO, "ray_tpu", "util", "metrics.py"),
         "--framework", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(proc.stdout) == []


def test_private_tree_is_family_b_clean():
    findings = lint_paths([os.path.join(REPO, "ray_tpu", "_private")])
    fam_b = [f for f in findings if f.rule.startswith("RT2")]
    assert fam_b == [], "\n".join(f.format() for f in fam_b)


def test_serve_tree_is_family_b_clean():
    """The serve plane is framework code under production traffic: its
    router holds a lock on the request hot path, its controller/proxies
    speak RPC constantly — a blocking call under the router lock, a
    silent except-pass on a reply path, or a constant-sleep re-resolve
    loop there is exactly the Family-B regression class. ``serve/`` is a
    framework path for the linter (base._is_framework_path), so the
    plain tier-1 CLI scan covers it too; this pins it explicitly."""
    findings = lint_paths([os.path.join(REPO, "ray_tpu", "serve")])
    fam_b = [f for f in findings if f.rule.startswith("RT2")]
    assert fam_b == [], "\n".join(f.format() for f in fam_b)


def test_cli_module_scan_json_clean():
    """The exact tier-1 invocation: ``python -m ray_tpu.lint ray_tpu/``
    with --json for dashboard ingestion; Family B must be silent."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", "ray_tpu", "--json",
         "--select", "RT2"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    findings = json.loads(proc.stdout)
    assert findings == [], proc.stdout
    assert proc.returncode == 0, proc.stderr


def test_full_tree_families_bcd_clean():
    """Lint v2 self-scan, the exact ``scripts/lint_check.sh``
    invocation: Families B (locks), C (concurrency) and D (wire/gate/
    chaos/phase invariants vs lint/catalog.py) over the WHOLE tree with
    --framework. A new blocking call in a coroutine, a fire-and-forget
    create_task, a wire flag whose receiver branch was refactored away,
    an un-matrixed faultpoint, or a phase name the analyzer doesn't
    know — any of these fails tier-1 right here."""
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", "ray_tpu", "--framework",
         "--select", "RT2,RT3,RT4", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=240,
    )
    findings = json.loads(proc.stdout)
    assert findings == [], "\n".join(
        f"{f['file']}:{f['line']}: {f['rule']} {f['message']}"
        for f in findings
    )
    assert proc.returncode == 0, proc.stderr


def test_lint_check_script_in_sync():
    """scripts/lint_check.sh is the CI entry point for the scan the
    test above just ran — pin that it invokes the SAME families over
    the SAME tree (running it twice in tier-1 would only burn wall
    clock re-proving the identical result)."""
    script = os.path.join(REPO, "scripts", "lint_check.sh")
    with open(script) as f:
        body = f.read()
    assert ("python -m ray_tpu.lint ray_tpu --framework "
            "--select RT2,RT3,RT4") in body
    assert os.access(script, os.X_OK)


def test_catalog_in_sync_with_tree():
    """``--regen`` on a clean tree is a no-op — i.e. lint/catalog.py was
    regenerated after the last faultpoint/gate/phase change."""
    from ray_tpu.lint import catalog_gen

    assert catalog_gen.regen(root=REPO, write=False) is False, (
        "lint/catalog.py is stale: run `python -m ray_tpu.lint --regen` "
        "and commit the diff"
    )


def test_cli_reports_seeded_finding(tmp_path):
    bad = tmp_path / "bad_module.py"
    bad.write_text(textwrap.dedent("""
        import time

        def loop(stop):
            while not stop():
                time.sleep(1.0)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.lint", str(bad), "--framework",
         "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert [f["rule"] for f in findings] == ["RT204"]
    assert findings[0]["line"] == 6


# ---------------------------------------------------------------- RT201
def test_rt201_sleep_under_lock_flagged():
    findings = lint_fw("""
        import threading
        import time

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def evict(self):
                with self._lock:
                    time.sleep(0.1)
    """)
    assert "RT201" in rule_ids(findings)
    assert "self._lock" in findings[0].message


def test_rt201_socket_recv_under_lock_flagged():
    findings = lint_fw("""
        class Conn:
            def read(self):
                with self._lock:
                    return self.sock.recv(4096)
    """)
    assert "RT201" in rule_ids(findings)


def test_rt201_clean_outside_critical_section():
    findings = lint_fw("""
        import time

        class Store:
            def evict(self):
                with self._lock:
                    victims = list(self._entries)
                time.sleep(0.1)
                return victims
    """)
    assert "RT201" not in rule_ids(findings)


def test_rt201_nested_def_under_lock_not_flagged():
    findings = lint_fw("""
        import time

        class Pool:
            def submit(self):
                with self._lock:
                    def later():
                        time.sleep(1.0)  # runs on the executor, lock-free
                    self._queue.append(later)
    """)
    assert "RT201" not in rule_ids(findings)


# ---------------------------------------------------------------- RT202
def test_rt202_lock_order_inversion_flagged():
    findings = lint_fw("""
        class Broker:
            def push(self):
                with self._a_lock:
                    with self._b_lock:
                        self._q.append(1)

            def drain(self):
                with self._b_lock:
                    with self._a_lock:
                        return self._q.pop()
    """)
    assert "RT202" in rule_ids(findings)
    assert "inversion" in findings[0].message


def test_rt202_reacquire_flagged():
    findings = lint_fw("""
        class Broker:
            def push(self):
                with self._lock:
                    with self._lock:
                        pass
    """)
    assert "RT202" in rule_ids(findings)
    assert "re-acquired" in findings[0].message


def test_rt202_consistent_order_clean():
    findings = lint_fw("""
        class Broker:
            def push(self):
                with self._a_lock:
                    with self._b_lock:
                        self._q.append(1)

            def drain(self):
                with self._a_lock:
                    with self._b_lock:
                        return self._q.pop()
    """)
    assert "RT202" not in rule_ids(findings)


def test_rt202_same_names_in_different_classes_clean():
    """Each class has its own self._lock instance — no cross-class edges."""
    findings = lint_fw("""
        class A:
            def f(self):
                with self._x_lock:
                    with self._y_lock:
                        pass

        class B:
            def g(self):
                with self._y_lock:
                    with self._x_lock:
                        pass
    """)
    assert "RT202" not in rule_ids(findings)


# ---------------------------------------------------------------- RT203
def test_rt203_swallowed_rpc_error_flagged():
    findings = lint_fw("""
        from ray_tpu._private import protocol

        class Client:
            def fire(self):
                try:
                    self.conn.notify("object_free", {})
                except protocol.ConnectionLost:
                    pass
    """)
    assert "RT203" in rule_ids(findings)


def test_rt203_logged_handler_clean():
    findings = lint_fw("""
        from ray_tpu._private import protocol

        class Client:
            def fire(self):
                try:
                    self.conn.notify("object_free", {})
                except protocol.ConnectionLost as e:
                    logger.debug("notify dropped: %s", e)
    """)
    assert "RT203" not in rule_ids(findings)


def test_rt203_non_rpc_pass_clean():
    findings = lint_fw("""
        import os

        def cleanup(path):
            try:
                os.remove(path)
            except OSError:
                pass
    """)
    assert "RT203" not in rule_ids(findings)


# ---------------------------------------------------------------- RT204
def test_rt204_constant_sleep_in_retry_loop_flagged():
    findings = lint_fw("""
        import time

        def wait_for(cond):
            while not cond():
                time.sleep(0.5)
    """)
    assert "RT204" in rule_ids(findings)
    assert "backoff" in findings[0].message.lower()


def test_rt204_backoff_helper_clean():
    findings = lint_fw("""
        from ray_tpu._private.backoff import Backoff

        def wait_for(cond):
            poll = Backoff(base=0.05, cap=0.5)
            while not cond():
                poll.sleep()
    """)
    assert "RT204" not in rule_ids(findings)


# ----------------------------------------------------- backoff satellite
def test_backoff_jittered_and_capped():
    from ray_tpu._private.backoff import Backoff

    slept = []
    rands = iter([0.0, 1.0, 0.5, 0.0, 0.0, 0.0])
    b = Backoff(base=1.0, cap=4.0, jitter=0.5,
                rand=lambda: next(rands), sleep=slept.append)
    assert b.sleep() == 1.0          # rand=0 -> no jitter removed
    assert b.sleep() == 1.0          # 2.0 * (1 - 0.5*1.0)
    assert b.sleep() == 3.0          # 4.0 * (1 - 0.5*0.5)
    assert b.sleep() == 4.0          # capped
    b.reset()
    assert b.sleep() == 1.0          # back to base
    assert slept == [1.0, 1.0, 3.0, 4.0, 1.0]


def test_backoff_rejects_bad_params():
    from ray_tpu._private.backoff import Backoff

    with pytest.raises(ValueError):
        Backoff(base=0)
    with pytest.raises(ValueError):
        Backoff(base=2.0, cap=1.0)


def test_backoff_never_overflows():
    """The exponent must stop growing at the cap: factor**n overflows a
    float after ~1k attempts, which would kill a long-lived poll thread
    (the pressure killer ticks ~forever on a calm node)."""
    from ray_tpu._private.backoff import Backoff

    b = Backoff(base=1.0, cap=4.0, jitter=0.0, sleep=lambda _d: None)
    for _ in range(5000):
        assert 0 < b.next_delay() <= 4.0
