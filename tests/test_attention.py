"""Attention kernel + sequence parallelism tests (8-device CPU mesh)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention_xla, flash_attention
from ray_tpu.parallel.mesh import MeshConfig
from ray_tpu.parallel.ring_attention import ring_attention, ulysses_attention
from jax.sharding import NamedSharding, PartitionSpec as P


def _make_qkv(B=2, T=128, H=4, D=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype)
    k = jax.random.normal(ks[1], (B, T, H, D), dtype)
    v = jax.random.normal(ks[2], (B, T, H, D), dtype)
    return q, k, v


def test_flash_matches_xla_causal():
    q, k, v = _make_qkv()
    ref = attention_xla(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, 64, 64, True)  # interpret mode
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_matches_xla_noncausal():
    q, k, v = _make_qkv(T=64)
    ref = attention_xla(q, k, v, causal=False)
    out = flash_attention(q, k, v, False, 32, 32, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_grad_matches_xla():
    q, k, v = _make_qkv(T=64)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, 32, 32, True).sum()

    def loss_xla(q, k, v):
        return attention_xla(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    mesh = MeshConfig(data=1, seq=4).build(jax.devices()[:4])
    q, k, v = _make_qkv(B=2, T=128, H=4, D=16)
    spec = P(None, "seq", None, None)
    sharding = NamedSharding(mesh, spec)
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh=mesh, axis="seq", causal=causal,
                         qkv_spec=spec)
    ref = attention_xla(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_grads():
    mesh = MeshConfig(data=1, seq=4).build(jax.devices()[:4])
    q, k, v = _make_qkv(B=1, T=64, H=2, D=8)
    spec = P(None, "seq", None, None)

    def loss_ring(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, axis="seq", causal=True,
                              qkv_spec=spec).sum()

    def loss_ref(q, k, v):
        return attention_xla(q, k, v, causal=True).sum()

    g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)


def test_ulysses_matches_dense():
    mesh = MeshConfig(data=1, seq=4).build(jax.devices()[:4])
    q, k, v = _make_qkv(B=2, T=128, H=4, D=16)
    spec = P(None, "seq", None, None)
    out = ulysses_attention(q, k, v, mesh=mesh, axis="seq", causal=True,
                            qkv_spec=spec)
    ref = attention_xla(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_xla():
    q, _, _ = _make_qkv(H=8)
    _, k, v = _make_qkv(H=2, seed=1)
    out = attention_xla(q, k, v, causal=True)
    assert out.shape == q.shape


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T", [96, 1000])
def test_flash_ragged_seq_len(causal, T):
    """Seq lengths not divisible by the block size (regression: the kernel's
    clamped dynamic slice silently re-read earlier K rows)."""
    q, k, v = _make_qkv(B=1, T=T, H=2, D=16)
    out = flash_attention(q, k, v, causal, 64, 64, True)
    ref = attention_xla(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_bwd_kernel_gqa_and_ragged(causal):
    """Pallas backward kernel: GQA head-group reduction + pad-row masking
    (q rows past seq end must contribute nothing to dk/dv)."""
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 4)
    B, T, H, Hkv, D = 1, 100, 4, 2, 32
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    g = jax.random.normal(ks[3], (B, T, H, D), jnp.float32)

    def loss_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, causal, 64, 64, True), g)

    def loss_xla(q, k, v):
        return jnp.vdot(attention_xla(q, k, v, causal=causal), g)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)
