"""TPU accelerator manager + slice placement groups.

Reference analog: ``python/ray/tests/accelerators/test_tpu.py`` (metadata
lookups patched) and ``python/ray/tests/test_tpu.py`` slice-PG coverage.
"""
import pytest

import ray_tpu
from ray_tpu._private.accelerators import (
    TPUAcceleratorManager,
    detect_node_accelerators,
    detect_node_labels,
)
from ray_tpu._private.accelerators import tpu as tpu_mod
from ray_tpu.util.tpu import (
    get_tpu_coordinator_env_vars,
    slice_placement_group,
)


@pytest.fixture(autouse=True)
def _no_gce(monkeypatch):
    monkeypatch.setattr(tpu_mod, "_fetch_metadata", lambda *a, **k: None)
    for var in ("TPU_ACCELERATOR_TYPE", "ACCELERATOR_TYPE", "TPU_WORKER_ID",
                "TPU_CHIPS_PER_HOST_BOUNDS", "TPU_NAME", "TPU_TOPOLOGY"):
        monkeypatch.delenv(var, raising=False)


def test_no_tpu_detected():
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 0
    assert detect_node_accelerators() == {}
    assert detect_node_labels() == {}


def test_detection_from_env(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-16")
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_NAME", "my-slice")
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 4
    res = detect_node_accelerators()
    assert res["TPU"] == 4.0
    assert res["TPU-v5e-16-head"] == 1.0  # worker 0 carries the head token
    labels = detect_node_labels()
    assert labels["ray_tpu.accelerator_type"] == "v5e-16"
    assert labels["ray_tpu.slice_name"] == "my-slice"


def test_non_head_worker_has_no_head_resource(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-16")
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,2,1")
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    res = detect_node_accelerators()
    assert res["TPU"] == 4.0
    assert "TPU-v5e-16-head" not in res


def test_single_host_slice_from_type(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    # 8 chips, v5e packs 8/host -> single host owns the whole slice
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 8


def test_visibility_env():
    env = {}
    TPUAcceleratorManager.set_visible_accelerators(["0", "1"], env)
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    # multi-chip grants keep default bounds (physical grid must win)
    assert "TPU_CHIPS_PER_PROCESS_BOUNDS" not in env
    solo = {}
    TPUAcceleratorManager.set_visible_accelerators(["2"], solo)
    assert solo["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"


def test_coordinator_env_vars():
    env = get_tpu_coordinator_env_vars("10.0.0.1:8080", 4, 2)
    assert env == {
        "MEGASCALE_COORDINATOR_ADDRESS": "10.0.0.1:8080",
        "MEGASCALE_NUM_SLICES": "4",
        "MEGASCALE_SLICE_ID": "2",
    }


def test_slice_placement_group_reserves_hosts():
    """v5e-16 = 2 hosts x 8 chips; the PG lands only when both hosts exist
    and the head token pins host 0."""
    ray_tpu.init(num_cpus=2)
    try:
        cluster = ray_tpu._internal_cluster()
        cluster.add_node({"CPU": 1, "TPU": 8, "TPU-v5e-16-head": 1})
        cluster.add_node({"CPU": 1, "TPU": 8})
        cluster.wait_for_nodes(3)
        spg = slice_placement_group("v5e-16")
        assert spg.ready(timeout=30)
        assert spg.num_workers == 2
        assert spg.chips_per_host == 8
        r0 = spg.worker_resources(0)
        assert r0["TPU"] == 8.0 and "TPU-v5e-16-head" in r0
        r1 = spg.worker_resources(1)
        assert r1 == {"TPU": 8.0}
    finally:
        ray_tpu.shutdown()


def test_slice_placement_group_never_split():
    """Slice atomicity: while one SlicePlacementGroup holds a slice, a
    second group can neither take the slice's head token nor poach its
    non-head hosts — it stays pending until the first group releases
    (reference behavior: ``util/tpu.py`` head-resource reservation)."""
    from ray_tpu.util.placement_group import remove_placement_group

    ray_tpu.init(num_cpus=2)
    try:
        cluster = ray_tpu._internal_cluster()
        cluster.add_node({"CPU": 1, "TPU": 8, "TPU-v5e-16-head": 1})
        cluster.add_node({"CPU": 1, "TPU": 8})
        cluster.wait_for_nodes(3)
        spg1 = slice_placement_group("v5e-16")
        assert spg1.ready(timeout=30)
        # The whole slice (head token on host 0 + every host's chips) is
        # reserved: a second slice group must not place anywhere.
        spg2 = slice_placement_group("v5e-16", timeout=2)
        assert not spg2.ready(timeout=3)
        # Release slice 1 -> the pending group takes the whole slice.
        remove_placement_group(spg1.placement_group)
        assert spg2.ready(timeout=30)
    finally:
        ray_tpu.shutdown()


def test_slice_placement_group_unsatisfiable():
    ray_tpu.init(num_cpus=2)
    try:
        spg = slice_placement_group("v5e-16", timeout=2)
        assert not spg.ready(timeout=2)
    finally:
        ray_tpu.shutdown()


def test_slice_placement_group_bad_type():
    with pytest.raises(ValueError, match="v5e-16"):
        slice_placement_group("v5e")


def test_chips_per_host_from_live_nodes():
    """A 4-host x 4-chip v5e-16 (differs from the generation table's 8)
    must be reserved with the observed per-host chip count."""
    ray_tpu.init(num_cpus=2)
    try:
        cluster = ray_tpu._internal_cluster()
        cluster.add_node({"CPU": 1, "TPU": 4, "TPU-v5e-16-head": 1},
                         labels={"ray_tpu.accelerator_type": "v5e-16"})
        for _ in range(3):
            cluster.add_node({"CPU": 1, "TPU": 4},
                             labels={"ray_tpu.accelerator_type": "v5e-16"})
        cluster.wait_for_nodes(5)
        spg = slice_placement_group("v5e-16")
        assert spg.chips_per_host == 4
        assert spg.num_workers == 4
        assert spg.ready(timeout=30)
    finally:
        ray_tpu.shutdown()


def test_init_autodetects_tpu_resources(monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    ray_tpu.init(num_cpus=2)
    try:
        total = ray_tpu.cluster_resources()
        assert total.get("TPU") == 8.0
        assert total.get("TPU-v5e-8-head") == 1.0
    finally:
        ray_tpu.shutdown()
