"""Serve layer tests (reference test model: ``python/ray/serve/tests``)."""
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def srv(rt_start):
    yield rt_start
    serve.shutdown()


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_deploy_and_call(srv):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return {"echo": x}

        def shout(self, x):
            return str(x).upper()

    handle = serve.run(Echo.bind(), name="echo_app")
    assert handle.remote(41).result(timeout=30) == {"echo": 41}
    assert handle.shout.remote("hi").result(timeout=30) == "HI"
    st = serve.status()
    assert st["Echo"]["running"] == 2


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_function_deployment_and_requests_spread(srv):
    import os

    @serve.deployment(num_replicas=2)
    def pid_of(x):
        import threading

        return f"{os.getpid()}:{id(threading.current_thread())}"

    handle = serve.run(pid_of.bind(), name="fn_app")
    outs = {handle.remote(i).result(timeout=30) for i in range(8)}
    assert len(outs) >= 1  # routed successfully (spread depends on timing)


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_composition_handles(srv):
    @serve.deployment
    class Adder:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Chain:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.remote(x).result(timeout=30) * 10

    handle = serve.run(Chain.bind(Adder.bind()), name="chain")
    assert handle.remote(4).result(timeout=30) == 50


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_batching(srv):
    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 2 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="batched")
    resps = [handle.remote(i) for i in range(8)]
    assert [r.result(timeout=30) for r in resps] == [i * 2 for i in range(8)]
    sizes = handle.sizes.remote().result(timeout=30)
    assert max(sizes) > 1, f"no dynamic batching happened: {sizes}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_autoscaling_scales_up(srv):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.1,
        ),
        num_replicas=1,
    )
    class Slow:
        async def __call__(self, x):
            import asyncio

            await asyncio.sleep(0.5)
            return x

    handle = serve.run(Slow.bind(), name="slow")
    resps = [handle.remote(i) for i in range(8)]  # queue depth >> target
    deadline = time.time() + 20
    scaled = False
    while time.time() < deadline:
        if serve.status()["Slow"]["running"] > 1:
            scaled = True
            break
        time.sleep(0.2)
    for r in resps:
        r.result(timeout=60)
    assert scaled, f"autoscaler never scaled up: {serve.status()}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_replica_death_recovers(srv):
    @serve.deployment(num_replicas=2)
    class Fragile:
        def __call__(self, x):
            return x

        def die(self):
            import os

            os._exit(1)  # kills the hosting worker process

    handle = serve.run(Fragile.bind(), name="fragile")
    assert handle.remote(1).result(timeout=30) == 1
    st = serve.status()
    assert st["Fragile"]["running"] == 2
    # controller reconcile loop should restore the target count
    deadline = time.time() + 30
    while time.time() < deadline:
        if serve.status()["Fragile"]["running"] >= 2:
            break
        time.sleep(0.2)
    assert serve.status()["Fragile"]["running"] >= 1


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_http_proxy(srv):
    import json
    import urllib.request

    @serve.deployment
    class Api:
        def __call__(self, request):
            q = request["query"]
            return {"path": request["path"], "x": int(q.get("x", 0)) * 2}

    serve.run(Api.bind(), name="api", route_prefix="/api")
    port = serve.start_http_proxy(port=0)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/predict?x=21", timeout=30
    ) as resp:
        out = json.loads(resp.read())
    assert out == {"path": "/api/predict", "x": 42}
    # unknown route → 404
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=30
        )
    assert ei.value.code == 404


def test_gang_scheduled_deployment(srv):
    """gang_size>1: one replica = a placement-group gang of actors; rank 0
    serves, every member gets a GangContext (reference: serve/gang.py)."""
    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, gang_size=2,
                      ray_actor_options={"num_cpus": 1})
    class GangModel:
        def __init__(self):
            from ray_tpu.serve import get_gang_context

            self.ctx = get_gang_context()

        def __call__(self, x):
            return {
                "rank": self.ctx.rank,
                "world_size": self.ctx.world_size,
                "value": x * 2,
            }

    h = serve.run(GangModel.bind(), name="gang_app")
    try:
        out = h.remote(21).result(timeout=60)
        assert out == {"rank": 0, "world_size": 2, "value": 42}
        # both gang members exist as replica actors under one pg
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        pgs = w.run_sync(w.gcs.call("list_pgs", {}))[0]["pgs"]
        created = [p for p in pgs if p["state"] == "CREATED"]
        assert any(len(p["bundles"]) == 2 for p in created)
    finally:
        serve.shutdown()


def test_gang_member_death_recycles_whole_gang(srv):
    """Death of ANY gang member must tear down and replace the whole gang
    (scale-as-a-unit; reference: gang autoscaling semantics)."""
    import time

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=1, gang_size=2,
                      ray_actor_options={"num_cpus": 1})
    class G:
        def __call__(self, x):
            return x

    h = serve.run(G.bind(), name="gang_ft")
    try:
        assert h.remote(1).result(timeout=60) == 1
        from ray_tpu.serve.controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        st = ray_tpu.get(controller.status.remote(), timeout=30)
        assert st["G"]["running"] == 1
        handles = ray_tpu.get(controller.get_handles.remote("G"), timeout=30)
        # kill the rank-1 member behind the controller's back: fetch the
        # full member list via replica state
        reps = ray_tpu.get(controller.get_replicas.remote("G"), timeout=30)
        assert len(reps) == 1
        # rank-0 handle is what get_handles returns; kill it to simulate
        # member death (any member death must recycle the gang)
        ray_tpu.kill(handles[0])
        deadline = time.time() + 60
        while time.time() < deadline:
            st = ray_tpu.get(controller.status.remote(), timeout=30)
            if st.get("G", {}).get("running", 0) >= 1:
                try:
                    if h.remote(2).result(timeout=10) == 2:
                        break
                except Exception:
                    pass
            time.sleep(0.3)
        assert h.remote(3).result(timeout=30) == 3
    finally:
        serve.shutdown()


def test_local_testing_mode_no_cluster():
    """serve.run(..., local_testing_mode=True) runs the graph in-process —
    no init(), no actors (reference: local_testing_mode.py)."""
    from ray_tpu import serve

    @serve.deployment(user_config={"suffix": "!"})
    class Shouter:
        def __init__(self, downstream=None):
            self.suffix = ""
            self.downstream = downstream

        def reconfigure(self, cfg):
            self.suffix = cfg["suffix"]

        def __call__(self, text):
            if self.downstream is not None:
                text = self.downstream.remote(text).result()
            return text.upper() + self.suffix

        def whisper(self, text):
            return text.lower()

    @serve.deployment(name="inner")
    class Inner:
        def __call__(self, text):
            return f"<{text}>"

    h = serve.run(Shouter.bind(Inner.bind()), local_testing_mode=True)
    assert h.remote("hey").result() == "<HEY>!"
    assert h.whisper.remote("LOUD").result() == "loud"


def test_grpc_proxy_unary(srv):
    """gRPC ingress shares the router with HTTP (reference: dual-protocol
    ProxyActor, serve/_private/proxy.py:11). Unary Predict + status codes."""
    import grpc
    import msgpack

    @serve.deployment
    class Api:
        def __call__(self, data):
            return {"doubled": data["x"] * 2}

        def extra(self, data):
            return {"method": "extra", "x": data["x"]}

    serve.run(Api.bind(), name="gapi", route_prefix="/gapi")
    port = serve.start_grpc_proxy(port=0)

    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    predict = chan.unary_unary(
        "/rayserve.v1.RayServe/Predict",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    out = msgpack.unpackb(predict(
        msgpack.packb({"route": "/gapi", "data": {"x": 21}},
                      use_bin_type=True), timeout=60,
    ), raw=False)
    assert out == {"doubled": 42}

    # named-method dispatch
    out = msgpack.unpackb(predict(
        msgpack.packb({"route": "/gapi", "method": "extra",
                       "data": {"x": 7}}, use_bin_type=True), timeout=60,
    ), raw=False)
    assert out == {"method": "extra", "x": 7}

    # unknown route -> NOT_FOUND
    with pytest.raises(grpc.RpcError) as ei:
        predict(msgpack.packb({"route": "/nope", "data": None},
                              use_bin_type=True), timeout=60)
    assert ei.value.code() == grpc.StatusCode.NOT_FOUND

    # user error -> INTERNAL
    with pytest.raises(grpc.RpcError) as ei:
        predict(msgpack.packb({"route": "/gapi", "data": {}},
                              use_bin_type=True), timeout=60)
    assert ei.value.code() == grpc.StatusCode.INTERNAL
    chan.close()


def test_grpc_proxy_streaming(srv):
    """Server-streaming over a generator deployment."""
    import grpc
    import msgpack

    @serve.deployment
    class Gen:
        def __call__(self, data):
            for i in range(int(data["n"])):
                yield {"i": i}

    serve.run(Gen.bind(), name="ggen", route_prefix="/ggen")
    port = serve.start_grpc_proxy(port=0)

    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    stream = chan.unary_stream(
        "/rayserve.v1.RayServe/PredictStream",
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b,
    )
    chunks = [
        msgpack.unpackb(c, raw=False)
        for c in stream(
            msgpack.packb({"route": "/ggen", "data": {"n": 4}},
                          use_bin_type=True), timeout=60,
        )
    ]
    assert chunks == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3}]
    chan.close()


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_max_queued_requests_sheds_load(srv):
    """Handle-side load shedding (reference: Serve max_queued_requests ->
    BackPressureError / HTTP 503): once the in-flight cap is reached,
    further submissions fail fast instead of queueing unboundedly."""
    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=2)
    class Slow:
        def __call__(self, x):
            time.sleep(3)
            return x

    handle = serve.run(Slow.bind(), name="slow_app")
    admitted = [handle.remote(i) for i in range(2)]
    with pytest.raises(serve.BackPressureError, match="max_queued"):
        for i in range(10):  # cap must trip within the window
            admitted.append(handle.remote(100 + i))
    # The admitted requests still complete: shedding, not failure.
    assert admitted[0].result(timeout=30) == 0


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_replica_change_push_invalidates_handles(srv):
    """Scaling a deployment pushes a replica-change message (long-poll
    fan-out analog); handles re-fetch on the NEXT call instead of waiting
    out the slow poll interval."""
    @serve.deployment(num_replicas=1)
    def f(x):
        return x

    handle = serve.run(f.bind(), name="scale_app")
    assert handle.remote(1).result(timeout=30) == 1
    router = handle._router
    assert len(router._replicas) == 1
    # Scale 1 -> 3; the push must invalidate well before the 5s poll.
    serve.run(f.options(num_replicas=3).bind(), name="scale_app")
    deadline = time.monotonic() + 4.0
    while time.monotonic() < deadline and len(router._replicas) < 3:
        handle.remote(2).result(timeout=30)  # pick() applies invalidation
        time.sleep(0.1)
    assert len(router._replicas) == 3, "push invalidation never landed"
