"""Serve layer tests (reference test model: ``python/ray/serve/tests``)."""
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def srv(rt_start):
    yield rt_start
    serve.shutdown()


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_deploy_and_call(srv):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return {"echo": x}

        def shout(self, x):
            return str(x).upper()

    handle = serve.run(Echo.bind(), name="echo_app")
    assert handle.remote(41).result(timeout=30) == {"echo": 41}
    assert handle.shout.remote("hi").result(timeout=30) == "HI"
    st = serve.status()
    assert st["Echo"]["running"] == 2


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_function_deployment_and_requests_spread(srv):
    import os

    @serve.deployment(num_replicas=2)
    def pid_of(x):
        import threading

        return f"{os.getpid()}:{id(threading.current_thread())}"

    handle = serve.run(pid_of.bind(), name="fn_app")
    outs = {handle.remote(i).result(timeout=30) for i in range(8)}
    assert len(outs) >= 1  # routed successfully (spread depends on timing)


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_composition_handles(srv):
    @serve.deployment
    class Adder:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Chain:
        def __init__(self, adder):
            self.adder = adder

        def __call__(self, x):
            return self.adder.remote(x).result(timeout=30) * 10

    handle = serve.run(Chain.bind(Adder.bind()), name="chain")
    assert handle.remote(4).result(timeout=30) == 50


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_batching(srv):
    @serve.deployment
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        async def handle_batch(self, items):
            self.batch_sizes.append(len(items))
            return [i * 2 for i in items]

        async def __call__(self, x):
            return await self.handle_batch(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind(), name="batched")
    resps = [handle.remote(i) for i in range(8)]
    assert [r.result(timeout=30) for r in resps] == [i * 2 for i in range(8)]
    sizes = handle.sizes.remote().result(timeout=30)
    assert max(sizes) > 1, f"no dynamic batching happened: {sizes}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_autoscaling_scales_up(srv):
    @serve.deployment(
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.1,
        ),
        num_replicas=1,
    )
    class Slow:
        async def __call__(self, x):
            import asyncio

            await asyncio.sleep(0.5)
            return x

    handle = serve.run(Slow.bind(), name="slow")
    resps = [handle.remote(i) for i in range(8)]  # queue depth >> target
    deadline = time.time() + 20
    scaled = False
    while time.time() < deadline:
        if serve.status()["Slow"]["running"] > 1:
            scaled = True
            break
        time.sleep(0.2)
    for r in resps:
        r.result(timeout=60)
    assert scaled, f"autoscaler never scaled up: {serve.status()}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_replica_death_recovers(srv):
    @serve.deployment(num_replicas=2)
    class Fragile:
        def __call__(self, x):
            return x

        def die(self):
            import os

            os._exit(1)  # kills the hosting worker process

    handle = serve.run(Fragile.bind(), name="fragile")
    assert handle.remote(1).result(timeout=30) == 1
    st = serve.status()
    assert st["Fragile"]["running"] == 2
    # controller reconcile loop should restore the target count
    deadline = time.time() + 30
    while time.time() < deadline:
        if serve.status()["Fragile"]["running"] >= 2:
            break
        time.sleep(0.2)
    assert serve.status()["Fragile"]["running"] >= 1


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_http_proxy(srv):
    import json
    import urllib.request

    @serve.deployment
    class Api:
        def __call__(self, request):
            q = request["query"]
            return {"path": request["path"], "x": int(q.get("x", 0)) * 2}

    serve.run(Api.bind(), name="api", route_prefix="/api")
    port = serve.start_http_proxy(port=0)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/api/predict?x=21", timeout=30
    ) as resp:
        out = json.loads(resp.read())
    assert out == {"path": "/api/predict", "x": 42}
    # unknown route → 404
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=30
        )
    assert ei.value.code == 404
