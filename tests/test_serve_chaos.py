"""Serve-plane request-lifecycle fault tolerance (reference test model:
``python/ray/serve/tests/test_replica_*``, ``test_proxy*``).

The contract under test (ISSUE 6 tentpole):

- a request that fails BEFORE reaching user code fails over transparently
  to another replica (bounded, jittered);
- a replica dying mid-execution / mid-stream surfaces a TYPED retryable
  error (``serve.ReplicaDiedError``) — never a hang, never a bare
  transport exception;
- graceful drain: scale-down lets in-flight requests finish (zero
  dropped);
- proxy admission control: global in-flight cap -> 503 + Retry-After,
  request deadline -> 504 + Retry-After, app errors stay 500;
- router accounting: no stranded in-flight counts after replicas die or
  the set refreshes (power-of-2 routing stays honest).
"""
import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private import faultpoints as fp
from ray_tpu._private.test_utils import wait_for_condition


@pytest.fixture(autouse=True)
def _clean_faults():
    fp.clear()
    yield
    fp.clear()


@pytest.fixture
def srv(rt_start):
    yield rt_start
    serve.shutdown()


def _replica_handles(name):
    from ray_tpu.serve.controller import CONTROLLER_NAME

    controller = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(controller.get_handles.remote(name), timeout=30)


def _leases_settled():
    cluster = ray_tpu._internal_cluster()
    return all(
        all(n.available.get(k, 0.0) >= v - 1e-9
            for k, v in n.resources.items())
        for n in cluster.head.nodes.values() if n.alive
    )


def _zero_stranded(router):
    snap = router.inflight_snapshot()
    return sum(snap.values()) == 0, snap


def _no_leaked_objects():
    """Zero leaked objects (memtrack plane SLO, same contract as the
    core chaos matrix): no orphaned directory entries past grace."""
    from ray_tpu.util import state

    return state.memory_summary(grace_s=1.0)["leaks"] == []


# ------------------------------------------------- pre-dispatch failover
@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_failover_before_user_code_is_transparent(srv):
    """An injected transport failure at handle->replica dispatch (request
    never reached user code) retries on another replica invisibly."""
    @serve.deployment(num_replicas=2)
    def echo(x):
        return x * 2

    handle = serve.run(echo.bind(), name="fo_app")
    assert handle.remote(1).result(timeout=30) == 2  # replicas warm
    fp.configure("serve.replica.call:error:1.0:2:21")
    assert handle.remote(21).result(timeout=30) == 42
    assert fp.stats()[0]["injected"] == 2
    fp.clear()
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"stranded in-flight counts after failover: {snap}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_failover_budget_exhausted_raises_typed_retryable(srv):
    @serve.deployment(num_replicas=2)
    def echo(x):
        return x

    handle = serve.run(echo.bind(), name="fo_exhaust")
    assert handle.remote(0).result(timeout=30) == 0
    fp.configure("serve.replica.call:error:1.0:0:22")  # every dispatch
    with pytest.raises(serve.ReplicaDiedError) as ei:
        handle.remote(1)
    assert isinstance(ei.value, serve.ServeRetryableError)
    assert ei.value.retryable
    fp.clear()
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"stranded in-flight counts after exhausted failover: {snap}"


# --------------------------------------------- mid-execution replica death
@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_mid_execution_death_surfaces_typed_error_and_evicts(
        srv, monkeypatch):
    """A replica killed while executing must fail the request with the
    typed retryable class (not a raw ActorDiedError), evict the dead
    replica, and strand no router counts."""
    # Short reply deadline: the caller notices the kill at the next
    # re-arm probe instead of 30s later.
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")

    @serve.deployment(num_replicas=1)
    class Slow:
        def __call__(self, x):
            time.sleep(float(x))
            return x

    handle = serve.run(Slow.bind(), name="mid_death")
    victims = _replica_handles("Slow")
    assert len(victims) == 1
    resp = handle.remote(30)  # parks inside user code
    time.sleep(0.5)
    ray_tpu.kill(victims[0])
    with pytest.raises(serve.ReplicaDiedError) as ei:
        resp.result(timeout=60)
    assert ei.value.retryable
    assert ei.value.__cause__ is not None  # original infra error chained
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"replica death stranded router counts: {snap}"
    # the reconcile loop replaces the dead replica; new requests succeed
    deadline = time.time() + 45
    while time.time() < deadline:
        try:
            assert handle.remote(0).result(timeout=30) == 0
            break
        except serve.ServeRetryableError:
            time.sleep(0.2)
    else:
        pytest.fail("deployment never recovered after replica death")


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_stream_replica_crash_mid_stream_terminal_typed_error(
        srv, monkeypatch):
    """A replica dying with an OPEN stream: the consumer sees a typed
    terminal error promptly (no hang until the 300s chunk deadline), and
    no router count is stranded."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")

    @serve.deployment(num_replicas=1)
    class Gen:
        def __call__(self, req):
            for i in range(1000):
                time.sleep(0.01)
                yield f"c{i}"

    handle = serve.run(Gen.bind(), name="stream_crash")
    victims = _replica_handles("Gen")
    it = iter(handle.options(stream=True).remote({}))
    got = [next(it) for _ in range(20)]  # at least one pull round-trip
    assert got[0] == "c0"
    ray_tpu.kill(victims[0])
    t0 = time.monotonic()
    with pytest.raises(serve.ReplicaDiedError):
        for _ in range(2000):
            next(it)
    assert time.monotonic() - t0 < 90, "mid-stream death hung the consumer"
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"mid-stream death stranded router counts: {snap}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_injected_stream_fault_is_typed(srv):
    """serve.replica.stream faultpoint: an injected mid-stream transport
    error surfaces as the typed retryable class."""
    @serve.deployment(num_replicas=1)
    class Gen:
        def __call__(self, req):
            for i in range(64):
                yield i

    handle = serve.run(Gen.bind(), name="stream_fault")
    it = iter(handle.options(stream=True).remote({}))
    assert next(it) == 0
    fp.configure("serve.replica.stream:error:1.0:0:23")
    # buffered chunks drain first; the next PULL hits the fault
    with pytest.raises(serve.ReplicaDiedError):
        while True:
            next(it)
    fp.clear()


# ------------------------------------------------------- router accounting
@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_inflight_counts_survive_refresh_and_reach_zero(srv):
    """Regression: router keys must be stable replica identities, not
    id(handle) — a refresh used to zero every count (handles are new
    objects per fetch), blinding power-of-2 routing; a dead replica used
    to strand its counts forever."""
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Slow:
        def __call__(self, x):
            time.sleep(1.5)
            return x

    handle = serve.run(Slow.bind(), name="acct")
    resps = [handle.remote(i) for i in range(4)]
    router = handle._router
    assert sum(router.inflight_snapshot().values()) == 4
    router._refresh(force=True)
    assert sum(router.inflight_snapshot().values()) == 4, (
        "refresh wiped live in-flight counts (unstable router keys)"
    )
    assert [r.result(timeout=30) for r in resps] == list(range(4))
    ok, snap = _zero_stranded(router)
    assert ok, f"counts failed to settle: {snap}"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_abandoned_response_settles_router_slot(srv):
    """A fire-and-forget handle call (response dropped without result())
    must not strand its in-flight slot once the response is GC'd."""
    @serve.deployment(num_replicas=1)
    def f(x):
        return x

    handle = serve.run(f.bind(), name="abandon")
    resp = handle.remote(1)
    router = handle._router
    assert sum(router.inflight_snapshot().values()) == 1
    del resp
    import gc

    gc.collect()
    ok, snap = _zero_stranded(router)
    assert ok, f"abandoned response stranded a slot: {snap}"


# ----------------------------------------------------------- graceful drain
@pytest.mark.parametrize(
    "rt_start", [{"num_cpus": 8}], indirect=True)
def test_graceful_drain_zero_dropped_on_scale_down(srv):
    """Scale 3 -> 1 with a burst in flight: every request completes
    (drained replicas finish their work before stopping), and the
    deployment converges to the new target with nothing draining."""
    @serve.deployment(num_replicas=3, max_ongoing_requests=4)
    class Work:
        def __call__(self, x):
            time.sleep(0.3)
            return x

    handle = serve.run(Work.bind(), name="drain_app")
    results = {}
    errors = []

    def one(i):
        try:
            results[i] = handle.remote(i).result(timeout=60)
        except Exception as e:  # noqa: BLE001 - recorded for the assert
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(24)]
    for t in threads[:12]:
        t.start()
    time.sleep(0.15)  # burst mid-flight on all 3 replicas
    serve.run(Work.options(num_replicas=1).bind(), name="drain_app")
    for t in threads[12:]:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, f"scale-down dropped requests: {errors[:3]}"
    assert results == {i: i for i in range(24)}

    def converged():
        st = serve.status()["Work"]
        return st["running"] == 1 and st["draining"] == 0

    wait_for_condition(converged, timeout=60,
                       message=f"drain never converged: {serve.status()}")
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"drain stranded router counts: {snap}"


@pytest.mark.parametrize(
    "rt_start",
    [{"num_cpus": 8, "_system_config": {"serve_drain_deadline_s": 1.0}}],
    indirect=True)
def test_drain_deadline_cuts_wedged_replica(srv):
    """A replica that can't finish by the drain deadline is cut: teardown
    never waits forever on a wedged request."""
    @serve.deployment(num_replicas=1)
    class Stuck:
        def __call__(self, x):
            time.sleep(120)
            return x

    handle = serve.run(Stuck.bind(), name="stuck_app")
    resp = handle.remote(1)  # occupies the replica forever
    time.sleep(0.3)
    serve.delete("stuck_app")
    wait_for_condition(
        lambda: "Stuck" not in serve.status(), timeout=30,
        message=f"drain deadline did not cut the replica: {serve.status()}",
    )
    with pytest.raises((serve.ServeRetryableError, ray_tpu.exceptions.RayTpuError)):
        resp.result(timeout=30)


# -------------------------------------------------- proxy admission control
@pytest.mark.parametrize(
    "rt_start",
    [{"num_cpus": 8, "_system_config": {"serve_max_inflight": 1}}],
    indirect=True)
def test_proxy_inflight_cap_sheds_with_503_retry_after(srv):
    import urllib.error
    import urllib.request

    @serve.deployment(num_replicas=1, max_ongoing_requests=4)
    class Slow:
        def __call__(self, req):
            time.sleep(2.0)
            return {"ok": True}

    serve.run(Slow.bind(), name="cap_app", route_prefix="/cap")
    port = serve.start_http_proxy(port=0)
    codes = []

    def hit():
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/cap", timeout=30
            ) as r:
                codes.append((r.status, dict(r.headers)))
        except urllib.error.HTTPError as e:
            codes.append((e.code, dict(e.headers)))

    threads = [threading.Thread(target=hit) for _ in range(3)]
    for t in threads:
        t.start()
        time.sleep(0.15)  # first request is parked in user code
    for t in threads:
        t.join(timeout=60)
    by_code = {}
    for code, headers in codes:
        by_code.setdefault(code, []).append(headers)
    assert 200 in by_code, f"no request succeeded: {by_code}"
    assert 503 in by_code, f"cap=1 never shed load: {by_code}"
    assert all("Retry-After" in h for h in by_code[503]), (
        f"shed without Retry-After: {by_code[503]}"
    )


@pytest.mark.parametrize(
    "rt_start",
    [{"num_cpus": 8, "_system_config": {"serve_request_timeout_s": 0.5}}],
    indirect=True)
def test_proxy_deadline_maps_to_504_and_app_error_to_500(srv):
    import urllib.error
    import urllib.request

    @serve.deployment(num_replicas=1)
    class Api:
        def __call__(self, req):
            if req["query"].get("boom"):
                raise ValueError("app exploded")
            time.sleep(3)
            return {"ok": True}

    serve.run(Api.bind(), name="dl_app", route_prefix="/dl")
    port = serve.start_http_proxy(port=0)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"http://127.0.0.1:{port}/dl", timeout=30)
    assert ei.value.code == 504, "deadline must be 504, not 500"
    assert ei.value.headers.get("Retry-After"), "504 without Retry-After"
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/dl?boom=1", timeout=30
        )
    assert ei.value.code == 500, "application errors stay 500"


# --------------------------------------------- SSE client-disconnect cleanup
@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_sse_client_disconnect_cancels_replica_generator(srv):
    """A client dropping an open SSE stream must release the replica-side
    generator and its slot promptly (cancel_stream), not leak it until
    the 10-minute idle sweep."""
    import http.client

    @serve.deployment(num_replicas=1)
    class Stream:
        def __call__(self, req):
            for i in range(2000):
                time.sleep(0.02)
                yield f"data: {i}\n\n"

    serve.run(Stream.bind(), name="sse_app", route_prefix="/sse")
    port = serve.start_http_proxy(port=0)
    replica = _replica_handles("Stream")[0]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/sse", body=json.dumps({"stream": True}))
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.read(16)  # stream is live on the replica
    assert ray_tpu.get(replica.stats.remote(), timeout=10)["streams"] == 1
    # client vanishes mid-stream: SHUT_RDWR forces the FIN out even while
    # the response file object still references the socket, so the
    # proxy's next writes get RST instead of landing in a zombie buffer
    import socket as socketmod

    conn.sock.shutdown(socketmod.SHUT_RDWR)
    conn.sock.close()
    wait_for_condition(
        lambda: ray_tpu.get(
            replica.stats.remote(), timeout=10)["streams"] == 0,
        timeout=30,
        message="client disconnect leaked the replica-side stream slot",
    )


@pytest.mark.parametrize(
    "rt_start",
    [{"num_cpus": 8, "_system_config": {"rpc_deadline_s": 2.0}}],
    indirect=True)
def test_sse_mid_stream_replica_crash_emits_terminal_error_event(srv):
    """HTTP SSE + replica crash mid-stream: the client receives a typed
    terminal ``event: error`` frame marked retryable — not a silent
    truncation, not a hang. (_system_config shortens the PROXY process's
    reply deadline so it notices the kill promptly.)"""
    import http.client

    @serve.deployment(num_replicas=1)
    class Stream:
        def __call__(self, req):
            for i in range(2000):
                time.sleep(0.02)
                yield f"data: {i}\n\n"

    serve.run(Stream.bind(), name="sse_crash", route_prefix="/ssec")
    port = serve.start_http_proxy(port=0)
    replica = _replica_handles("Stream")[0]
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", "/ssec", body=json.dumps({"stream": True}))
    resp = conn.getresponse()
    assert resp.read(16)
    ray_tpu.kill(replica)
    rest = resp.read()  # proxy must terminate the stream promptly
    conn.close()
    assert b"event: error" in rest, (
        f"no terminal error event after replica crash: ...{rest[-200:]!r}"
    )
    frame = json.loads(
        rest.split(b"event: error\ndata: ", 1)[1].split(b"\n", 1)[0]
    )
    assert frame["retryable"] is True
    assert frame["error"] == "ReplicaDiedError"


# ------------------------------------------------------------ chaos matrix
@pytest.mark.slow
def test_serve_chaos_matrix_mixed_faults_and_crash(monkeypatch,
                                                   chaos_flight_trace):
    """The serve request lifecycle under sustained 10% faults at the new
    serve.* points PLUS a replica crash mid-stream: every request ends in
    success or a typed retryable error (no hangs, no raw transport
    errors), zero leaked leases, zero stranded router counts. A failure
    dumps the joined flight + task-track trace (chaos_flight_trace)."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")
    ray_tpu.init(num_cpus=4)
    try:
        @serve.deployment(num_replicas=3, max_ongoing_requests=8)
        class App:
            def __call__(self, x):
                time.sleep(0.05)
                return x * 2

            def gen(self, n):
                for i in range(int(n)):
                    time.sleep(0.02)
                    yield i

        handle = serve.run(App.bind(), name="chaos_app")
        assert handle.remote(1).result(timeout=60) == 2
        fp.configure(
            "serve.replica.call:error:0.1:0:201,"
            "serve.replica.stream:error:0.1:0:202"
        )
        outcomes = []

        def unary(i):
            try:
                outcomes.append(("ok", handle.remote(i).result(timeout=60)))
            except serve.ServeRetryableError as e:
                outcomes.append(("retryable", e))
            except Exception as e:  # noqa: BLE001 - the assert below flags it
                outcomes.append(("BAD", e))

        def stream(i):
            try:
                got = list(handle.options(stream=True).gen.remote(40))
                outcomes.append(("ok", len(got)))
            except serve.ServeRetryableError as e:
                outcomes.append(("retryable", e))
            except Exception as e:  # noqa: BLE001
                outcomes.append(("BAD", e))

        threads = (
            [threading.Thread(target=unary, args=(i,)) for i in range(30)]
            + [threading.Thread(target=stream, args=(i,)) for i in range(6)]
        )
        for t in threads:
            t.start()
        time.sleep(0.4)  # streams + unary in flight everywhere
        ray_tpu.kill(_replica_handles("App")[0])  # crash mid-stream
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), (
            "a request hung under chaos"
        )
        bad = [o for o in outcomes if o[0] == "BAD"]
        assert not bad, (
            f"untyped failures under chaos: "
            f"{[(type(e).__name__, str(e)[:120]) for _, e in bad[:4]]}"
        )
        assert sum(s["calls"] for s in fp.stats()) > 0
        fp.clear()
        ok, snap = _zero_stranded(handle._router)
        assert ok, f"chaos stranded router counts: {snap}"
        serve.shutdown()  # releases replica leases
        wait_for_condition(_leases_settled, timeout=30,
                           message="serve chaos leaked leases")
        wait_for_condition(_no_leaked_objects, timeout=20,
                           message="serve chaos leaked objects")
    finally:
        fp.clear()
        ray_tpu.shutdown()


def test_serve_chaos_smoke(srv):
    """Fast tier-1 slice: one injected dispatch fault (transparent
    failover) + one injected stream fault (typed terminal error) in a
    single app."""
    @serve.deployment(num_replicas=2)
    class App:
        def __call__(self, x):
            return x + 1

        def gen(self, n):
            for i in range(int(n)):
                yield i

    handle = serve.run(App.bind(), name="chaos_smoke")
    assert handle.remote(1).result(timeout=30) == 2
    fp.configure("serve.replica.call:error:1.0:1:31")
    assert handle.remote(2).result(timeout=30) == 3  # failed over
    assert fp.stats()[0]["injected"] == 1
    fp.clear()
    it = iter(handle.options(stream=True).gen.remote(64))
    assert next(it) == 0
    fp.configure("serve.replica.stream:error:1.0:0:32")
    with pytest.raises(serve.ReplicaDiedError):
        while True:
            next(it)
    fp.clear()
    ok, snap = _zero_stranded(handle._router)
    assert ok, f"smoke stranded router counts: {snap}"
