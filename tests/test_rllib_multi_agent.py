"""Multi-agent episodes: per-policy learners over one env (reference:
``rllib/env/multi_agent_env_runner.py`` + multi_agent config)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.multi_agent import MultiAgentPPOConfig


@pytest.fixture
def rl_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class TwoGuessersEnv:
    """Two agents; each sees its private target bit (+noise) and earns +1
    for guessing it. agent 'b' terminates halfway — exercising per-agent
    done masking."""

    possible_agents = ["a", "b"]

    def __init__(self):
        import gymnasium as gym

        self._obs_space = gym.spaces.Box(-1.0, 2.0, (2,), np.float32)
        self._act_space = gym.spaces.Discrete(2)
        self._rng = np.random.RandomState(0)
        self.t = 0

    def observation_space(self, agent):
        return self._obs_space

    def action_space(self, agent):
        return self._act_space

    def _obs(self):
        return {
            a: np.array(
                [self.targets[a], self._rng.rand() * 0.1], np.float32
            )
            for a in self.possible_agents
        }

    def reset(self, seed=None):
        self._rng = np.random.RandomState(seed or 0)
        self.targets = {
            a: float(self._rng.randint(0, 2)) for a in self.possible_agents
        }
        self.t = 0
        return self._obs(), {}

    def step(self, actions):
        self.t += 1
        rews = {
            a: float(actions.get(a, -1) == self.targets[a])
            for a in self.possible_agents
        }
        terms = {a: False for a in self.possible_agents}
        truncs = {a: False for a in self.possible_agents}
        terms["b"] = self.t >= 10  # b leaves early
        done_all = self.t >= 20
        terms["__all__"] = done_all
        truncs["__all__"] = False
        # re-randomize targets so the policy must read the observation
        self.targets = {
            a: float(self._rng.randint(0, 2)) for a in self.possible_agents
        }
        return self._obs(), rews, terms, truncs, {}


def test_multi_agent_ppo_learns_per_policy(rl_cluster):
    cfg = (MultiAgentPPOConfig()
           .environment(env_creator=TwoGuessersEnv)
           .env_runners(num_env_runners=2, rollout_fragment_length=40)
           .multi_agent(
               policies=["pa", "pb"],
               policy_mapping_fn=lambda agent: f"p{agent}",
           )
           .debugging(seed=0))
    algo = cfg.build_algo()
    try:
        first, last = None, None
        for _ in range(40):
            r = algo.train()
            assert np.isfinite(r["total_loss"])
            assert "pa/policy_loss" in r and "pb/policy_loss" in r
            if first is None and r["num_episodes"] > 0:
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
            # max return: a earns up to 20, b up to 10 -> 30
            if last >= 24:
                break
        assert last is not None and last >= 18, (
            f"multi-agent PPO did not learn: {first} -> {last}"
        )
    finally:
        algo.stop()


def test_shared_policy_mapping(rl_cluster):
    cfg = (MultiAgentPPOConfig()
           .environment(env_creator=TwoGuessersEnv)
           .env_runners(num_env_runners=1, rollout_fragment_length=20)
           .multi_agent(
               policies=["shared"],
               policy_mapping_fn=lambda agent: "shared",
           )
           .debugging(seed=1))
    algo = cfg.build_algo()
    try:
        r = algo.train()
        assert "shared/policy_loss" in r
        w = algo.get_policy_weights("shared")
        assert w is not None
    finally:
        algo.stop()


def test_multi_agent_save_restore(rl_cluster, tmp_path):
    cfg = (MultiAgentPPOConfig()
           .environment(env_creator=TwoGuessersEnv)
           .env_runners(num_env_runners=1, rollout_fragment_length=20)
           .multi_agent(policies=["shared"],
                        policy_mapping_fn=lambda a: "shared")
           .debugging(seed=2))
    algo = cfg.build_algo()
    try:
        algo.train()
        path = algo.save(str(tmp_path / "ckpt"))
        w_before = algo.get_policy_weights("shared")
        algo.train()
        algo.restore(path)
        w_after = algo.get_policy_weights("shared")
        import jax

        for a, b in zip(jax.tree.leaves(w_before), jax.tree.leaves(w_after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        algo.stop()
