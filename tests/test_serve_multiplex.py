"""Serve model multiplexing + response streaming.

Reference analogs: ``python/ray/serve/multiplex.py`` (per-replica model LRU,
model-aware routing, ``get_multiplexed_model_id``) and streaming
DeploymentResponses over generator deployments.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    serve.shutdown()
    ray_tpu.shutdown()


@serve.deployment(num_replicas=1)
class MultiModel:
    def __init__(self):
        self.loads = []

    @serve.multiplexed(max_num_models_per_replica=2)
    async def get_model(self, model_id: str):
        self.loads.append(model_id)
        return {"name": model_id, "pid": os.getpid()}

    async def __call__(self, x):
        model_id = serve.get_multiplexed_model_id()
        model = await self.get_model(model_id)
        return {
            "model": model["name"],
            "ctx_model_id": model_id,
            "loads": list(self.loads),
            "pid": os.getpid(),
            "x": x,
        }


def test_multiplexed_lru_and_context(serve_cluster):
    handle = serve.run(MultiModel.bind(), name="mux")
    out = handle.options(multiplexed_model_id="m1").remote(1).result()
    assert out["model"] == "m1"
    assert out["ctx_model_id"] == "m1"
    assert out["loads"] == ["m1"]
    # same model again: served from cache, no reload
    out = handle.options(multiplexed_model_id="m1").remote(2).result()
    assert out["loads"] == ["m1"]
    # second model fits (max 2)
    out = handle.options(multiplexed_model_id="m2").remote(3).result()
    assert out["loads"] == ["m1", "m2"]
    # third model evicts the LRU (m1); re-requesting m1 reloads it
    handle.options(multiplexed_model_id="m3").remote(4).result()
    out = handle.options(multiplexed_model_id="m1").remote(5).result()
    assert out["loads"] == ["m1", "m2", "m3", "m1"]
    serve.delete("mux")


def test_router_prefers_model_holder(serve_cluster):
    handle = serve.run(
        MultiModel.options(num_replicas=2).bind(), name="mux2"
    )
    # Warm one replica with m7, then let the router learn the mapping.
    first = handle.options(multiplexed_model_id="m7").remote(0).result()
    time.sleep(1.3)  # > router refresh interval
    pids = set()
    for i in range(8):
        out = handle.options(multiplexed_model_id="m7").remote(i).result()
        pids.add(out["pid"])
        assert out["loads"].count("m7") == 1  # never reloaded anywhere
    assert pids == {first["pid"]}, "requests did not stick to the holder"
    serve.delete("mux2")


@serve.deployment(num_replicas=1)
class MuxStreamer:
    """Multiplexing + streaming combined: the generator body must still see
    the request's model id (it runs under next_chunks, not handle_request)."""

    @serve.multiplexed(max_num_models_per_replica=2)
    async def get_model(self, model_id: str):
        return model_id.upper()

    async def tokens(self, n: int):
        model = await self.get_model(serve.get_multiplexed_model_id())
        for i in range(n):
            yield f"{model}:{i}"


def test_streaming_sees_multiplexed_model_id(serve_cluster):
    handle = serve.run(MuxStreamer.bind(), name="muxstream")
    it = (
        handle.options(multiplexed_model_id="mA", stream=True)
        .tokens.remote(3)
        .result()
    )
    assert list(it) == ["MA:0", "MA:1", "MA:2"]
    serve.delete("muxstream")


@serve.deployment
class Streamer:
    def stream_sync(self, n: int):
        for i in range(n):
            yield {"i": i}

    async def stream_async(self, n: int):
        for i in range(n):
            yield i * 10


def test_streaming_sync_generator(serve_cluster):
    handle = serve.run(Streamer.bind(), name="streamer")
    it = handle.options(stream=True).stream_sync.remote(40).result()
    assert [c["i"] for c in it] == list(range(40))
    serve.delete("streamer")


def test_streaming_async_generator(serve_cluster):
    handle = serve.run(Streamer.bind(), name="streamer2")
    # async generators stream implicitly (no other way to return)
    out = handle.stream_async.remote(5).result()
    assert list(out) == [0, 10, 20, 30, 40]
    serve.delete("streamer2")
