"""GPT-2 model + SPMD train step tests on the 8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt2
from ray_tpu.parallel.mesh import MeshConfig
from ray_tpu.parallel.moe import MoEConfig, init_moe_params, moe_layer
from ray_tpu.train.step import OptimizerConfig, create_train_state, make_train_step

CFG = gpt2.GPT2_TINY


def _batch(B=4, T=64, seed=0, vocab=CFG.vocab_size):
    rng = np.random.RandomState(seed)
    return {"tokens": jnp.asarray(rng.randint(0, vocab, (B, T + 1)))}


def test_forward_shapes():
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    tokens = _batch()["tokens"][:, :-1]
    logits, aux = gpt2.forward(params, tokens, CFG)
    assert logits.shape == (4, 64, CFG.vocab_size)
    assert float(aux) == 0.0


def test_loss_decreases_single_device():
    opt = OptimizerConfig(learning_rate=1e-2, warmup_steps=1, total_steps=50).build()
    state = create_train_state(CFG, opt, jax.random.PRNGKey(0))
    step = make_train_step(CFG, opt)
    batch = _batch()
    first = None
    for i in range(10):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first, (first, float(metrics["loss"]))


@pytest.mark.parametrize("mesh_cfg", [
    MeshConfig(data=8),                      # pure DP
    MeshConfig(data=2, fsdp=2, tensor=2),    # DP x FSDP x TP
    MeshConfig(data=1, fsdp=4, tensor=2),    # ZeRO x TP
])
def test_spmd_train_step(mesh_cfg):
    mesh = mesh_cfg.build()
    opt = OptimizerConfig(learning_rate=1e-3).build()
    state = create_train_state(CFG, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(CFG, opt, mesh)
    batch = _batch(B=8)
    batch = jax.device_put(
        batch, {"tokens": NamedSharding(mesh, P(("data", "fsdp"), None))}
    )
    state, m1 = step(state, batch)
    state, m2 = step(state, batch)
    assert np.isfinite(float(m2["loss"]))
    assert float(m2["loss"]) < float(m1["loss"]) + 1.0


def test_spmd_matches_single_device():
    """Sharded and unsharded training must produce the same losses."""
    opt = OptimizerConfig(learning_rate=1e-3).build()
    batch = _batch(B=8)

    state1 = create_train_state(CFG, opt, jax.random.PRNGKey(0))
    step1 = make_train_step(CFG, opt, donate=False)
    losses1 = []
    for _ in range(3):
        state1, m = step1(state1, batch)
        losses1.append(float(m["loss"]))

    mesh = MeshConfig(data=2, fsdp=2, tensor=2).build()
    state2 = create_train_state(CFG, opt, jax.random.PRNGKey(0), mesh)
    step2 = make_train_step(CFG, opt, mesh, donate=False)
    losses2 = []
    for _ in range(3):
        state2, m = step2(state2, batch)
        losses2.append(float(m["loss"]))
    np.testing.assert_allclose(losses1, losses2, rtol=2e-3)


def test_seq_parallel_ring_model():
    mesh = MeshConfig(data=2, seq=4).build()
    cfg = gpt2.GPT2Config(
        vocab_size=512, max_seq_len=128, num_layers=2, num_heads=2,
        embed_dim=64, attention_impl="ring", dtype=jnp.float32,
    )
    params = gpt2.init_params(cfg, jax.random.PRNGKey(1))
    tokens = _batch(B=4, T=64, vocab=512)["tokens"][:, :-1]
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", "seq")))
    logits, _ = jax.jit(
        lambda p, t: gpt2.forward(p, t, cfg, mesh)
    )(params, tokens)
    # must match the dense path
    cfg_dense = gpt2.GPT2Config(
        vocab_size=512, max_seq_len=128, num_layers=2, num_heads=2,
        embed_dim=64, attention_impl="xla", dtype=jnp.float32,
    )
    ref, _ = gpt2.forward(params, jax.device_put(tokens), cfg_dense)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref), atol=3e-4, rtol=3e-4
    )


def test_pipeline_forward_matches_sequential():
    mesh = MeshConfig(data=2, stage=4).build()
    cfg = gpt2.GPT2Config(
        vocab_size=512, max_seq_len=128, num_layers=4, num_heads=2,
        embed_dim=64, attention_impl="xla", dtype=jnp.float32, remat=False,
    )
    params = gpt2.init_params(cfg, jax.random.PRNGKey(2))
    tokens = _batch(B=8, T=32, vocab=512)["tokens"][:, :-1]
    ref, _ = gpt2.forward(params, tokens, cfg)
    out, _ = jax.jit(
        lambda p, t: gpt2.forward_pipelined(p, t, cfg, mesh, num_microbatches=4)
    )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4
    )


def test_moe_layer_routing():
    cfg = MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0)
    params = init_moe_params(jax.random.PRNGKey(0), 32, 64, cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    out, aux = moe_layer(params, x, cfg)
    assert out.shape == x.shape
    assert float(aux) > 0


def test_moe_model_ep_sharded():
    mesh = MeshConfig(data=2, expert=4).build()
    cfg = gpt2.GPT2Config(
        vocab_size=512, max_seq_len=128, num_layers=2, num_heads=2,
        embed_dim=64, attention_impl="xla", dtype=jnp.float32,
        moe=MoEConfig(num_experts=4, top_k=2),
    )
    opt = OptimizerConfig().build()
    state = create_train_state(cfg, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(cfg, opt, mesh)
    batch = _batch(B=4, T=64, vocab=512)
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_moe_with_pipeline_parallelism():
    """MoE + pipeline: the router aux loss survives the microbatch loop
    (pipeline_apply(collect_aux=True)) instead of being dropped."""
    mesh = MeshConfig(data=2, stage=2, expert=2).build()
    cfg = gpt2.GPT2Config(
        vocab_size=512, max_seq_len=128, num_layers=4, num_heads=2,
        embed_dim=64, attention_impl="xla", dtype=jnp.float32, remat=False,
        moe=MoEConfig(num_experts=4, top_k=2),
    )
    params = gpt2.init_params(cfg, jax.random.PRNGKey(2))
    tokens = _batch(B=8, T=32, vocab=512)["tokens"][:, :-1]
    logits, aux = jax.jit(
        lambda p, t: gpt2.forward_pipelined(p, t, cfg, mesh,
                                            num_microbatches=2)
    )(params, tokens)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0, "pipelined MoE must report a router aux loss"
    # and the full train step composes
    opt = OptimizerConfig().build()
    state = create_train_state(cfg, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(cfg, opt, mesh, pipeline_microbatches=2)
    batch = _batch(B=8, T=64, vocab=512)
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_moe_dropless_routing_matches_topk():
    """Dropless mode: every token reaches its top-k experts; output is a
    convex combination of expert outputs (no capacity drops)."""
    from dataclasses import replace

    cfg = MoEConfig(num_experts=4, top_k=2, dropless=True)
    params = init_moe_params(jax.random.PRNGKey(0), 32, 64, cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
    out, aux = moe_layer(params, x, cfg)
    assert out.shape == x.shape and np.isfinite(np.asarray(out)).all()
    # with generous capacity, capacity routing converges to dropless
    cfg_cap = replace(cfg, dropless=False, capacity_factor=100.0)
    out_cap, _ = moe_layer(params, x, cfg_cap)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out_cap), atol=1e-4, rtol=1e-4
    )


def test_chunked_xent_matches_naive():
    """Vocab-chunked cross entropy (no [B,T,V] materialization) must equal
    the naive log_softmax loss, values and gradients."""
    from ray_tpu.ops.xent import chunked_softmax_xent

    rng = jax.random.PRNGKey(0)
    B, T, E, V = 2, 48, 16, 97
    x = jax.random.normal(rng, (B, T, E), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (V, E), jnp.float32) * 0.1
    t = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, V)

    def naive(x, w):
        logits = jnp.einsum("bte,ve->btv", x, w)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.take_along_axis(logp, t[..., None], -1)[..., 0].mean()

    def chunked(x, w):
        return chunked_softmax_xent(x, w, t, chunk=16)

    np.testing.assert_allclose(
        np.asarray(chunked(x, w)), np.asarray(naive(x, w)), rtol=1e-5
    )
    g1 = jax.grad(naive, argnums=(0, 1))(x, w)
    g2 = jax.grad(chunked, argnums=(0, 1))(x, w)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    # masked variant
    m = (jnp.arange(T)[None, :] < 30).astype(jnp.float32) * jnp.ones((B, 1))

    def naive_m(x, w):
        logits = jnp.einsum("bte,ve->btv", x, w)
        logp = jax.nn.log_softmax(logits, -1)
        ll = jnp.take_along_axis(logp, t[..., None], -1)[..., 0]
        return -(ll * m).sum() / m.sum()

    np.testing.assert_allclose(
        np.asarray(chunked_softmax_xent(x, w, t, mask=m, chunk=16)),
        np.asarray(naive_m(x, w)), rtol=1e-5,
    )


def test_loss_fn_chunked_matches_logits_path():
    """gpt2/llama loss_fn (now feature+chunked) must match the explicit
    logits-based computation."""
    from ray_tpu.models import llama

    for mod, cfg in (
        (gpt2, gpt2.GPT2Config(
            vocab_size=512, max_seq_len=64, num_layers=2, num_heads=2,
            embed_dim=64, dtype=jnp.float32, attention_impl="xla",
        )),
        (llama, llama.LlamaConfig(
            vocab_size=512, max_seq_len=64, num_layers=2, num_heads=4,
            num_kv_heads=2, embed_dim=64, dtype=jnp.float32,
            attention_impl="xla",
        )),
    ):
        params = mod.init_params(cfg, jax.random.PRNGKey(0))
        batch = _batch(B=2, T=32, vocab=512)
        loss = float(mod.loss_fn(params, batch, cfg))
        logits, aux = mod.forward(params, batch["tokens"][:, :-1], cfg)
        logp = jax.nn.log_softmax(logits, -1)
        tgt = batch["tokens"][:, 1:]
        ref = float(
            -jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0].mean()
            + aux
        )
        assert abs(loss - ref) < 1e-4, (mod.__name__, loss, ref)
