"""Head WAL durability: mutations survive a hard kill between snapshots.

Reference analog: GCS fault tolerance via the Redis store
(``src/ray/gcs/store_client/redis_store_client.cc``) — per-mutation
durability, not snapshot-timer durability. The head appends durable-table
mutations (KV, jobs) to a generational WAL (``_private/wal.py``); restart
replays snapshot + WAL.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest


def test_wal_record_roundtrip_and_torn_tail(tmp_path):
    from ray_tpu._private.wal import WalWriter, replay_all, replay_file

    prefix = str(tmp_path / "head.wal")
    w = WalWriter(prefix)
    w.append({"op": "kv_put", "ns": "a", "key": "k1", "val": b"v1"})
    w.append({"op": "kv_del", "ns": "a", "key": "k0"})
    w.close()
    ops = list(replay_all(prefix))
    assert [o["op"] for o in ops] == ["kv_put", "kv_del"]
    assert ops[0]["val"] == b"v1"

    # torn tail: truncate mid-record — earlier records still replay
    path = prefix + ".00000000"
    size = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\x99\x99\x99\x99partial")
    full = list(replay_file(path))
    assert len(full) == 2  # corrupt tail dropped, intact prefix kept


def test_wal_rotation_deletes_old_generations(tmp_path):
    from ray_tpu._private.wal import WalWriter, existing_generations, replay_all

    prefix = str(tmp_path / "head.wal")
    w = WalWriter(prefix)
    w.append({"op": "kv_put", "ns": "a", "key": "k", "val": b"1"})
    old = w.rotate()
    w.append({"op": "kv_put", "ns": "a", "key": "k2", "val": b"2"})
    assert existing_generations(prefix) == [0, 1]
    w.delete_through(old)
    assert existing_generations(prefix) == [1]
    assert [o["key"] for o in replay_all(prefix)] == ["k2"]
    w.close()


@pytest.mark.parametrize("clean", [False])
def test_head_kv_survives_hard_kill_via_wal(tmp_path, clean, monkeypatch):
    """SIGKILL the head BEFORE any snapshot tick (interval = 1h): restart
    must recover KV purely from the WAL."""
    state_file = str(tmp_path / "head_state.bin")
    # fixed token shared by both head incarnations and this client (the
    # test skips the 0600 address file that normally distributes it)
    monkeypatch.setenv("RT_AUTH_TOKEN", "waltest" * 4)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["RT_AUTH_TOKEN"] = "waltest" * 4

    def start_head():
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.head_main",
             "--state-file", state_file,
             "--state-save-interval", "3600", "--no-address-file"],
            stdout=subprocess.PIPE, text=True, env=env, cwd="/root/repo",
        )
        return proc, json.loads(proc.stdout.readline().strip())

    from ray_tpu._private.sync_client import SyncHeadClient

    proc, info = start_head()
    try:
        client = SyncHeadClient(info["address"])
        client.call("kv_put", {"ns": "user", "key": "alpha"},
                    frames=[b"value-1"])
        client.call("kv_put", {"ns": "user", "key": "beta"},
                    frames=[b"value-2"])
        client.call("kv_del", {"ns": "user", "key": "alpha"})
        # fsync is coalesced off-loop; give it a beat
        time.sleep(0.5)
        client.close()
    finally:
        proc.send_signal(signal.SIGKILL)  # crash: no shutdown snapshot
        proc.wait(timeout=10)

    assert not os.path.exists(state_file)  # no snapshot ever written
    proc, info = start_head()
    try:
        client = SyncHeadClient(info["address"])
        h, frames = client.call("kv_get", {"ns": "user", "key": "beta"})
        assert h["found"] and frames[0] == b"value-2"
        h, _ = client.call("kv_get", {"ns": "user", "key": "alpha"})
        assert not h["found"]  # the delete replayed too
        client.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)
