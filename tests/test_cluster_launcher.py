"""Cluster launcher: YAML → head + autoscaler + min_workers (reference:
``ray up``/``ray down`` in scripts.py + autoscaler commands)."""
import json
import os
import subprocess
import sys
import time

import pytest

from ray_tpu.autoscaler import launcher


@pytest.fixture
def state_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("RT_CLUSTER_STATE_DIR", str(tmp_path / "state"))
    yield tmp_path


def _write_yaml(tmp_path, name="ltest", min_workers=1):
    p = tmp_path / "cluster.yaml"
    p.write_text(f"""
cluster_name: {name}
provider:
  type: local
head:
  num_cpus: 2
node_types:
  worker:
    resources: {{CPU: 2}}
    min_workers: {min_workers}
    max_workers: 4
idle_timeout_s: 300
""")
    return str(p)


def test_yaml_validation(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("provider: {type: local}\n")
    with pytest.raises(ValueError, match="cluster_name"):
        launcher.load_cluster_config(str(bad))
    bad.write_text("cluster_name: x\nnode_types: {w: {min_workers: 1}}\n")
    with pytest.raises(ValueError, match="resources"):
        launcher.load_cluster_config(str(bad))


def test_up_launches_min_workers_then_down(state_dir, tmp_path):
    path = _write_yaml(tmp_path, min_workers=1)
    state = launcher.up(path, wait_for_min_workers=60)
    try:
        assert launcher.cluster_state("ltest")["address"] == state["address"]
        # head reachable; min_workers registered
        from ray_tpu._private.sync_client import SyncHeadClient

        client = SyncHeadClient(state["address"])
        h, _ = client.call("get_nodes", {})
        client.close()
        alive = [n for n in h["nodes"] if n.get("alive")]
        assert len(alive) >= 1, h["nodes"]
        # double-up refuses while running
        with pytest.raises(RuntimeError, match="already running"):
            launcher.up(path)
        # a driver can connect and run work
        import ray_tpu

        ray_tpu.init(address=state["address"])

        @ray_tpu.remote
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
        ray_tpu.shutdown()
    finally:
        assert launcher.down(path)
    assert launcher.cluster_state("ltest") is None
    # processes actually gone
    for key in ("head_pid", "monitor_pid"):
        assert not launcher._pid_alive(state[key])


def test_cli_up_down(state_dir, tmp_path):
    path = _write_yaml(tmp_path, name="clitest", min_workers=0)
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, "-m", "ray_tpu.cli", "up", path],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert r.returncode == 0, r.stderr
    assert "up at" in r.stdout
    try:
        assert launcher.cluster_state("clitest") is not None
    finally:
        r = subprocess.run(
            [sys.executable, "-m", "ray_tpu.cli", "down", path],
            capture_output=True, text=True, timeout=60, env=env,
        )
        assert r.returncode == 0, r.stderr
    assert launcher.cluster_state("clitest") is None
