"""Native shm arena store: allocator, pin/delete lifetime, cross-process.

Mirrors the reference's plasma tests
(src/ray/object_manager/plasma/test/object_store_test.cc — create/seal/get/
delete lifecycle) against our arena client.
"""
import multiprocessing as mp
import os
import secrets

import pytest

from ray_tpu.native import load_library
from ray_tpu.native.arena import HybridShmStore, NativeArenaStore

pytestmark = pytest.mark.skipif(
    load_library() is None, reason="native toolchain unavailable"
)


def _hex() -> str:
    return secrets.token_hex(28)


@pytest.fixture
def arena():
    name = f"/rt_test_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    yield store
    store.close_all()


def test_roundtrip_frames(arena):
    oid = _hex()
    frames = [b"header-bytes", b"x" * 100_000, b""]
    meta = arena.put_frames(oid, frames)
    assert meta["arena"] == arena.name
    got = arena.get_frames(oid, meta)
    assert [bytes(f) for f in got] == frames
    assert arena.contains(oid)


def test_get_is_zero_copy(arena):
    oid = _hex()
    arena.put_frames(oid, [b"a" * 4096])
    v1 = arena.get_frames(oid, {})[0]
    v2 = arena.get_frames(oid, {})[0]
    # Same underlying arena memory, not copies.
    import ctypes
    a1 = ctypes.addressof(ctypes.c_char.from_buffer(v1))
    a2 = ctypes.addressof(ctypes.c_char.from_buffer(v2))
    assert a1 == a2


def test_missing_object(arena):
    assert arena.get_frames(_hex(), {}) is None
    assert not arena.contains(_hex())


def test_delete_reclaims_memory(arena):
    base = arena.stats()["bytes_in_use"]
    oids = []
    for _ in range(16):
        oid = _hex()
        arena.put_frames(oid, [b"y" * 50_000])
        oids.append(oid)
    assert arena.stats()["num_objects"] == 16
    for oid in oids:
        arena.free(oid)
    st = arena.stats()
    assert st["num_objects"] == 0
    assert st["bytes_in_use"] == base


def test_pinned_object_survives_delete(arena):
    import gc

    oid = _hex()
    arena.put_frames(oid, [b"z" * 1000])
    view = arena.get_frames(oid, {})[0]  # pin rides the view's lifetime
    # Creator deletes while the reader view is live: memory must not be
    # reused until the view dies (plasma pin semantics).
    in_use = arena.stats()["bytes_in_use"]
    arena._created.discard(oid)  # simulate owner in another process
    arena._lib.rt_obj_delete(arena._h, oid.encode())
    assert arena.stats()["bytes_in_use"] == in_use  # still held by pin
    assert bytes(view) == b"z" * 1000
    del view
    gc.collect()
    assert arena.stats()["bytes_in_use"] < in_use


def test_coalescing_allows_large_realloc(arena):
    # Fill with small objects, free them all, then allocate one block that
    # only fits if neighbors coalesced back into a single free range.
    cap = arena.stats()["capacity"]
    oids = []
    small = (cap // 64) & ~15
    for _ in range(32):
        oid = _hex()
        if arena.put_frames(oid, [b"s" * small]) is None:
            break
        oids.append(oid)
    for oid in oids:
        arena.free(oid)
    big = int(cap * 0.75)
    oid = _hex()
    assert arena.put_frames(oid, [b"B" * big]) is not None
    arena.free(oid)


def test_arena_full_returns_none(arena):
    cap = arena.stats()["capacity"]
    oid = _hex()
    assert arena.put_frames(oid, [b"Q" * (cap * 2)]) is None


def test_duplicate_create_raises(arena):
    oid = _hex()
    arena.put_frames(oid, [b"1"])
    with pytest.raises(RuntimeError):
        arena.put_frames(oid, [b"2"])


def _child_reader(name, oid, payload_len, q):
    try:
        store = NativeArenaStore(name, create=False)
        frames = store.get_frames(oid, {})
        q.put(("ok", bytes(frames[1]) == b"p" * payload_len))
    except Exception as e:  # pragma: no cover - diagnostic path
        q.put(("err", repr(e)))


def test_cross_process_read():
    name = f"/rt_test_xp_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        oid = _hex()
        store.put_frames(oid, [b"hdr", b"p" * 10_000])
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_reader, args=(name, oid, 10_000, q))
        p.start()
        status, ok = q.get(timeout=30)
        p.join(timeout=10)
        assert status == "ok", ok
        assert ok
    finally:
        store.close_all()


def _child_writer(name, oid, q):
    try:
        store = NativeArenaStore(name, create=False)
        store.put_frames(oid, [b"from-child" * 100])
        q.put("ok")
        # Exit WITHOUT delete: creator pin leaks, object must stay readable.
    except Exception as e:  # pragma: no cover
        q.put(repr(e))


def test_cross_process_write_then_parent_read():
    name = f"/rt_test_xw_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        oid = _hex()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_writer, args=(name, oid, q))
        p.start()
        assert q.get(timeout=30) == "ok"
        p.join(timeout=10)
        frames = store.get_frames(oid, {})
        assert bytes(frames[0]) == b"from-child" * 100
    finally:
        store.close_all()


def test_hybrid_falls_back_when_arena_full():
    name = f"/rt_test_hy_{os.getpid()}_{secrets.token_hex(4)}"
    store = HybridShmStore(name)
    try:
        if store.arena is None:
            pytest.skip("no native arena")
        cap = store.arena.stats()["capacity"]
        oid = _hex()
        meta = store.put_frames(oid, [b"W" * (cap * 2)])
        assert "seg" in meta  # portable fallback segment
        got = store.get_frames(oid, meta)
        assert bytes(got[0]) == b"W" * (cap * 2)
        store.free(oid, meta)
    finally:
        store.close_all()


def test_many_alloc_free_cycles(arena):
    """Allocator churn: interleaved sizes, no leak at the end."""
    import random

    rng = random.Random(0)
    live = {}
    base = arena.stats()["bytes_in_use"]
    for i in range(400):
        if live and (rng.random() < 0.45 or len(live) > 40):
            oid = rng.choice(list(live))
            n = live.pop(oid)
            got = arena.get_frames(oid, {})
            assert len(got[0]) == n
            arena.free(oid)
        else:
            oid = _hex()
            n = rng.randrange(10, 60_000)
            if arena.put_frames(oid, [bytes([i % 256]) * n]) is not None:
                live[oid] = n
    for oid in list(live):
        arena.free(oid)
    del got
    import gc

    gc.collect()  # drop view pins so deletable blocks reclaim
    assert arena.stats()["bytes_in_use"] == base
    assert arena.stats()["num_objects"] == 0
