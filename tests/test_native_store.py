"""Native shm arena store: allocator, pin/delete lifetime, cross-process.

Mirrors the reference's plasma tests
(src/ray/object_manager/plasma/test/object_store_test.cc — create/seal/get/
delete lifecycle) against our arena client.
"""
import multiprocessing as mp
import os
import secrets

import pytest

from ray_tpu import native as rt_native
from ray_tpu.native import load_library
from ray_tpu.native.arena import HybridShmStore, NativeArenaStore

# A compile error with a working toolchain is a repo bug and must FAIL the
# suite (collection error), never skip — see test_native_build.py.
if load_library() is None and rt_native.build_failure() is not None:
    raise RuntimeError(
        "native build FAILED (compile error, toolchain present):\n"
        + rt_native.build_failure()
    )

pytestmark = pytest.mark.skipif(
    load_library() is None, reason="native toolchain unavailable"
)


def _hex() -> str:
    return secrets.token_hex(28)


@pytest.fixture
def arena():
    name = f"/rt_test_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    yield store
    store.close_all()


def test_roundtrip_frames(arena):
    oid = _hex()
    frames = [b"header-bytes", b"x" * 100_000, b""]
    meta = arena.put_frames(oid, frames)
    assert meta["arena"] == arena.name
    got = arena.get_frames(oid, meta)
    assert [bytes(f) for f in got] == frames
    assert arena.contains(oid)


def test_get_is_zero_copy(arena):
    oid = _hex()
    arena.put_frames(oid, [b"a" * 4096])
    v1 = arena.get_frames(oid, {})[0]
    v2 = arena.get_frames(oid, {})[0]
    # Same underlying arena memory, not copies.
    import ctypes
    a1 = ctypes.addressof(ctypes.c_char.from_buffer(v1))
    a2 = ctypes.addressof(ctypes.c_char.from_buffer(v2))
    assert a1 == a2


def test_missing_object(arena):
    assert arena.get_frames(_hex(), {}) is None
    assert not arena.contains(_hex())


def test_delete_reclaims_memory(arena):
    base = arena.stats()["bytes_in_use"]
    oids = []
    for _ in range(16):
        oid = _hex()
        arena.put_frames(oid, [b"y" * 50_000])
        oids.append(oid)
    assert arena.stats()["num_objects"] == 16
    for oid in oids:
        arena.free(oid)
    st = arena.stats()
    assert st["num_objects"] == 0
    assert st["bytes_in_use"] == base


def test_pinned_object_survives_delete(arena):
    import gc

    oid = _hex()
    arena.put_frames(oid, [b"z" * 1000])
    view = arena.get_frames(oid, {})[0]  # pin rides the view's lifetime
    # Creator deletes while the reader view is live: memory must not be
    # reused until the view dies (plasma pin semantics).
    in_use = arena.stats()["bytes_in_use"]
    arena._created.pop(oid, None)  # simulate owner in another process
    arena._lib.rt_obj_delete(arena._h, oid.encode())
    assert arena.stats()["bytes_in_use"] == in_use  # still held by pin
    assert bytes(view) == b"z" * 1000
    del view
    gc.collect()
    assert arena.stats()["bytes_in_use"] < in_use


def test_double_delete_does_not_steal_reader_pin(arena):
    """Owner free AND creator free (object_free pubsub fanout) both call
    rt_obj_delete; the creator pin must drop exactly once, or the second
    delete steals the READER's pin and the block is reclaimed (and reused)
    under a live zero-copy view — observed as streamed values swapping."""
    import gc

    oid = _hex()
    arena.put_frames(oid, [b"A" * 100_000])
    view = arena.get_frames(oid, {})[0]  # reader pin rides the view
    in_use = arena.stats()["bytes_in_use"]
    # owner-side free (borrower process path: delete via meta)
    arena._lib.rt_obj_delete(arena._h, oid.encode())
    # creator-side free (pubsub fanout path) — a second delete
    arena._created.pop(oid, None)
    arena._lib.rt_obj_delete(arena._h, oid.encode())
    assert arena.stats()["bytes_in_use"] == in_use, "reader pin stolen"
    # A new same-size object must NOT overwrite the pinned block.
    oid2 = _hex()
    arena.put_frames(oid2, [b"B" * 100_000])
    assert bytes(view[:10]) == b"A" * 10
    del view
    gc.collect()
    # Pin released: now the block reclaims.
    assert arena.stats()["bytes_in_use"] <= in_use


def test_coalescing_allows_large_realloc(arena):
    # Fill with small objects, free them all, then allocate one block that
    # only fits if neighbors coalesced back into a single free range.
    cap = arena.stats()["capacity"]
    oids = []
    small = (cap // 64) & ~15
    for _ in range(32):
        oid = _hex()
        if arena.put_frames(oid, [b"s" * small]) is None:
            break
        oids.append(oid)
    for oid in oids:
        arena.free(oid)
    big = int(cap * 0.75)
    oid = _hex()
    assert arena.put_frames(oid, [b"B" * big]) is not None
    arena.free(oid)


def test_arena_full_returns_none(arena):
    cap = arena.stats()["capacity"]
    oid = _hex()
    assert arena.put_frames(oid, [b"Q" * (cap * 2)]) is None


def test_duplicate_create_raises(arena):
    oid = _hex()
    arena.put_frames(oid, [b"1"])
    with pytest.raises(RuntimeError):
        arena.put_frames(oid, [b"2"])


def _child_reader(name, oid, payload_len, q):
    try:
        store = NativeArenaStore(name, create=False)
        frames = store.get_frames(oid, {})
        q.put(("ok", bytes(frames[1]) == b"p" * payload_len))
    except Exception as e:  # pragma: no cover - diagnostic path
        q.put(("err", repr(e)))


def test_cross_process_read():
    name = f"/rt_test_xp_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        oid = _hex()
        store.put_frames(oid, [b"hdr", b"p" * 10_000])
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_reader, args=(name, oid, 10_000, q))
        p.start()
        status, ok = q.get(timeout=30)
        p.join(timeout=10)
        assert status == "ok", ok
        assert ok
    finally:
        store.close_all()


def _child_writer(name, oid, q):
    try:
        store = NativeArenaStore(name, create=False)
        store.put_frames(oid, [b"from-child" * 100])
        q.put("ok")
        # Exit WITHOUT delete: creator pin leaks, object must stay readable.
    except Exception as e:  # pragma: no cover
        q.put(repr(e))


def test_cross_process_write_then_parent_read():
    name = f"/rt_test_xw_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        oid = _hex()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_writer, args=(name, oid, q))
        p.start()
        assert q.get(timeout=30) == "ok"
        p.join(timeout=10)
        frames = store.get_frames(oid, {})
        assert bytes(frames[0]) == b"from-child" * 100
    finally:
        store.close_all()


def test_hybrid_falls_back_when_arena_full():
    name = f"/rt_test_hy_{os.getpid()}_{secrets.token_hex(4)}"
    store = HybridShmStore(name)
    try:
        if store.arena is None:
            pytest.skip("no native arena")
        cap = store.arena.stats()["capacity"]
        oid = _hex()
        meta = store.put_frames(oid, [b"W" * (cap * 2)])
        assert "seg" in meta  # portable fallback segment
        got = store.get_frames(oid, meta)
        assert bytes(got[0]) == b"W" * (cap * 2)
        store.free(oid, meta)
    finally:
        store.close_all()


def test_many_alloc_free_cycles(arena):
    """Allocator churn: interleaved sizes, no leak at the end."""
    import random

    rng = random.Random(0)
    live = {}
    base = arena.stats()["bytes_in_use"]
    for i in range(400):
        if live and (rng.random() < 0.45 or len(live) > 40):
            oid = rng.choice(list(live))
            n = live.pop(oid)
            got = arena.get_frames(oid, {})
            assert len(got[0]) == n
            arena.free(oid)
        else:
            oid = _hex()
            n = rng.randrange(10, 60_000)
            if arena.put_frames(oid, [bytes([i % 256]) * n]) is not None:
                live[oid] = n
    for oid in list(live):
        arena.free(oid)
    del got
    import gc

    gc.collect()  # drop view pins so deletable blocks reclaim
    assert arena.stats()["bytes_in_use"] == base
    assert arena.stats()["num_objects"] == 0


def test_tombstone_rehash_bounded():
    """Churn far more objects than index slots: tombstones must rehash away
    and lookups keep working."""
    name = f"/rt_test_tb_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24, index_slots=256)
    try:
        for i in range(2000):
            oid = _hex()
            assert store.put_frames(oid, [b"t" * 64]) is not None
            assert store.contains(oid)
            store.free(oid)
        tombs = store._lib.rt_arena_num_tombs(store._h)
        assert tombs <= 64, f"tombstones not rehashed: {tombs}"
        assert not store.contains(_hex())  # miss lookups still terminate
        st = store.stats()
        assert st["num_objects"] == 0
    finally:
        store.close_all()


def _child_crash_in_lock(name, q):
    import time as _time

    try:
        store = NativeArenaStore(name, create=False)
        store.put_frames(secrets.token_hex(28), [b"pre-crash" * 10])
        store._lib.rt_test_hold_lock(store._h)
        q.put("locked")
        # Let the queue feeder thread flush, then die holding the mutex.
        # (The parent blocks on the robust mutex until this process dies,
        # then wakes with EOWNERDEAD.)
        _time.sleep(0.5)
        os._exit(9)
    except Exception as e:  # pragma: no cover
        q.put(repr(e))


def test_crash_recovery_eownerdead():
    """A process dying inside the critical section must not wedge or corrupt
    the arena: the next locker recovers and normal operation continues."""
    name = f"/rt_test_cr_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        survivor = _hex()
        store.put_frames(survivor, [b"S" * 5000])
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_crash_in_lock, args=(name, q))
        p.start()
        assert q.get(timeout=30) == "locked"
        p.join(timeout=10)
        # Next operation takes the robust mutex, recovers, and proceeds.
        assert store.contains(survivor)
        got = store.get_frames(survivor, {})
        assert bytes(got[0]) == b"S" * 5000
        # Allocator still sane after recovery: alloc/free cycles work.
        for _ in range(50):
            oid = _hex()
            assert store.put_frames(oid, [b"x" * 10_000]) is not None
            store.free(oid)
    finally:
        store.close_all()


def _child_pin_and_die(name, oid, q):
    try:
        store = NativeArenaStore(name, create=False)
        frames = store.get_frames(oid, {})
        assert frames is not None
        q.put("pinned")
        import time as _t
        _t.sleep(0.5)  # let the queue flush
        os._exit(9)  # die holding the reader pin (no release)
    except Exception as e:  # pragma: no cover
        q.put(repr(e))


def test_dead_process_pins_are_scrubbed():
    """A reader killed while holding pins must not leak its blocks: the
    scrub (also triggered on allocation pressure) subtracts the dead
    process's pin ledger and reclaims (plasma client-disconnect analog)."""
    name = f"/rt_test_sc_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        oid = _hex()
        store.put_frames(oid, [b"L" * 100_000])
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_pin_and_die, args=(name, oid, q))
        p.start()
        assert q.get(timeout=30) == "pinned"
        p.join(timeout=10)
        base = store.stats()["bytes_in_use"]
        store.free(oid)  # owner delete: dead reader's pin still blocks it
        assert store.stats()["bytes_in_use"] == base
        live = store._lib.rt_arena_scrub(store._h)
        assert live >= 1  # this process
        assert store.stats()["bytes_in_use"] < base
        assert store.stats()["num_objects"] == 0
    finally:
        store.close_all()


def test_scrub_triggers_on_allocation_pressure():
    """When the arena fills, create() scrubs dead clients automatically and
    retries before reporting ENOSPC."""
    name = f"/rt_test_sp_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 24)
    try:
        cap = store.stats()["capacity"]
        big = int(cap * 0.6)
        oid = _hex()
        store.put_frames(oid, [b"X" * big])
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_child_pin_and_die, args=(name, oid, q))
        p.start()
        assert q.get(timeout=30) == "pinned"
        p.join(timeout=10)
        store.free(oid)  # deletable, but dead reader pin holds it
        # This put only fits if the dead client's pin got scrubbed inline.
        oid2 = _hex()
        assert store.put_frames(oid2, [b"Y" * big]) is not None
        store.free(oid2)
    finally:
        store.close_all()


def _child_multithread_putter(name, oid, n, q):
    try:
        # RT_COPY_THREADS was set by the parent BEFORE spawn: the budget is
        # cached on first use, so it must be in the env at process start.
        store = NativeArenaStore(name, create=False)
        payload = bytes(range(256)) * (n // 256) + b"Z" * (n % 256)
        store.put_frames(oid, [payload])
        q.put(("ok", len(payload)))
    except Exception as e:  # pragma: no cover
        q.put(("err", repr(e)))


@pytest.mark.parametrize("extra", [1, 63, 65, 4097])
def test_parallel_copy_covers_tail(extra):
    """Multi-threaded payload copies must cover every byte: chunk rounding
    that floors len/nthreads before 64-aligning used to drop the tail when
    the floor was already aligned (silent corruption on multi-core hosts)."""
    name = f"/rt_test_tail_{os.getpid()}_{secrets.token_hex(4)}"
    store = NativeArenaStore(name, capacity=1 << 25)
    n = (8 << 20) + extra  # >= 2 x 4MB per-thread chunks, never divisible
    try:
        oid = _hex()
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        env_backup = os.environ.get("RT_COPY_THREADS")
        os.environ["RT_COPY_THREADS"] = "4"
        try:
            p = ctx.Process(
                target=_child_multithread_putter, args=(name, oid, n, q)
            )
            p.start()
            status, detail = q.get(timeout=60)
            p.join(timeout=10)
        finally:
            if env_backup is None:
                os.environ.pop("RT_COPY_THREADS", None)
            else:
                os.environ["RT_COPY_THREADS"] = env_backup
        assert status == "ok", detail
        got = store.get_frames(oid, {})[0]
        expect = bytes(range(256)) * (n // 256) + b"Z" * (n % 256)
        assert len(got) == n
        assert bytes(got[-4096:]) == expect[-4096:]  # the dropped region
        assert bytes(got) == expect
    finally:
        store.close_all()
