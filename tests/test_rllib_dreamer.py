"""DreamerV3: world-model learning, imagination actor-critic, recurrent
acting.

Reference analog: ``rllib/algorithms/dreamerv3/`` learning tests. The
learning test uses a parity environment whose reward depends on the ACTION
at each phase — solvable only if the RSSM carries actions through its
recurrent state (random ≈ 4/8, optimal 8/8). Unit tests pin the symlog
pair, replay windowing, and checkpoint roundtrip.
"""
import gymnasium as gym
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DreamerV3Config


class ParityEnv:
    """8-step episodes; obs one-hot phase; reward 1 iff action == phase%2."""

    observation_space = gym.spaces.Box(-1, 1, (8,))
    action_space = gym.spaces.Discrete(2)

    def __init__(self):
        self._t = 0

    def _obs(self):
        o = np.zeros(8, np.float32)
        o[self._t % 8] = 1.0
        return o

    def reset(self, seed=None):
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        r = 1.0 if int(action) == (self._t % 2) else 0.0
        self._t += 1
        return self._obs(), r, self._t >= 8, False, {}

    def close(self):
        pass


@pytest.fixture
def rl_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    ray_tpu.shutdown()


def _config():
    cfg = (
        DreamerV3Config()
        .environment(env_creator=ParityEnv)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .debugging(seed=0)
    )
    cfg.min_replay_size = 64
    cfg.updates_per_step = 8
    cfg.units = 64
    cfg.deter_dim = 64
    cfg.imagine_horizon = 8
    return cfg


def test_symlog_roundtrip():
    from ray_tpu.rllib.algorithms.dreamerv3 import symexp, symlog

    x = np.array([-100.0, -1.0, 0.0, 0.5, 10.0, 1e4])
    assert np.allclose(np.asarray(symexp(symlog(x))), x, rtol=1e-5)


def test_sequence_replay_windows_and_boundaries():
    from ray_tpu.rllib.algorithms.dreamerv3 import SequenceReplay

    buf = SequenceReplay(64, num_envs=2, obs_dim=3, seed=0)
    T = 10
    batch = {
        "obs": np.arange(T * 2 * 3, dtype=np.float32).reshape(T, 2, 3),
        "actions": np.zeros((T, 2), np.int32),
        "rewards": np.arange(T * 2, dtype=np.float32).reshape(T, 2),
        "dones": np.zeros((T, 2), np.float32),
    }
    batch["dones"][4] = 1.0  # episode boundary mid-fragment
    buf.add_fragments(batch)
    win = buf.sample(4, 8)
    assert win["obs"].shape == (4, 8, 3)
    assert np.all(win["is_first"][:, 0] == 1.0)  # window starts reset
    # boundary flag lands on the step AFTER the done
    buf2 = SequenceReplay(64, num_envs=1, obs_dim=1, seed=0)
    b = {
        "obs": np.zeros((6, 1, 1), np.float32),
        "actions": np.zeros((6, 1), np.int32),
        "rewards": np.zeros((6, 1), np.float32),
        "dones": np.zeros((6, 1), np.float32),
    }
    b["dones"][2] = 1.0
    buf2.add_fragments(b)
    assert buf2.is_first[0, 3] == 1.0
    assert buf2.is_first[0, 2] == 0.0


def test_sequence_replay_survives_column_count_change():
    """Runner loss shrinks the fragment's env axis: the buffer remaps
    streams onto its columns and forces a reset flag (no bogus
    continuity across the outage)."""
    from ray_tpu.rllib.algorithms.dreamerv3 import SequenceReplay

    buf = SequenceReplay(32, num_envs=4, obs_dim=2, seed=0)
    full = {
        "obs": np.ones((4, 4, 2), np.float32),
        "actions": np.zeros((4, 4), np.int32),
        "rewards": np.zeros((4, 4), np.float32),
        "dones": np.zeros((4, 4), np.float32),
    }
    buf.add_fragments(full)
    # outage: only 2 columns arrive
    half = {
        "obs": 2 * np.ones((4, 2, 2), np.float32),
        "actions": np.zeros((4, 2), np.int32),
        "rewards": np.zeros((4, 2), np.float32),
        "dones": np.zeros((4, 2), np.float32),
    }
    buf.add_fragments(half)
    assert buf.size == 8
    # every column restarted at the outage boundary
    assert np.all(buf.is_first[:, 4] == 1.0)
    assert np.all(buf.obs[:, 4:8] == 2.0)


def test_dreamer_learns_action_conditioned_reward(rl_cluster):
    """Return climbs from ~4 (random) toward 8 once the world model's
    reward head becomes action-discriminative and the actor exploits it
    in imagination. ~70 iterations on CPU."""
    algo = _config().build_algo()
    try:
        rets = []
        for _ in range(70):
            r = algo.train()
            rets.append(r["episode_return_mean"])
        last = float(np.mean(rets[-3:]))
        assert last > 6.0, f"DreamerV3 did not learn: last={last} rets tail {rets[-10:]}"
        assert r["reward_loss"] < 0.05, r["reward_loss"]
    finally:
        algo.stop()


def test_dreamer_checkpoint_roundtrip(rl_cluster, tmp_path):
    algo = _config().build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "ck"))
        import jax

        w = jax.device_get(algo.params)
        algo2 = _config().build_algo()
        try:
            algo2.restore(path)
            for a, b in zip(jax.tree.leaves(w),
                            jax.tree.leaves(algo2.params)):
                assert np.allclose(a, np.asarray(b))
            assert algo2.iteration == algo.iteration
        finally:
            algo2.stop()
    finally:
        algo.stop()
