"""Vectorized ref hot paths: batched directory lookups, owner-coalesced
pulls, and the wait() fast path.

These tests pin the O(owners)-not-O(refs) RPC shape of the batched resolve
path (reference: batched location lookups + owner-local metadata ops, Wang
et al. NSDI'21) by counting calls through instrumented connections."""
import time

import pytest

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private import worker as worker_mod


class _CallCounter:
    """Wraps a Connection.call coroutine method, counting per-method."""

    def __init__(self):
        self.counts = {}

    def install(self, conn):
        orig = conn.call
        counts = self.counts

        async def counted(method, extras=None, frames=()):
            counts[method] = counts.get(method, 0) + 1
            return await orig(method, extras, frames)

        conn.call = counted
        return conn


@pytest.fixture
def counted_gcs(rt_start):
    """The driver's GCS connection with per-verb call counting."""
    w = worker_mod.global_worker
    counter = _CallCounter()
    counter.install(w.gcs)
    yield w, counter.counts


def _flush_pending(w):
    """Let queued borrow/release drains land before counting RPCs."""
    time.sleep(0.05)
    w.run_sync(_noop())


async def _noop():
    return None


def test_batched_lookup_one_round_trip(counted_gcs):
    """A multi-ref get of directory-resolvable (shm) objects NOT owned by
    this driver issues ONE object_lookup_batch, not N object_lookup
    calls (and no per-ref pulls: the directory resolves them all)."""
    w, counts = counted_gcs
    import numpy as np

    @ray_tpu.remote
    class Maker:
        def make(self, n):
            # > inline threshold: shm-backed, registered in the directory,
            # owned by the hosting worker (not the driver).
            return [ray_tpu.put(np.full(200_000, i, dtype=np.uint8))
                    for i in range(n)]

    refs = ray_tpu.get(Maker.remote().make.remote(8))
    _flush_pending(w)
    counts.clear()
    vals = ray_tpu.get(refs)
    assert [int(v[0]) for v in vals] == list(range(8))
    assert counts.get("object_lookup_batch", 0) == 1
    assert counts.get("object_lookup", 0) == 0


def test_owner_coalesced_pull_o_owners_rpcs(rt_cluster):
    """100 inline refs owned by 2 workers resolve with exactly one
    pull_object_batch per owner (2 RPCs), not one pull per ref."""
    rt, _cluster = rt_cluster

    # num_cpus=2 per holder on 2-CPU nodes: one holder per node, so the
    # 100 refs are owned by exactly two distinct workers.
    @rt.remote(num_cpus=2)
    class Holder:
        def make(self, n, base):
            return [rt.put(base + i) for i in range(n)]

    h1, h2 = Holder.remote(), Holder.remote()
    refs = rt.get(h1.make.remote(50, 0)) + rt.get(h2.make.remote(50, 50))
    owners = {tuple(r.owner_address) for r in refs}
    assert len(owners) == 2, "holders must live in two distinct workers"

    w = worker_mod.global_worker
    counter = _CallCounter()
    for addr in owners:
        conn = w.run_sync(w.get_peer(addr))
        counter.install(conn)
    vals = rt.get(refs)
    assert vals == list(range(100))
    assert counter.counts.get("pull_object_batch", 0) == 2
    assert counter.counts.get("pull_object", 0) == 0


def test_wait_all_ready_fast_path_no_loop_hop(rt_start):
    """wait() over all-ready refs answers on the calling thread: zero
    probe futures, zero loop round-trips."""
    @ray_tpu.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(64)]
    ray_tpu.get(refs)
    w = worker_mod.global_worker
    orig = w.run_sync
    calls = []
    w.run_sync = lambda *a, **k: (calls.append(a), orig(*a, **k))[1]
    try:
        ready, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=5)
    finally:
        w.run_sync = orig
    assert len(ready) == 64 and not not_ready
    assert calls == [], "all-ready wait must not touch the event loop"


def test_wait_mixed_pending(rt_start):
    """wait() with a pending tail still blocks/partitions correctly."""
    @ray_tpu.remote
    def quick(i):
        return i

    @ray_tpu.remote
    def slow():
        time.sleep(5)

    fast_refs = [quick.remote(i) for i in range(3)]
    ray_tpu.get(fast_refs)
    hang = slow.remote()
    ready, not_ready = ray_tpu.wait(fast_refs + [hang], num_returns=3,
                                    timeout=5)
    assert set(ready) == set(fast_refs)
    assert not_ready == [hang]
    ray_tpu.cancel(hang)


def test_mixed_local_remote_error_batch(rt_start):
    """One get() over local puts, task returns, and an errored ref keeps
    per-ref semantics through the batched resolve."""
    @ray_tpu.remote
    def ok(i):
        return i * 10

    @ray_tpu.remote
    def boom():
        raise ValueError("batched boom")

    local = ray_tpu.put("here")
    remote_refs = [ok.remote(i) for i in range(5)]
    err = boom.remote()
    ready, _ = ray_tpu.wait([err], timeout=10)
    assert ready
    with pytest.raises(ray_tpu.exceptions.RayTpuError,
                       match="batched boom"):
        ray_tpu.get([local] + remote_refs + [err])
    assert ray_tpu.get([local] + remote_refs) == \
        ["here", 0, 10, 20, 30, 40]


def test_wait_duplicate_refs_resolve(rt_start):
    """Duplicate refs in one wait() each get their own future: the shared
    remote poller must settle every copy, not just one per object id."""
    @ray_tpu.remote
    class Holder:
        def mk(self):
            return ray_tpu.put(42)

    ref = ray_tpu.get(Holder.remote().mk.remote())
    # Evict the local copy so wait() exercises the remote poller.
    worker_mod.global_worker.memory_store.pop(ref.id().hex(), None)
    ready, not_ready = ray_tpu.wait([ref, ref], num_returns=2, timeout=10)
    assert len(ready) == 2 and not not_ready


def test_wait_ownerless_ref_errors_not_hangs(rt_start):
    """A ref with no owner address and no directory entry becomes
    ready-with-error promptly (the poller must not die or hang)."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu.object_ref import ObjectRef

    bogus = ObjectRef(ObjectID.from_random(), None)
    t0 = time.monotonic()
    ready, not_ready = ray_tpu.wait([bogus], num_returns=1, timeout=5)
    assert time.monotonic() - t0 < 3
    assert ready == [bogus] and not not_ready
    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        ray_tpu.get(bogus, timeout=1)


def test_batched_borrow_single_registration(rt_cluster):
    """Deserializing a container of foreign refs registers ALL borrows
    (values stay alive through the borrowers' pins) and repeated
    materialization doesn't double-register: gets of the same container
    return interned (aliased) refs."""
    rt, _cluster = rt_cluster

    @rt.remote
    class Holder:
        def make(self, n):
            return [rt.put(i) for i in range(n)]

    h = Holder.remote()
    container_ref = h.make.remote(20)
    refs_a = rt.get(container_ref)
    refs_b = rt.get(container_ref)
    assert refs_a[0] is refs_b[0], "live refs should intern by object id"
    assert rt.get(refs_a) == list(range(20))
    assert rt.get(refs_b) == list(range(20))
