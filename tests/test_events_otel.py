"""Structured export events and OTel metric export.

Reference analog: ``src/ray/observability/ray_event_recorder.cc`` +
``dashboard/modules/aggregator/aggregator_agent.py`` (typed lifecycle
events → JSONL/HTTP) and
``observability/open_telemetry_metric_recorder.cc`` (stats → OTel).
"""
import json
import os

import pytest

import ray_tpu
from ray_tpu.util.events import EventRecorder, read_events


def test_event_recorder_jsonl_roundtrip(tmp_path):
    p = str(tmp_path / "events" / "events.jsonl")
    rec = EventRecorder(path=p, flush_interval_s=1e9)  # manual flush
    rec.emit("NODE", "NODE_ALIVE", "n1", addr=["127.0.0.1", 1])
    rec.emit("ACTOR", "ACTOR_DEAD", "a1", message="oom")
    assert rec.flush() == 2
    evs = read_events(p)
    assert [e["event_type"] for e in evs] == ["NODE_ALIVE", "ACTOR_DEAD"]
    assert evs[0]["attributes"]["addr"] == ["127.0.0.1", 1]
    assert evs[1]["message"] == "oom"
    # recent() filtering
    assert len(rec.recent(source_type="ACTOR")) == 1
    with pytest.raises(ValueError, match="source_type"):
        rec.emit("BOGUS", "X", "y")


def test_event_recorder_drop_oldest(tmp_path):
    rec = EventRecorder(path=None, max_buffer=3, flush_interval_s=1e9)
    for i in range(5):
        rec.emit("TASK", "TASK_FAILED", f"t{i}")
    assert rec.dropped == 2
    assert [e["entity_id"] for e in rec.recent()] == ["t2", "t3", "t4"]


def test_head_emits_lifecycle_events(tmp_path, monkeypatch):
    """Node/actor/PG lifecycle transitions land in the head's event log and
    are queryable over RPC."""
    monkeypatch.setenv("RT_SESSION_DIR", str(tmp_path / "sess"))
    ray_tpu.init(num_cpus=2, num_nodes=2)
    try:
        @ray_tpu.remote
        class A:
            def ping(self):
                return 1

        a = A.remote()
        assert ray_tpu.get(a.ping.remote()) == 1
        from ray_tpu.util.placement_group import (
            placement_group,
            remove_placement_group,
        )

        pg = placement_group([{"CPU": 1}])
        assert pg.ready()
        remove_placement_group(pg)
        ray_tpu.kill(a)

        import time

        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        deadline = time.monotonic() + 10
        types = set()
        while time.monotonic() < deadline:
            h, _ = w.run_sync(w.gcs.call("export_events", {"limit": 200}))
            types = {e["event_type"] for e in h["events"]}
            if {"NODE_ALIVE", "ACTOR_ALIVE", "PG_CREATED",
                    "PG_REMOVED"} <= types:
                break
            time.sleep(0.2)
        assert {"NODE_ALIVE", "ACTOR_ALIVE", "PG_CREATED",
                "PG_REMOVED"} <= types, types
    finally:
        ray_tpu.shutdown()
    # (state API + CLI surfaces queried while the cluster was up are
    # covered in test_events_surfaces below)
    # persisted JSONL exists under the session dir after head close
    p = str(tmp_path / "sess" / "events" / "events.jsonl")
    assert os.path.exists(p)
    evs = read_events(p)
    assert any(e["event_type"] == "NODE_ALIVE" for e in evs)


def test_otel_callbacks_without_sdk():
    """The observable-instrument callbacks (the part that reads our
    registry) work against the OTel API package alone — the SDK is only
    needed for the exporter plumbing."""
    pytest.importorskip("opentelemetry.metrics")
    from ray_tpu.util import metrics
    from ray_tpu.util.metrics_otel import OtelMetricsBridge

    c = metrics.Counter("otel_cb_total", "demo")
    c.inc(3.0, tags={"k": "v"})
    h = metrics.Histogram("otel_cb_hist", boundaries=[1.0, 10.0])
    h.observe(0.5)
    h.observe(5.0)

    bridge = OtelMetricsBridge.__new__(OtelMetricsBridge)
    vals = bridge._value_callback("otel_cb_total")(None)
    assert [(dict(o.attributes), o.value) for o in vals] == [({"k": "v"}, 3.0)]
    cnt = bridge._hist_callback("otel_cb_hist", "count")(None)
    assert cnt[0].value == 2
    buckets = {
        o.attributes["le"]: o.value
        for o in bridge._hist_callback("otel_cb_hist", "bucket")(None)
    }
    assert buckets["1.0"] == 1 and buckets["+Inf"] == 2


def test_otel_bridge_exports_registry():
    otel_sdk = pytest.importorskip("opentelemetry.sdk.metrics")
    from opentelemetry.sdk.metrics.export import InMemoryMetricReader

    from ray_tpu.util import metrics
    from ray_tpu.util.metrics_otel import OtelMetricsBridge

    c = metrics.Counter("otel_test_total", "demo")
    c.inc(3.0, tags={"k": "v"})
    g = metrics.Gauge("otel_test_gauge")
    g.set(7.5)
    h = metrics.Histogram("otel_test_hist", boundaries=[1.0, 10.0])
    h.observe(0.5)
    h.observe(5.0)

    # Bridge with an in-memory reader: bypass the periodic exporter and
    # collect synchronously.
    bridge = OtelMetricsBridge.__new__(OtelMetricsBridge)
    from opentelemetry.sdk.metrics import MeterProvider

    reader = InMemoryMetricReader()
    bridge._provider = MeterProvider(metric_readers=[reader])
    bridge._meter = bridge._provider.get_meter("test")
    bridge._registered = set()
    bridge._reader = reader
    bridge.refresh_instruments()

    data = reader.get_metrics_data()
    points = {}
    for rm in data.resource_metrics:
        for sm in rm.scope_metrics:
            for m in sm.metrics:
                for dp in m.data.data_points:
                    points.setdefault(m.name, []).append(
                        (dict(dp.attributes), dp.value)
                    )
    assert points["otel_test_total"] == [({"k": "v"}, 3.0)]
    assert points["otel_test_gauge"][0][1] == 7.5
    assert any(v == 2 for _, v in points["otel_test_hist_count"])
    buckets = dict(
        (a["le"], v) for a, v in points["otel_test_hist_bucket"]
    )
    assert buckets["1.0"] == 1 and buckets["+Inf"] == 2
    bridge._provider.shutdown()



def test_events_surfaces(tmp_path, monkeypatch, capsys):
    """The event pipeline's query surfaces: state.list_events and the
    `rt events` CLI (reference: aggregator query endpoints)."""
    monkeypatch.setenv("RT_SESSION_DIR", str(tmp_path / "sess"))
    ray_tpu.init(num_cpus=1, num_nodes=1)
    try:
        import time

        from ray_tpu.util import state

        deadline = time.monotonic() + 10
        evs = []
        while time.monotonic() < deadline:
            evs = state.list_events(source_type="NODE")
            if evs:
                break
            time.sleep(0.2)
        assert evs and all(e["source_type"] == "NODE" for e in evs)

        from ray_tpu import cli
        from ray_tpu._private.worker import get_global_worker

        addr = "%s:%d" % get_global_worker().gcs_addr
        cli.main(["events", "--address", addr, "--source-type", "NODE"])
        out = capsys.readouterr().out.strip().splitlines()
        assert out and json.loads(out[0])["source_type"] == "NODE"
    finally:
        ray_tpu.shutdown()
