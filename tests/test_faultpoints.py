"""Deterministic fault injection plane + RPC hardening tests.

Reference analog: the reference exercises its retry/dedup machinery with
per-RPC injected failures (``RAY_testing_rpc_failure`` hooks consulted in
``src/ray/rpc/grpc_client.h``), not just whole-node kills. Here:

- unit coverage for the spec language, seeded determinism, and kind
  semantics of ``_private/faultpoints.py``;
- cluster tests proving the hardening holds where injection bites —
  dropped lease/create_actor replies are retried and corr-deduped
  (never double-applied), dropped/failed pulls re-arm, a timed-out
  ``run_sync`` cancels its coroutine;
- a ``slow``-marked chaos matrix running core workloads under sustained
  10% faults at the major points, asserting completion and no leaked
  lease accounting;
- head-snapshot-restore under injected faults (corrupt snapshot + a
  dropped first post-restore lease reply must leave the head serving).
"""
import asyncio
import threading
import time
from concurrent.futures import TimeoutError as SyncTimeoutError

import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private.test_utils import NodeKiller, wait_for_condition


@pytest.fixture(autouse=True)
def _clean_faults():
    fp.clear()
    yield
    fp.clear()


# chaos_flight_trace moved to conftest.py (shared with the serve chaos
# matrix): it now joins the task-event tracks into the failure artifact.


@pytest.fixture
def fast_rpc(monkeypatch):
    """Short deadlines so dropped replies retry in test time, plus extra
    retries so sustained-probability faults can't exhaust the budget."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")
    monkeypatch.setenv("RT_LEASE_REQUEST_TIMEOUT_S", "1")
    monkeypatch.setenv("RT_RPC_RETRIES", "4")


# ------------------------------------------------------------- spec parsing
def test_parse_full_and_partial_specs():
    specs = fp.parse_spec(
        "worker.pull:error:0.5:3:42, gcs.dispatch.lease:drop:0.1"
    )
    assert [(s.point, s.kind, s.prob, s.count, s.seed) for s in specs] == [
        ("worker.pull", "error", 0.5, 3, 42),
        ("gcs.dispatch.lease", "drop", 0.1, 0, 0),
    ]


def test_parse_rejects_unknown_point():
    with pytest.raises(ValueError, match="unknown fault point"):
        fp.parse_spec("no.such.point:error:1.0")


def test_parse_rejects_unknown_and_unsupported_kind():
    with pytest.raises(ValueError, match="unknown fault kind"):
        fp.parse_spec("worker.pull:explode:1.0")
    # spill.write supports error/delay only
    with pytest.raises(ValueError, match="does not support"):
        fp.parse_spec("spill.write:drop:1.0")


def test_parse_rejects_bad_prob():
    with pytest.raises(ValueError, match="prob"):
        fp.parse_spec("worker.pull:error:1.5")


def test_wildcard_spec_matches_all_verbs():
    fp.configure("gcs.dispatch.*:drop:1.0:0:1")
    assert fp.fire("gcs.dispatch.kv_put") == "drop"
    assert fp.fire("gcs.dispatch.lease") == "drop"
    assert fp.fire("worker.pull") is None


def test_inactive_is_total_noop():
    assert fp.ACTIVE is False
    assert fp.fire("worker.pull") is None
    assert fp.stats() == []


def test_configure_and_clear_toggle_active():
    fp.configure("worker.pull:error:1.0")
    assert fp.ACTIVE is True
    fp.clear()
    assert fp.ACTIVE is False


# ------------------------------------------------------------- determinism
def _collect_indices(spec, n=50):
    fp.configure(spec)
    for _ in range(n):
        try:
            fp.fire("worker.pull")
        except ConnectionError:
            pass
    return fp.stats()[0]["indices"]


def test_same_seed_injects_at_identical_indices():
    a = _collect_indices("worker.pull:error:0.3:0:42")
    b = _collect_indices("worker.pull:error:0.3:0:42")
    assert a == b and len(a) > 0


def test_different_seed_injects_differently():
    a = _collect_indices("worker.pull:error:0.3:0:42")
    b = _collect_indices("worker.pull:error:0.3:0:43")
    assert a != b


def test_count_caps_injections_without_shifting_draws():
    # count=2 must stop injecting after two hits, but the RNG draw stream
    # keeps advancing so the WOULD-HAVE indices match the uncapped run.
    uncapped = _collect_indices("worker.pull:error:0.3:0:7")
    capped = _collect_indices("worker.pull:error:0.3:2:7")
    assert capped == uncapped[:2]
    assert fp.stats()[0]["calls"] == 50


def test_error_kind_carries_unavailable_code():
    fp.configure("worker.pull:error:1.0:0:1")
    with pytest.raises(ConnectionError) as ei:
        fp.fire("worker.pull")
    assert getattr(ei.value, "code", None) == "unavailable"


def test_error_kind_uses_call_site_exception_class():
    from ray_tpu._private import protocol

    fp.configure("worker.pull:error:1.0:0:1")
    with pytest.raises(protocol.ConnectionLost):
        fp.fire("worker.pull", err=protocol.ConnectionLost)


def test_delay_kind_sleeps_then_proceeds():
    fp.configure("worker.pull:delay:1.0:0:1", delay_s=0.1)
    t0 = time.monotonic()
    assert fp.fire("worker.pull") == "delay"
    assert time.monotonic() - t0 >= 0.09


def test_async_fire_matches_sync_semantics():
    fp.configure("worker.pull:drop:1.0:0:1")

    async def go():
        return await fp.async_fire("worker.pull")

    assert asyncio.run(go()) == "drop"


def test_env_spec_format_via_configure_roundtrip():
    # the RT_FAULT_SPEC string format is the configure() format
    fp.configure("spill.write:error:1.0:1:5,spill.restore:delay:0.5")
    assert [s["point"] for s in fp.stats()] == [
        "spill.write", "spill.restore"
    ]


# ----------------------------------------------------- spill chaos (unit)
def test_spill_write_fault_keeps_object_in_arena(tmp_path):
    from ray_tpu._private.spill import SpillManager

    sm = SpillManager(root=str(tmp_path / "spill"))
    fp.configure("spill.write:error:1.0:1:9")
    metas = sm.spill_many([("aa" * 28, [b"x" * 10]), ("bb" * 28, [b"y"])])
    # exactly one write hit the injected storage failure; the batch API
    # reports it as None (object stays in the arena) without raising
    assert metas.count(None) == 1
    ok = [m for m in metas if m is not None]
    assert len(ok) == 1 and sm.stats["spilled_objects"] == 1
    # restore: first read hits the injected failure -> None (callers fall
    # back to pull/reconstruction); the next read succeeds
    fp.configure("spill.restore:error:1.0:1:9")
    assert sm.read(ok[0]) is None
    frames = sm.read(ok[0])
    assert frames is not None and sm.stats["restored_objects"] == 1
    sm.cleanup()


# ------------------------------------------------- test_utils satellites
class _FakeNode:
    def __init__(self, node_id):
        self.node_id = node_id

    def alive(self):
        return True


class _FailingCluster:
    def __init__(self):
        self.nodes = [_FakeNode("aaaa1111"), _FakeNode("bbbb2222")]

    def kill_node(self, handle):
        raise RuntimeError("kill exploded")


def test_node_killer_records_failed_kills():
    cluster = _FailingCluster()
    killer = NodeKiller(cluster, interval_s=0.01, min_alive=1).start()
    try:
        wait_for_condition(
            lambda: killer.kill_errors, timeout=5,
            message="NodeKiller never recorded the failed kill",
        )
    finally:
        killer.stop()
    assert killer.killed == []
    node_id, err = killer.kill_errors[0]
    assert node_id in ("aaaa1111", "bbbb2222") and "kill exploded" in err


def test_wait_for_condition_polls_and_times_out():
    hits = []

    def cond():
        hits.append(1)
        return len(hits) >= 3

    wait_for_condition(cond, timeout=5, interval=0.01)
    assert len(hits) == 3
    with pytest.raises(TimeoutError, match="nope"):
        wait_for_condition(lambda: False, timeout=0.2, interval=0.01,
                           message="nope")


# --------------------------------------------------- cluster: retry/dedup
def _leases_settled():
    """All leases returned: every alive node's availability is back to its
    full capacity at the head."""
    cluster = ray_tpu._internal_cluster()
    return all(
        all(n.available.get(k, 0.0) >= v - 1e-9
            for k, v in n.resources.items())
        for n in cluster.head.nodes.values() if n.alive
    )


def _no_leaked_objects():
    """Zero leaked objects (the memtrack plane's chaos SLO, joined to the
    zero-leaked-leases one): no directory entry past the grace window
    that no live process owns, stores, or borrows."""
    from ray_tpu.util import state

    return state.memory_summary(grace_s=1.0)["leaks"] == []


def test_lease_reply_drop_is_retried_and_deduped(rt_start, fast_rpc):
    # The FIRST lease reply is swallowed after the head applied the grant;
    # the client's deadline fires, the retry carries the same correlation
    # id, and the head replays the original grants — the task completes
    # and no capacity is double-acquired.
    fp.configure("gcs.dispatch.lease:drop:1.0:1:7")

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(41), timeout=60) == 42
    s = fp.stats()[0]
    assert s["injected"] == 1
    fp.clear()
    wait_for_condition(_leases_settled, timeout=15,
                       message="dropped-then-replayed lease leaked")


def test_lease_error_unavailable_is_retried(rt_start, fast_rpc):
    # Verb fails twice with the transient-unavailability class before it
    # ever grants; the retryable client re-issues until it lands.
    fp.configure("gcs.dispatch.lease:error:1.0:2:3")

    @ray_tpu.remote
    def f():
        return "ok"

    assert ray_tpu.get(f.remote(), timeout=60) == "ok"
    assert fp.stats()[0]["injected"] == 2


def test_pull_reply_drop_rearms_long_poll(rt_start, fast_rpc):
    @ray_tpu.remote
    def make():
        return ray_tpu.put(123)  # inner ref owned by the executing worker

    inner = ray_tpu.get(make.remote(), timeout=60)
    fp.configure("worker.pull:drop:1.0:1:5")
    # the first pull's reply is lost; the attempt deadline re-arms the
    # long-poll instead of hanging the get() forever
    assert ray_tpu.get(inner, timeout=60) == 123
    assert fp.stats()[0]["injected"] == 1


def test_pull_connection_errors_are_retried(rt_start, fast_rpc):
    @ray_tpu.remote
    def make():
        return ray_tpu.put([1, 2, 3])

    inner = ray_tpu.get(make.remote(), timeout=60)
    fp.configure("worker.pull:error:1.0:2:6")
    assert ray_tpu.get(inner, timeout=60) == [1, 2, 3]
    assert fp.stats()[0]["injected"] == 2


def test_create_actor_reply_drop_is_deduped(rt_start, fast_rpc):
    # Reply to create_actor dropped after the actor was placed: the retry
    # must return the ORIGINAL placement, not create a twin. A NAMED
    # actor keeps the synchronous per-actor verb (anonymous creations
    # ride create_actor_batch since round 10 — their dropped-reply replay
    # is pinned in test_submission_plane.py).
    fp.configure("gcs.dispatch.create_actor:drop:1.0:1:1")

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    a = Counter.options(name="dedup-droptest").remote()
    assert ray_tpu.get(a.incr.remote(), timeout=60) == 1
    assert fp.stats()[0]["injected"] == 1
    head = ray_tpu._internal_cluster().head
    live = [x for x in head.actors.values() if x.state == "ALIVE"]
    assert len(live) == 1, "retry after dropped reply double-created"
    ray_tpu.kill(a)


def test_task_push_failure_retries_elsewhere(rt_start, fast_rpc):
    # An injected connection loss on the push path must surface as a
    # retriable worker failure, and the released slots must not leak the
    # head's capacity accounting.
    fp.configure("worker.task.push:error:1.0:1:4")

    @ray_tpu.remote
    def f(x):
        return x * 2

    assert ray_tpu.get(f.remote(21), timeout=60) == 42
    assert fp.stats()[0]["injected"] == 1
    fp.clear()
    wait_for_condition(_leases_settled, timeout=15,
                       message="push-failure slots leaked at the head")


def test_run_sync_timeout_cancels_coroutine(rt_start):
    from ray_tpu._private.worker import get_global_worker

    w = get_global_worker()
    state = {}
    started = threading.Event()

    async def slow():
        started.set()
        try:
            await asyncio.sleep(60)
            state["done"] = True
        except asyncio.CancelledError:
            state["cancelled"] = True
            raise

    with pytest.raises(SyncTimeoutError):
        w.run_sync(slow(), timeout=0.2)
    assert started.wait(5)
    wait_for_condition(
        lambda: state.get("cancelled"), timeout=5,
        message="timed-out run_sync left its coroutine running",
    )
    assert "done" not in state


# ------------------------------------------- head restore under faults
def test_head_restore_corrupt_snapshot_then_lease_drop(tmp_path):
    """A corrupt/truncated snapshot must not crash-loop the head, and a
    dropped reply on the first post-restore lease RPC must leave it
    serving: the corr-tagged retry replays the original grant."""
    from ray_tpu._private import protocol
    from ray_tpu._private.gcs import HeadService

    state = tmp_path / "head_state.bin"
    state.write_bytes(b"\x80\x04garbage truncated snapshot")

    async def run():
        head = HeadService()
        assert head.load_from_file(str(state)) is False  # fresh, no crash
        addr = await head.start()
        fp.configure("gcs.dispatch.lease:drop:1.0:1:11")
        conn = await protocol.connect(addr)
        await conn.call("register_node", {
            "node_id": "n1", "addr": ["127.0.0.1", 1],
            "resources": {"CPU": 2.0}, "labels": {},
        })
        req = {"resources": {"CPU": 1.0}, "count": 1, "timeout": 5.0,
               "corr": "restore-test-corr"}
        # first attempt: grant applied, reply swallowed -> client deadline
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(conn.call("lease", dict(req)), 1.5)
        # retry with the same corr: the head is still serving and replays
        # the ORIGINAL grant instead of acquiring a second CPU
        h, _ = await asyncio.wait_for(conn.call("lease", dict(req)), 10)
        assert len(h["grants"]) == 1
        assert head.nodes["n1"].available["CPU"] == pytest.approx(1.0)
        await conn.close()
        await head.close()

    asyncio.run(run())


# ------------------------------------------------------- chaos matrix
def _workload_fanout():
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(24)]
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(24)]


def _workload_actor_roundtrip():
    @ray_tpu.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, v):
            self.total += v
            return self.total

    a = Acc.remote()
    for i in range(1, 6):
        last = a.add.remote(i)
    assert ray_tpu.get(last, timeout=120) == 15
    ray_tpu.kill(a)


def _workload_multiref_get_wait():
    @ray_tpu.remote
    def nest(i):
        return ray_tpu.put(i)

    inners = ray_tpu.get([nest.remote(i) for i in range(8)], timeout=120)
    ready, not_ready = ray_tpu.wait(inners, num_returns=len(inners),
                                    timeout=120)
    assert not not_ready
    assert sorted(ray_tpu.get(inners, timeout=120)) == list(range(8))


def _workload_pg():
    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK", timeout=60)
    assert pg.ready(timeout=60)
    remove_placement_group(pg)


def _workload_device_objects():
    """Device plane under chaos, both directions: driver-owned sharded
    array consumed by a task (owner-side shard serving), task-owned
    device object pulled by the driver (consumer-side pull + retry)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:2]), ("x",))
    arr = jax.device_put(
        jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
        NamedSharding(mesh, P("x")),
    )
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def consume(v):
        import numpy as _np

        return float(_np.asarray(v).sum())

    @ray_tpu.remote
    def produce():
        import jax.numpy as _jnp

        return ray_tpu.put(_jnp.ones((8, 8), _jnp.float32))

    expect = float(np.asarray(arr).sum())
    assert ray_tpu.get(consume.remote(ref), timeout=120) == expect
    inner = ray_tpu.get(produce.remote(), timeout=120)
    v = ray_tpu.get(inner, timeout=120)
    assert float(np.asarray(v).sum()) == 64.0


CHAOS_SPECS = [
    "gcs.dispatch.lease:drop:0.1:0:101",
    "gcs.dispatch.lease:error:0.1:0:102",
    "gcs.lease.grant:error:0.1:0:103",
    "worker.pull:drop:0.1:0:104",
    "worker.pull:error:0.1:0:105",
    # Anonymous creations ride the round-10 batched verb: a dropped batch
    # reply must replay the ORIGINAL per-item outcomes via corr dedup (no
    # double-created actors, no leaked placements).
    "gcs.dispatch.create_actor_batch:drop:1.0:1:106",
    "gcs.dispatch.create_pg:drop:1.0:1:107",
    "protocol.rpc.reply:delay:0.2:0:108",
    "worker.actor.push:drop:0.2:0:109",
    # Batch-entry failure fires BEFORE any item registers: retryable-
    # unavailable, the client re-issues, nothing half-created.
    "gcs.create_actor_batch:error:1.0:1:111",
    # Spec-template build failure degrades that submission to the inline
    # full-header path — framing is an optimization, never a correctness
    # dependency.
    "worker.spec.frame:error:0.5:0:110",
    # Device plane: a failed/lost shard pull is retried against the owner
    # as a typed retryable error (never a hang, never a half-materialized
    # array); a lost registration degrades readers to pull-from-owner.
    "devstore.shard_pull:error:0.3:0:112",
    "devstore.shard_pull:drop:1.0:1:113",
    "devstore.register:drop:1.0:1:114",
    # Reply plane (round 15): a dropped coalesced multi-result frame
    # loses EVERY rider's reply at once — each per-task deadline must
    # re-arm and the corr-deduped re-push must replay recorded outcomes
    # (exactly-once application), with zero leaked leases/objects.
    "worker.reply.window:drop:1.0:1:115",
    "worker.reply.window:error:0.1:0:116",
    # Arg interning, both sides: pusher-side error degrades that push to
    # full frames / drop resets peer coverage; executor-side error forces
    # — and drop really performs — an interned-frame eviction, so the
    # typed arg_intern_miss retry re-sends the exact bytes.
    "worker.arg.intern:error:0.2:0:117",
    "worker.arg.intern:drop:0.3:0:118",
    # Transit pacing (round 16): error degrades a chunk to the fixed
    # pre-pacing fan-out, drop cold-resets a slot's window to its floor
    # — pacing is an optimization, so every workload must complete with
    # zero leaked leases/objects either way.
    "worker.push.window:error:0.3:0:119",
    "worker.push.window:drop:0.3:0:120",
    # Round-17 RT403 dividend (the lint catalog now pins the fire-site
    # set; these were live points with no matrix row). Named/synchronous
    # actor creation failing at the head must surface as a retryable
    # error the client re-issues — same contract the batched verb
    # already proves above.
    "gcs.actor.create:error:0.2:0:121",
    # Sender-side RPC delay: every control verb tolerates a slow write
    # leg the same way it tolerates the matrixed slow reply leg.
    "protocol.rpc.send:delay:0.2:0:122",
    # Driver loop scale-out (round 20): a refused settle-plane handoff
    # settles THAT reply batch inline on the event loop; a refused
    # pack-plane handoff packs THAT submission inline on the caller
    # thread. Either way every frame/task completes — the planes are
    # optimizations, never correctness gates — with zero leaked
    # leases/objects.
    "driver.settle.handoff:error:0.3:0:123",
    "driver.settle.handoff:drop:0.3:0:124",
    "driver.settle.handoff:delay:0.2:0:125",
    "driver.submit.pack:error:0.3:0:126",
    "driver.submit.pack:drop:0.3:0:127",
]


@pytest.mark.slow
@pytest.mark.parametrize("spec", CHAOS_SPECS)
def test_chaos_matrix(spec, monkeypatch, chaos_flight_trace):
    """Core workloads complete under sustained injected faults at every
    major point, and the head's lease accounting converges back to full
    capacity (no leaked leases). The spec rides RT_FAULT_SPEC into the
    spawned node processes too (they configure at import), so
    executor-side points — the reply-window flush, the interned-arg
    lookup — inject where they actually live, not just in the driver. A
    failure dumps the fault-annotated flight trace (chaos_flight_trace
    fixture)."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")
    monkeypatch.setenv("RT_LEASE_REQUEST_TIMEOUT_S", "1")
    monkeypatch.setenv("RT_RPC_RETRIES", "6")
    monkeypatch.setenv("RT_FAULT_SPEC", spec)
    if spec.startswith("driver.settle.handoff"):
        # The settle plane auto-stands-down on single-core hosts; these
        # rows exercise the handoff path itself, so pin it live.
        monkeypatch.setenv("RT_DRIVER_SETTLE_THREAD", "1")
    ray_tpu.init(num_cpus=2)
    try:
        fp.configure(spec)
        _workload_fanout()
        _workload_actor_roundtrip()
        _workload_multiref_get_wait()
        _workload_pg()
        _workload_device_objects()
        calls = sum(s["calls"] for s in fp.stats())
        if not calls:
            # Executor-side-only point: its hits live in the node
            # processes — probe one (any node of this cluster carries
            # the env-configured spec).
            @ray_tpu.remote
            def _node_fp_stats():
                from ray_tpu._private import faultpoints as fpp

                return fpp.stats()

            calls = sum(
                s["calls"]
                for s in ray_tpu.get(_node_fp_stats.remote(), timeout=60)
            )
        assert calls > 0, "chaos spec never matched a fired point"
        fp.clear()
        wait_for_condition(_leases_settled, timeout=20,
                           message=f"leaked leases under {spec}")
        wait_for_condition(_no_leaked_objects, timeout=20,
                           message=f"leaked objects under {spec}")
    finally:
        fp.clear()
        ray_tpu.shutdown()


@pytest.mark.slow
def test_chaos_matrix_worker_crash(monkeypatch, chaos_flight_trace):
    """The ``crash`` fault kind, exercised for real: a worker process
    hard-exits (os._exit, the SIGKILL-equivalent) at its first task
    execution — after the lease was consumed, before any reply. The
    workload must still complete (pushes fail over and retry on the
    surviving node) and the head's lease accounting must converge with
    zero leaked leases; the dead node lands in the tombstone cache."""
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "2")
    monkeypatch.setenv("RT_LEASE_REQUEST_TIMEOUT_S", "1")
    monkeypatch.setenv("RT_RPC_RETRIES", "6")
    ray_tpu.init(num_cpus=2)
    cluster = ray_tpu._internal_cluster()
    try:
        # "doom" pins the bait task to this node: the crash must fire on
        # ITS first dispatch, not depend on how a burst happens to spread.
        doomed = cluster.add_node(
            resources={"CPU": 2, "doom": 2},
            env={"RT_FAULT_SPEC": "worker.task.exec:crash:1.0:1:1"},
        )

        @ray_tpu.remote
        def sq(x):
            return x * x

        # Fire-and-forget bait: its execution attempt kills the process,
        # so its ref can never resolve (no other node has "doom") — we
        # only await the plain workload, which must fail over cleanly.
        sq.options(resources={"doom": 1}).remote(0)
        refs = [sq.remote(i) for i in range(24)]
        assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(24)]
        # the injected crash really killed the process, mid-dispatch
        wait_for_condition(
            lambda: not doomed.alive(), timeout=30,
            message="doomed worker survived its crash faultpoint",
        )
        assert doomed.proc.returncode == 17  # faultpoints' os._exit code
        # the head noticed: the node is no longer alive in its view
        wait_for_condition(
            lambda: doomed.node_id not in cluster.head.nodes
            or not cluster.head.nodes[doomed.node_id].alive,
            timeout=30, message="head never observed the crashed node",
        )
        # and the crash leaked no lease accounting on the survivors —
        # nor any object: whatever the dead node registered must either
        # be borrower-held or gone from the directory
        wait_for_condition(_leases_settled, timeout=20,
                           message="worker crash leaked leases")
        wait_for_condition(_no_leaked_objects, timeout=20,
                           message="worker crash leaked objects")
    finally:
        ray_tpu.shutdown()


def test_chaos_smoke(rt_start, fast_rpc):
    """Fast tier-1 slice of the matrix: one dropped lease reply + one
    failed pull inside a single fan-out workload."""
    fp.configure(
        "gcs.dispatch.lease:drop:1.0:1:7,worker.pull:error:1.0:1:8"
    )
    _workload_multiref_get_wait()
    fp.clear()
    wait_for_condition(_leases_settled, timeout=15,
                       message="chaos smoke leaked leases")
    wait_for_condition(_no_leaked_objects, timeout=15,
                       message="chaos smoke leaked objects")
