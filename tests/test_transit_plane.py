"""Transit-plane pacing economics (round 16).

Pins the three self-clocking transit mechanisms the way
``test_reply_plane.py`` pins the reply plane:

- the per-slot adaptive push window (``specframe.PushWindow``) grows
  additively on clean drains, shrinks multiplicatively when settle
  latency inflates, and never leaves its floor/ceiling box — a
  saturated executor stops accumulating parked chunks, an idle one
  ramps immediately;
- the ring pump hands a WHOLE drain to the executor-side batch dispatch
  in one pass: executor-pool wakeups are O(drains), never O(messages);
- the driver's TCP recv loop settles every already-buffered reply frame
  in one wakeup (multi-frame settling), and the ``pump-queue`` phase
  the analyzer carves out of reply-ack keeps named + residual == wall;
- the ``push_window`` / ``pump_batch_drain`` / ``settle_batching``
  gates restore the fixed pre-round-16 fan-out and per-message loops
  byte-identically when off;
- the ``worker.push.window`` faultpoint degrades pacing, never
  correctness.
"""
import asyncio

import pytest

import ray_tpu
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import protocol, specframe, taskpath
from ray_tpu._private import worker as worker_mod


@pytest.fixture(autouse=True)
def _fp_clean():
    fp.clear()
    yield
    fp.clear()


# ------------------------------------------------------ window mechanics
def test_push_window_grows_additively_on_clean_drains():
    """Settles at steady low latency grow the window one task per chunk
    up to the ceiling — an idle executor's fast acks ramp it straight
    from initial toward the pipe's real depth."""
    w = specframe.PushWindow(initial=8, floor=2, ceiling=16)
    assert w.window == 8
    for _ in range(40):
        n = w.grant(4)
        assert n > 0
        w.on_settled(n, 0.005)
    assert w.window == 16  # ceiling, never beyond
    assert w.peak == 16
    assert w.shrinks == 0


def test_push_window_shrinks_on_settle_latency_inflation():
    """An inflated settle (> latency_factor x the clean baseline) halves
    the window; sustained inflation walks it to the floor and no
    further. Recovery after the congestion clears regrows additively."""
    w = specframe.PushWindow(initial=16, floor=2, ceiling=32,
                             latency_factor=3.0)
    w.on_settled(w.grant(4), 0.010)  # baseline ~10ms
    w.on_settled(w.grant(4), 0.010)
    assert w.window == 17  # second clean settle grew it
    assert not w.on_settled(w.grant(4), 0.100)  # 10x: congestion
    assert w.window == 8  # multiplicative: 17 -> 8
    for _ in range(10):
        w.on_settled(w.grant(4), 0.100)
    assert w.window == 2  # floored, never below
    for _ in range(8):
        w.on_settled(w.grant(2), 0.010)
    assert w.window > 2  # clean settles regrow
    assert w.shrinks >= 3


def test_push_window_grant_release_accounting():
    """grant() never exceeds window - inflight; release()/on_settled()
    free capacity; reset() re-ramps the pacing state but keeps flight
    accounting (in-flight chunks still settle correctly)."""
    w = specframe.PushWindow(initial=8, floor=2, ceiling=16)
    assert w.grant(6) == 6
    assert w.grant(6) == 2  # clipped to remaining room
    assert w.grant(6) == 0  # full
    w.release(2)
    assert w.inflight == 6
    assert w.grant(6) == 2
    w.reset()
    assert w.window == 2  # cold re-ramp from the floor
    assert w.inflight == 8  # accounting survived the reset
    w.on_settled(8, 0.005)
    assert w.inflight == 0


def test_push_window_min_base_guards_noise():
    """Micro-latency jitter on a quiet box (base well under min_base_s)
    must not read as 3x inflation: 0.1ms -> 0.5ms is noise, not
    congestion."""
    w = specframe.PushWindow(initial=8, floor=2, ceiling=16,
                             latency_factor=3.0, min_base_s=0.002)
    w.on_settled(w.grant(4), 0.0001)
    assert w.on_settled(w.grant(4), 0.0005)  # clean despite 5x base
    assert w.shrinks == 0


# ------------------------------------------------- pump drain economics
@pytest.mark.parametrize("rt_start", [dict(num_cpus=2)], indirect=True)
def test_pump_wakeups_are_o_drains_not_o_tasks(rt_start):
    """A queued single-peer burst reaches the executor pool through
    O(drains) batch handoffs and executor wakeups — never one wakeup per
    task or per wire message. (Drain counts are load-dependent; the
    invariant is wakeups << tasks and one claim pass per drain.)"""

    @ray_tpu.remote
    def noop(i):
        return i

    @ray_tpu.remote
    def probe():
        w = worker_mod.global_worker
        return (
            {k: v for k, v in w._stats.items() if k.startswith("pump_")},
            w.transit_stats()["pump"],
        )

    ray_tpu.get([noop.remote(i) for i in range(20)], timeout=120)  # warm
    before, _ = ray_tpu.get(probe.remote(), timeout=60)
    n = 400
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    after, pump = ray_tpu.get(probe.remote(), timeout=60)
    calls = after["pump_batch_calls"] - before["pump_batch_calls"]
    items = after["pump_batch_items"] - before["pump_batch_items"]
    wakeups = after["pump_exec_wakeups"] - before["pump_exec_wakeups"]
    assert items >= n  # every task rode a batch handoff
    assert calls <= items // 4, (calls, items)  # one pass per DRAIN
    assert wakeups <= n // 4, (wakeups, n)  # pool wakeups O(drains)
    assert pump["drains"] <= pump["msgs"]  # drains coalesce messages


def test_push_window_paces_live_burst(rt_start):
    """On a real cluster the driver's slots carry live windows: a burst
    settles them (settled ~ tasks), the window stays inside its
    floor/ceiling box, and the rt_push_window gauge sees the peer."""
    from ray_tpu._private.config import rt_config

    @ray_tpu.remote
    def noop(i):
        return i

    n = 300
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    w = worker_mod.global_worker
    push = w.transit_stats()["push_window"]
    assert push, "no push-window stats recorded"
    floor = int(rt_config.push_window_floor)
    ceiling = int(rt_config.push_window_ceiling)
    total_settled = 0
    for peer, s in push.items():
        assert floor <= s["window"] <= ceiling, (peer, s)
        assert s["peak"] <= ceiling
        total_settled += s["settled"]
    assert total_settled >= n


# -------------------------------------------------- multi-frame settling
def test_multi_frame_settle_one_wakeup(monkeypatch):
    """N coalesced reply frames already buffered on the driver's stream
    settle in ONE recv-loop wakeup: the drain parses them straight from
    the reader buffer (no per-frame coroutine hop), every future
    resolves, and the settle stats pin the economics."""

    async def run():
        reader = asyncio.StreamReader()
        writer_sink = []

        class _W:  # minimal writer stand-in (never used by the drain)
            def write(self, d):
                writer_sink.append(d)

            def close(self):
                pass

            async def drain(self):
                pass

        conn = protocol.Connection(reader, _W(), name="test")
        conn._settle_batching = True
        conn.start()
        futs = {}
        for cid in range(1, 7):
            conn._next_id = cid
            fut = asyncio.get_running_loop().create_future()
            conn._pending[cid] = fut
            futs[cid] = fut
        # Six single-reply frames land in the buffer as one TCP segment.
        blob = b"".join(
            protocol.encode_message({"i": cid, "r": 1, "rets": [cid]}, [])
            for cid in range(1, 7)
        )
        reader.feed_data(blob)
        await asyncio.wait_for(
            asyncio.gather(*futs.values()), timeout=5
        )
        for cid, fut in futs.items():
            h, frames = fut.result()
            assert h["rets"] == [cid]
        st = conn.settle_stats
        assert st["frames"] == 6
        assert st["wakeups"] == 1, st  # ONE loop wakeup settled all six
        assert st["drained"] == 5
        assert st["max_batch"] == 6
        await conn.close()

    asyncio.run(run())


def test_settle_batching_off_one_frame_per_wakeup():
    """Gate off: the recv loop settles exactly one frame per wakeup —
    the pre-round-16 loop, byte-identically (drained stays 0)."""

    async def run():
        reader = asyncio.StreamReader()

        class _W:
            def write(self, d):
                pass

            def close(self):
                pass

            async def drain(self):
                pass

        conn = protocol.Connection(reader, _W(), name="test")
        conn._settle_batching = False
        conn.start()
        futs = {}
        for cid in range(1, 5):
            fut = asyncio.get_running_loop().create_future()
            conn._pending[cid] = fut
            futs[cid] = fut
        reader.feed_data(b"".join(
            protocol.encode_message({"i": cid, "r": 1}, [])
            for cid in range(1, 5)
        ))
        await asyncio.wait_for(asyncio.gather(*futs.values()), timeout=5)
        st = conn.settle_stats
        assert st["frames"] == 4
        assert st["drained"] == 0, st
        await conn.close()

    asyncio.run(run())


def test_parse_buffered_partial_and_exact():
    """The buffer parser consumes exactly one complete message and
    reports None for any partial prefix — byte-boundary safety for the
    in-place drain."""
    msg = protocol.encode_message({"i": 9, "r": 1}, [b"abc", b"defg"])
    for cut in range(len(msg)):
        assert protocol._parse_buffered(bytearray(msg[:cut])) is None
    buf = bytearray(msg + b"tail")
    header, frames, consumed = protocol._parse_buffered(buf)
    assert header["i"] == 9 and frames == [b"abc", b"defg"]
    assert consumed == len(msg)


# --------------------------------------------------- pump-queue analysis
def test_pump_queue_phase_keeps_attribution_exhaustive():
    """The new pump-queue phase is carved OUT of reply-ack (their sum is
    the old reply-ack), pump-queue renders in PHASES, and
    named + residual == wall still holds exactly."""
    assert "pump-queue" in taskpath.PHASES
    tid = "ab" * 12
    t0 = 1000.0
    spans = [
        {"kind": "task", "cid": tid, "verb": "task.submit",
         "ts": t0, "dur": 0.001},
        {"kind": "task", "cid": tid, "verb": "task.queued",
         "ts": t0 + 0.001, "dur": 0.002, "outcome": "submit-queue"},
        {"kind": "task", "cid": tid, "verb": "task.serve",
         "ts": t0 + 0.004, "dur": 0.010},
        {"kind": "task", "cid": tid, "verb": "task.exec",
         "ts": t0 + 0.006, "dur": 0.004},
        {"kind": "task", "cid": tid, "verb": "task.pump_queue",
         "ts": t0 + 0.020, "dur": 0.015},
        {"kind": "task", "cid": tid, "verb": "task.push",
         "ts": t0 + 0.003, "dur": 0.040},
    ]
    for e in spans:
        e.setdefault("outcome", "ok")
    b = taskpath.task_breakdown(spans, tid)
    ph = b["phases"]
    assert ph["pump-queue"] == pytest.approx(0.015)
    # reply-ack = push - serve - reply-window - pump-queue
    assert ph["reply-ack"] == pytest.approx(0.040 - 0.010 - 0.015)
    named = sum(v for p, v in ph.items())
    assert named == pytest.approx(b["wall_s"])  # residual explicit
    # Rendering: the fixed-width table names the phase.
    assert "pump-queue" in taskpath.format_task_timeline(b)


# ------------------------------------------------------- gates-off parity
def test_gates_off_restore_fixed_fanout(monkeypatch):
    """RT_PUSH_WINDOW=0 / RT_PUMP_BATCH_DRAIN=0 / RT_SETTLE_BATCHING=0:
    no window objects ever attach to slots, the TCP recv loop never
    drains past one frame, and a burst completes identically."""
    monkeypatch.setenv("RT_PUSH_WINDOW", "0")
    monkeypatch.setenv("RT_PUMP_BATCH_DRAIN", "0")
    monkeypatch.setenv("RT_SETTLE_BATCHING", "0")
    ray_tpu.init(num_cpus=2)
    try:
        w = worker_mod.global_worker
        assert not w._push_window

        @ray_tpu.remote
        def noop(i):
            return i

        n = 150
        assert ray_tpu.get([noop.remote(i) for i in range(n)],
                           timeout=120) == list(range(n))
        assert w.transit_stats()["push_window"] == {}
        assert all(
            s.pwin is None
            for ls in w.leases.values() for s in ls.slots
        )
        for c in list(w.peers.values()) + [w.gcs]:
            st = getattr(c, "settle_stats", None)
            assert st is None or st["drained"] == 0, (c.name, st)
        assert w._stats["push_window_waits"] == 0
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------ faultpoint chaos
def test_push_window_faultpoint_degrades_not_breaks(rt_start):
    """worker.push.window error = that chunk pushes with the fixed
    fan-out (pacing is an optimization); drop = the slot's window
    cold-resets to its floor and re-ramps. Either way every task
    completes and no future is lost."""
    w = worker_mod.global_worker

    @ray_tpu.remote
    def noop(i):
        return i

    ray_tpu.get([noop.remote(i) for i in range(10)], timeout=120)  # warm
    fp.configure("worker.push.window:error:0.5:0:7")
    n = 120
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    st = fp.stats()
    assert sum(s["injected"] for s in st) > 0, st
    fp.configure("worker.push.window:drop:1.0:2:9")
    assert ray_tpu.get([noop.remote(i) for i in range(n)],
                       timeout=120) == list(range(n))
    assert sum(s["injected"] for s in fp.stats()) > 0
    # Windows (where still attached) came back inside the box.
    from ray_tpu._private.config import rt_config

    for ls in w.leases.values():
        for s in ls.slots:
            if s.pwin is not None:
                assert s.pwin.window >= int(rt_config.push_window_floor)
    fp.clear()
