"""HF Transformers Train integration.

Reference analog: ``python/ray/train/huggingface/transformers`` tests —
an HF Trainer inside a TorchTrainer train_fn reports metrics/checkpoints
through the Ray-style report callback.
"""
import math

import pytest

import ray_tpu

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture
def hf_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class TinyRegressor(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.lin = torch.nn.Linear(4, 1)

    def forward(self, x=None, labels=None):
        pred = self.lin(x).squeeze(-1)
        loss = torch.nn.functional.mse_loss(pred, labels)
        return {"loss": loss, "logits": pred}


class TinyData(torch.utils.data.Dataset):
    def __len__(self):
        return 16

    def __getitem__(self, i):
        g = torch.Generator().manual_seed(i)
        x = torch.randn(4, generator=g)
        return {"x": x, "labels": x.sum()}


def test_hf_trainer_reports_through_torch_trainer(hf_cluster, tmp_path):
    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def train_fn(config):
        from transformers import Trainer, TrainingArguments

        from ray_tpu.train.huggingface import prepare_trainer

        args = TrainingArguments(
            output_dir=config["out"],
            max_steps=4,
            per_device_train_batch_size=4,
            logging_steps=2,
            save_steps=4,
            report_to=[],
            use_cpu=True,
            disable_tqdm=True,
        )
        trainer = Trainer(
            model=TinyRegressor(), args=args, train_dataset=TinyData()
        )
        trainer = prepare_trainer(trainer)
        trainer = prepare_trainer(trainer)  # idempotent
        n_cbs = sum(
            type(cb).__name__ == "RayTrainReportCallback"
            for cb in trainer.callback_handler.callbacks
        )
        assert n_cbs == 1
        trainer.train()

    result = TorchTrainer(
        train_fn,
        train_loop_config={"out": str(tmp_path / "hf_out")},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="hf_e2e", storage_path=str(tmp_path)),
    ).fit()
    assert result.error is None
    assert math.isfinite(result.metrics.get("loss", result.metrics.get("step", 0)))
    # the HF save at step 4 surfaced as a train checkpoint
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        import os

        assert any("model" in f or "safetensors" in f for f in os.listdir(d))
