"""The native libraries must COMPILE whenever a toolchain is present.

Round 3 shipped a compile error in arena_store.cc that silently degraded the
whole object plane to the Python fallback store because every consumer treated
"build failed" as "toolchain unavailable" and skipped. This gate makes a
compile error a loud test FAILURE: a from-scratch `make` in a temp dir with
RT_NATIVE_WERROR=1 (the CI-strict mode from native/Makefile) must produce all
four shared libraries.

Reference analog: the Bazel build of src/ray/object_manager/plasma is a hard
CI gate in /root/reference (BUILD.bazel targets fail the build on any compile
error); this is our equivalent for the ctypes-loaded native plane.
"""
import os
import shutil
import subprocess

import pytest

from ray_tpu import native as rt_native

_NATIVE_DIR = os.path.dirname(os.path.abspath(rt_native.__file__))

_TARGETS = [
    "librt_native.so",
    "librt_sched.so",
    "librt_xfer.so",
    "librt_ring.so",
]


@pytest.mark.skipif(
    not rt_native.toolchain_available(), reason="no g++/make toolchain"
)
def test_native_libs_build_from_scratch_werror(tmp_path):
    build = tmp_path / "native"
    build.mkdir()
    shutil.copy(os.path.join(_NATIVE_DIR, "Makefile"), build / "Makefile")
    shutil.copytree(os.path.join(_NATIVE_DIR, "src"), build / "src")
    env = dict(os.environ, RT_NATIVE_WERROR="1")
    res = subprocess.run(
        ["make", "-C", str(build)],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert res.returncode == 0, (
        "native build FAILED (this is a compile error in the repo, not an "
        "environment problem):\n" + res.stderr[-4000:]
    )
    for t in _TARGETS:
        assert (build / t).exists(), f"{t} missing after successful make"


@pytest.mark.skipif(
    not rt_native.toolchain_available(), reason="no g++/make toolchain"
)
def test_checked_in_libs_not_stale():
    """The lazy in-tree rebuild must succeed too (exercises the loader path
    workers actually take), and the loader must report no compile errors."""
    lib = rt_native.load_library()
    assert rt_native.build_failure() is None, rt_native.build_failure()
    assert lib is not None
