"""Data layer tests (reference test model: ``python/ray/data/tests/``)."""
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture
def rt(rt_start):
    yield rt_start


def test_range_count_take(rt):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]
    assert ds.num_blocks() == 4


def test_map_filter_flatmap_fusion(rt):
    ds = (
        rd.range(20, parallelism=2)
        .map(lambda r: {"id": r["id"] * 2})
        .filter(lambda r: r["id"] % 4 == 0)
        .flat_map(lambda r: [{"v": r["id"]}, {"v": r["id"] + 1}])
    )
    vals = [r["v"] for r in ds.take_all()]
    assert vals[:4] == [0, 1, 4, 5]
    assert ds.count() == 20


def test_map_batches_numpy(rt):
    ds = rd.range(64, parallelism=2).map_batches(
        lambda b: {"sq": b["id"] ** 2}, batch_size=16
    )
    out = ds.take_batch(64)
    np.testing.assert_array_equal(out["sq"], np.arange(64) ** 2)


def test_aggregates_and_groupby(rt):
    ds = rd.from_items([
        {"k": i % 3, "v": float(i)} for i in range(12)
    ], parallelism=3)
    assert ds.sum("v") == sum(range(12))
    assert ds.min("v") == 0.0
    assert ds.max("v") == 11.0
    assert ds.mean("v") == pytest.approx(5.5)
    counts = ds.groupby("k").count().to_pandas()
    assert sorted(counts["k_count"]) == [4, 4, 4]
    sums = ds.groupby("k").sum("v").to_pandas().sort_values("k")
    assert list(sums["v_sum"]) == [18.0, 22.0, 26.0]


def test_sort_shuffle_repartition(rt):
    ds = rd.from_items([{"x": i} for i in [3, 1, 2, 5, 4]])
    assert [r["x"] for r in ds.sort("x").take_all()] == [1, 2, 3, 4, 5]
    assert [r["x"] for r in ds.sort("x", descending=True).take_all()] == [5, 4, 3, 2, 1]
    sh = ds.random_shuffle(seed=0)
    assert sorted(r["x"] for r in sh.take_all()) == [1, 2, 3, 4, 5]
    rp = ds.repartition(3)
    assert rp.num_blocks() == 3
    assert rp.count() == 5


def test_split_and_train_test(rt):
    ds = rd.range(10, parallelism=2)
    parts = ds.split(3)
    assert sum(p.count() for p in parts) == 10
    tr, te = ds.train_test_split(0.2)
    assert tr.count() == 8 and te.count() == 2


def test_zip_union_limit(rt):
    a = rd.from_items([{"a": i} for i in range(4)])
    b = rd.from_items([{"b": i * 10} for i in range(4)])
    z = a.zip(b)
    assert z.take(1)[0] == {"a": 0, "b": 0}
    u = a.union(a)
    assert u.count() == 8
    assert a.limit(2).count() == 2


def test_iter_batches_respects_batch_size(rt):
    ds = rd.range(50, parallelism=3)
    sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=16)]
    assert sizes == [16, 16, 16, 2]
    sizes = [len(b["id"])
             for b in ds.iter_batches(batch_size=16, drop_last=True)]
    assert sizes == [16, 16, 16]


def test_iter_jax_batches_device_and_sharding(rt):
    import jax

    ds = rd.range(32, parallelism=2)
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert len(batches) == 4
    assert isinstance(batches[0]["id"], jax.Array)
    # with an explicit data-parallel sharding over 4 devices
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    sh = NamedSharding(mesh, P("data"))
    batches = list(ds.iter_jax_batches(batch_size=8, sharding=sh))
    assert batches[0]["id"].sharding == sh


def test_tensor_columns_roundtrip(rt):
    arr = np.arange(24, dtype=np.float32).reshape(6, 4)
    ds = rd.from_numpy({"feat": arr})
    out = ds.take_batch(6)
    np.testing.assert_array_equal(out["feat"], arr)
    # >2-D tensors keep their full inner shape (images etc.)
    img = np.arange(2 * 3 * 4 * 5, dtype=np.float32).reshape(2, 3, 4, 5)
    out = rd.from_numpy({"img": img}).take_batch(2)
    assert out["img"].shape == (2, 3, 4, 5)
    np.testing.assert_array_equal(out["img"], img)


def test_equal_split_balances_rows(rt):
    # pathologically skewed blocks: equal=True must rebalance by rows
    a = rd.from_items([{"x": i} for i in range(10)], parallelism=1)
    b = rd.from_items([{"x": i} for i in range(10, 11)], parallelism=1)
    ds = a.union(b)  # blocks of 10 and 1 rows
    parts = ds.split(2, equal=True)
    counts = [p.count() for p in parts]
    assert counts == [5, 5], counts  # 11th row dropped for equality


def test_empty_tensor_column_ok(rt):
    ds = rd.from_numpy({"x": np.zeros((0, 4), np.float32)})
    assert ds.count() == 0


def test_schema_skips_empty_blocks(rt):
    ds = (rd.range(8, parallelism=4)
          .filter(lambda r: r["id"] >= 6)
          .map_batches(lambda b: {"v": b["id"]}, batch_size=8))
    assert ds.columns() == ["v"]


def test_aggregates_on_empty(rt):
    ds = rd.range(10).filter(lambda r: False)
    assert ds.sum("id") is None
    assert ds.min("id") is None
    assert ds.max("id") is None
    assert ds.mean("id") is None
    assert ds.std("id") is None


def test_file_roundtrip_parquet_csv_json(rt, tmp_path):
    ds = rd.from_items([{"x": i, "y": float(i) / 2} for i in range(10)])
    for fmt, reader in [
        ("parquet", rd.read_parquet),
        ("csv", rd.read_csv),
        ("json", rd.read_json),
    ]:
        path = str(tmp_path / fmt)
        getattr(ds, f"write_{fmt}")(path)
        back = reader(path)
        assert back.count() == 10
        assert back.sum("x") == 45


def test_columns_ops(rt):
    ds = rd.from_items([{"a": 1, "b": 2}])
    assert ds.select_columns(["a"]).columns() == ["a"]
    assert ds.drop_columns(["a"]).columns() == ["b"]
    assert ds.rename_columns({"a": "c"}).columns() == ["c", "b"]


def test_dataset_feeds_trainer(rt, tmp_path):
    """Dataset → JaxTrainer worker shards (reference: DataConfig sharding)."""
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig
    from ray_tpu import train as rt_train

    ds = rd.range(16, parallelism=4)

    def train_fn(config):
        shard = rt_train.get_dataset_shard("train")
        total = shard.sum("id") or 0
        rt_train.report({"total": total,
                         "rank": rt_train.get_context().get_world_rank()})

    res = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": ds},
    ).fit()
    assert res.error is None
    # shards partition the id space: rank 0's sum + rank 1's = 0..15 total
    assert res.metrics["total"] < sum(range(16))


def test_join_inner_and_left(rt_start):
    from ray_tpu import data

    users = data.from_items([
        {"uid": 1, "name": "ada"},
        {"uid": 2, "name": "bob"},
        {"uid": 3, "name": "cy"},
    ])
    orders = data.from_items([
        {"uid": 1, "amount": 10},
        {"uid": 1, "amount": 5},
        {"uid": 3, "amount": 7},
    ])
    inner = users.join(orders, on="uid").sort("amount").take_all()
    assert [(r["name"], r["amount"]) for r in inner] == [
        ("ada", 5), ("cy", 7), ("ada", 10),
    ]
    left = users.join(orders, on="uid", how="left").take_all()
    assert len(left) == 4  # bob kept with null amount
    assert any(r["name"] == "bob" and r["amount"] is None for r in left)
    with pytest.raises(ValueError):
        users.join(orders, on="uid", how="cross")


def test_actor_pool_map_batches(rt):
    """Callable-class UDFs run on a stateful actor pool: constructed once
    per actor, reused across blocks (reference: actor_pool_map_operator)."""
    rtd = rd

    class AddConst:
        def __init__(self, c):
            self.c = c
            self.constructions = getattr(AddConst, "_n", 0) + 1

        def __call__(self, batch):
            return {"x": batch["x"] + self.c}

    ds = rtd.from_items([{"x": i} for i in range(100)], parallelism=10)
    out = ds.map_batches(
        AddConst, batch_size=16, concurrency=2, fn_constructor_args=(5,)
    )
    vals = sorted(r["x"] for r in out.take_all())
    assert vals == [i + 5 for i in range(100)]


def test_actor_pool_state_reused_across_blocks(rt):
    """The pool has `concurrency` instances total — NOT one per block."""
    rtd = rd

    class Tagger:
        def __init__(self):
            import os
            import random

            self.tag = f"{os.getpid()}-{random.random()}"

        def __call__(self, batch):
            return {**batch, "tag": np.array([self.tag] * len(batch["x"]))}

    ds = rtd.from_items([{"x": i} for i in range(60)], parallelism=12)
    rows = ds.map_batches(Tagger, batch_size=5, concurrency=2).take_all()
    tags = {r["tag"] for r in rows}
    assert 1 <= len(tags) <= 2, tags  # 12 blocks, but at most 2 instances


def test_read_images(rt, tmp_path):
    rtd = rd
    from PIL import Image

    for i in range(4):
        Image.fromarray(
            (np.ones((8, 6, 3)) * (i * 40)).astype(np.uint8)
        ).save(tmp_path / f"img_{i}.png")
    ds = rtd.read_images(str(tmp_path), size=(4, 4))
    batches = list(ds.iter_batches(batch_size=4, batch_format="numpy"))
    imgs = np.concatenate([b["image"] for b in batches])
    assert imgs.shape == (4, 4, 4, 3)  # tensor shape survives via metadata
    assert imgs.dtype == np.uint8


def test_distributed_shuffle_driver_memory_flat(rt_cluster):
    """Barrier ops must NOT materialize the dataset in the driver
    (reference: hash_shuffle.py map->aggregator operators). Shuffle +
    groupby + sort a dataset much larger than any single block while
    asserting the driver's resident memory stays flat."""
    import os

    import numpy as np

    import ray_tpu
    from ray_tpu.data import range as rt_range

    def rss_mb():
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS"):
                    return int(line.split()[1]) / 1024
        return 0.0

    rt, cluster = rt_cluster
    n = 200_000  # ~a few MB per block x 16 blocks
    ds = rt_range(n, parallelism=16).map_batches(
        lambda b: {"id": b["id"], "k": b["id"] % 13, "v": b["id"] * 2},
        batch_size=50_000,
    )

    def barrier_pass():
        shuffled = ds.random_shuffle(seed=7)
        agg = shuffled.groupby("k").sum("v")
        rows = agg.take_all()
        assert len(rows) == 13
        assert sum(r["v_sum"] for r in rows) == 2 * (n * (n - 1)) // 2
        top = ds.sort("id", descending=True).take(1)
        assert top[0]["id"] == n - 1

    # Warmup pass FIRST: pymalloc/glibc arenas grown by earlier tests in
    # this process plateau here, so the measured pass sees steady-state
    # allocator behavior (cold-baseline measurement is order-dependent —
    # this test failed on some orderings of the suite with no data-layer
    # change at all). A real driver materialization leaks/copies on every
    # pass and still trips the bound.
    barrier_pass()
    base = rss_mb()
    barrier_pass()
    grown = rss_mb() - base
    # the dataset is ~n*3*8B ~ 5MB x several copies through a driver
    # materialization; flat means well under one full-dataset copy
    assert grown < 100, f"driver RSS grew {grown:.0f}MB during barrier ops"


def test_distributed_join(rt_cluster):
    import ray_tpu
    from ray_tpu.data import from_items

    left = from_items(
        [{"id": i, "a": i * 10} for i in range(500)], parallelism=4
    )
    right = from_items(
        [{"id": i, "b": i * 3} for i in range(0, 500, 2)], parallelism=3
    )
    j = left.join(right, on="id", how="inner")
    rows = j.take_all()
    assert len(rows) == 250
    for r in rows[:10]:
        assert r["a"] == r["id"] * 10 and r["b"] == r["id"] * 3
    outer = left.join(right, on="id", how="left").take_all()
    assert len(outer) == 500


def test_distributed_repartition_order_and_shuffle_determinism(rt_cluster):
    """Distributed repartition must preserve global row order (like the
    local path); random_shuffle(seed=) must reproduce across runs."""
    import ray_tpu
    from ray_tpu.data import range as rt_range

    ds = rt_range(1000, parallelism=7)
    rep = ds.repartition(4)
    ids = [r["id"] for r in rep.take_all()]
    assert ids == list(range(1000)), "repartition reordered rows"
    assert rep.num_blocks() == 4

    a = [r["id"] for r in ds.random_shuffle(seed=11).take_all()]
    b = [r["id"] for r in ds.random_shuffle(seed=11).take_all()]
    assert a == b, "seeded shuffle not reproducible"
    assert sorted(a) == list(range(1000))
    assert a != list(range(1000))


def test_transform_kwargs_validated_and_honored(rt):
    """Bogus kwargs raise TypeError (reference: Dataset.map validates);
    num_cpus/resources/concurrency actually shape execution."""
    import pytest as _pytest

    import ray_tpu
    from ray_tpu import data as rt_data

    ds = rt_data.range(20)
    with _pytest.raises(TypeError, match="unexpected keyword"):
        ds.map(lambda r: r, totally_bogus=1)
    with _pytest.raises(TypeError, match="unexpected keyword"):
        ds.filter(lambda r: True, num_cpu=1)  # typo'd kwarg
    with _pytest.raises(TypeError, match="unexpected keyword"):
        ds.map_batches(lambda b: b, wat=2)

    # resources are honored: demanding a resource no node has leaves the
    # stage unschedulable (bounded wait), proving the request reaches the
    # scheduler; a satisfiable request completes.
    out = ds.map(
        lambda r: {"id": r["id"] * 2}, num_cpus=0.01
    ).take_all()
    assert sorted(r["id"] for r in out) == sorted(2 * i for i in range(20))


def test_sort_empty_after_filter(rt):
    """Distributed sort of a fully-filtered (empty) dataset is valid and
    returns empty (regression: sample_bounds np.concatenate([]) raised)."""
    from ray_tpu import data as rt_data

    out = rt_data.range(50).filter(lambda r: False).sort("id").take_all()
    assert out == []


def test_read_write_sql_sqlite(rt, tmp_path):
    """DBAPI SQL datasource against stdlib sqlite3 (reference:
    data/datasource/sql_datasource.py)."""
    import sqlite3

    from ray_tpu import data as rt_data

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE pts (x INTEGER, label TEXT)")
    conn.executemany(
        "INSERT INTO pts VALUES (?, ?)",
        [(i, f"l{i % 3}") for i in range(30)],
    )
    conn.commit()
    conn.close()

    def factory(path=db):
        import sqlite3 as s

        return s.connect(path)

    ds = rt_data.read_sql("SELECT x, label FROM pts", factory)
    rows = ds.take_all()
    assert len(rows) == 30 and {r["label"] for r in rows} == {"l0", "l1", "l2"}

    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE out (x INTEGER, label TEXT)")
    conn.commit()
    conn.close()
    n = rt_data.write_sql(ds.filter(lambda r: r["x"] < 10), "out", factory)
    assert n == 10
    conn = sqlite3.connect(db)
    assert conn.execute("SELECT COUNT(*) FROM out").fetchone()[0] == 10
    conn.close()


def test_read_webdataset(rt, tmp_path):
    """WebDataset tar shards group files by key into rows (reference:
    data/datasource/webdataset_datasource.py)."""
    import io
    import tarfile

    from ray_tpu import data as rt_data

    shard = str(tmp_path / "shard-000.tar")
    with tarfile.open(shard, "w") as tf:
        for i in range(4):
            for suffix, payload in (("txt", f"caption {i}"),
                                    ("cls", str(i % 2))):
                data_b = payload.encode()
                info = tarfile.TarInfo(f"sample{i:04d}.{suffix}")
                info.size = len(data_b)
                tf.addfile(info, io.BytesIO(data_b))
    rows = rt_data.read_webdataset(shard).take_all()
    assert len(rows) == 4
    assert rows[0]["__key__"] == "sample0000"
    assert rows[0]["txt"] == b"caption 0"
    assert {r["cls"] for r in rows} == {b"0", b"1"}


def test_optional_datasources_gated(rt):
    """Missing optional client libs raise a helpful ImportError, not a
    bare ModuleNotFoundError at call time."""
    import pytest as _pytest

    from ray_tpu import data as rt_data

    import importlib.util as ilu

    for fn, args, lib in (
        (rt_data.read_lance, ("/tmp/x.lance",), "lance"),
        (rt_data.read_iceberg, ("db.t",), "pyiceberg"),
        (rt_data.read_bigquery, ("SELECT 1",), "google.cloud.bigquery"),
        (rt_data.read_mongo, ("mongodb://x", "db", "c"), "pymongo"),
    ):
        if ilu.find_spec(lib.split(".")[0]) is not None:
            continue  # lib installed here: the gate isn't reachable
        with _pytest.raises(ImportError, match="optional"):
            fn(*args)


def test_read_sql_sharded(rt, tmp_path):
    """parallelism > 1 shards via a projected row number (window functions
    are illegal in WHERE)."""
    import sqlite3

    from ray_tpu import data as rt_data

    db = str(tmp_path / "s.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE t (x INTEGER)")
    conn.executemany("INSERT INTO t VALUES (?)", [(i,) for i in range(40)])
    conn.commit()
    conn.close()

    def factory(path=db):
        import sqlite3 as s

        return s.connect(path)

    rows = rt_data.read_sql(
        "SELECT x FROM t", factory, parallelism=3, order_by="x"
    ).take_all()
    assert sorted(r["x"] for r in rows) == list(range(40))

    # Sharding without a total order is refused loudly: row numbering is
    # only stable across the per-shard re-runs under an ORDER BY.
    with pytest.raises(ValueError, match="order_by"):
        rt_data.read_sql("SELECT x FROM t", factory, parallelism=3)


def test_iter_tf_batches_and_to_tf(rt):
    """TF feed paths (reference: iter_tf_batches / to_tf): tensors come out
    typed and batched; to_tf trains a keras model end-to-end."""
    import numpy as np
    import tensorflow as tf

    rng = np.random.RandomState(0)
    X = rng.randn(64, 3).astype(np.float32)
    y = (X.sum(1, keepdims=True) > 0).astype(np.float32)
    ds = rd.from_numpy({"x": X, "y": y}, parallelism=4)

    batches = list(ds.iter_tf_batches(batch_size=16))
    assert len(batches) == 4
    assert isinstance(batches[0]["x"], tf.Tensor)
    assert batches[0]["x"].shape == (16, 3)

    tfds = ds.to_tf("x", "y", batch_size=16)
    f, l = next(iter(tfds))
    assert f.shape == (16, 3) and l.shape == (16, 1)
    model = tf.keras.Sequential([
        tf.keras.layers.Dense(4, activation="relu"),
        tf.keras.layers.Dense(1),
    ])
    model.compile(optimizer="sgd", loss="mse")
    hist = model.fit(tfds, epochs=1, verbose=0)
    assert np.isfinite(hist.history["loss"][0])
    # dict-mode: list columns yield dict structures
    tfds2 = ds.to_tf(["x"], ["y"], batch_size=32)
    f2, l2 = next(iter(tfds2))
    assert set(f2) == {"x"} and set(l2) == {"y"}


def test_tfrecords_roundtrip(rt, tmp_path):
    """write_tfrecords -> read_tfrecords round trip (reference:
    Dataset.write_tfrecords / ray.data.read_tfrecords): int64/float/bytes
    feature mapping, multi-value lists, schema preserved by type."""
    import numpy as np

    rows = [
        {"i": 7, "f": 1.5, "s": "hello", "vec": np.array([1.0, 2.0, 3.0])},
        {"i": 8, "f": 2.5, "s": "world", "vec": np.array([4.0, 5.0, 6.0])},
    ]
    ds = rd.from_items(rows, parallelism=2)
    out_dir = str(tmp_path / "tfr")
    ds.write_tfrecords(out_dir)
    import os

    files = [f for f in os.listdir(out_dir) if f.endswith(".tfrecord")]
    assert files
    back = rd.read_tfrecords(
        [os.path.join(out_dir, f) for f in sorted(files)]
    )
    got = sorted(back.take_all(), key=lambda r: r["i"])
    assert [r["i"] for r in got] == [7, 8]
    assert got[0]["s"] == b"hello"  # bytes features stay bytes
    assert abs(got[1]["f"] - 2.5) < 1e-6
    assert np.allclose(got[0]["vec"], [1.0, 2.0, 3.0])
