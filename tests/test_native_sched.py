"""Native C++ cluster scheduler: resource accounting + best-node policies.

Mirrors the reference's scheduler tests
(src/ray/raylet/scheduling/tests/ — ClusterResourceScheduler driven purely
in-memory with synthetic node resources) against the ctypes-wrapped
ray_tpu/native/src/sched.cc, plus a decision-parity fuzz against the head's
Python fallback policy and an end-to-end check that the head keeps its
Python mirror and the native view consistent.
"""
import random

import pytest

from ray_tpu.native import sched as native_sched


@pytest.fixture
def ns():
    s = native_sched.create()
    if s is None:
        pytest.skip("native toolchain unavailable")
    return s


def test_accounting_and_fit(ns):
    ns.add_node("n1", {"CPU": 4, "TPU": 8}, {"zone": "a"})
    ns.add_node("n2", {"CPU": 8}, {"zone": "b"})
    assert ns.num_nodes() == 2
    assert ns.fits("n1", {"CPU": 4})
    assert not ns.fits("n1", {"CPU": 4.5})
    ns.acquire("n1", {"CPU": 3.5})
    assert abs(ns.available("n1", "CPU") - 0.5) < 1e-12
    ns.release("n1", {"CPU": 3.5})
    assert ns.available("n1", "CPU") == 4.0
    # unknown resources read as 0, unknown nodes as -1
    assert ns.available("n2", "TPU") == 0.0
    assert ns.available("ghost", "CPU") == -1.0


def test_fixed_point_no_drift(ns):
    """0.1 is inexact in binary floats; fixed-point accounting must return to
    exactly the registered total after many acquire/release cycles
    (reference rationale: FixedPoint in common/scheduling/fixed_point.h)."""
    ns.add_node("n", {"CPU": 8})
    for _ in range(10_000):
        ns.acquire("n", {"CPU": 0.1})
        ns.release("n", {"CPU": 0.1})
    assert ns.available("n", "CPU") == 8.0


def test_policies(ns):
    ns.add_node("n1", {"CPU": 4, "TPU": 8}, {"zone": "a"})
    ns.add_node("n2", {"CPU": 8}, {"zone": "b"})
    # pack: min sum-of-available (n2: 8 < n1: 12)
    assert ns.best_node({"CPU": 2}) == "n2"
    # compound demand only n1 satisfies
    assert ns.best_node({"CPU": 1, "TPU": 1}) == "n1"
    # labels / hard node affinity / soft avoid
    assert ns.best_node({"CPU": 1}, labels={"zone": "a"}) == "n1"
    assert ns.best_node({"CPU": 1}, labels={"zone": "nope"}) is None
    assert ns.best_node({"CPU": 1}, affinity_node="n1") == "n1"
    assert ns.best_node({"CPU": 1}, avoid=["n2"]) == "n1"
    # avoid is soft: when only the avoided node fits, it is still used
    assert ns.best_node({"CPU": 6}, avoid=["n2"]) == "n2"
    # spread round-robins over fitting nodes
    picks = {ns.best_node({"CPU": 1}, spread=True) for _ in range(4)}
    assert picks == {"n1", "n2"}
    # dead nodes drop out; nothing fits -> None
    ns.set_alive("n1", False)
    assert ns.best_node({"TPU": 1}) is None
    ns.set_alive("n1", True)
    assert ns.best_node({"TPU": 1}) == "n1"


def test_node_reregistration_resets(ns):
    ns.add_node("n", {"CPU": 4})
    ns.acquire("n", {"CPU": 3})
    ns.add_node("n", {"CPU": 16})  # re-register with new shape
    assert ns.available("n", "CPU") == 16.0
    assert ns.num_nodes() == 1
    ns.remove_node("n")
    assert ns.num_nodes() == 0


def _python_pick(head, need, strategy, avoid=None):
    """Drive the head's Python fallback path."""
    saved, head._nsched = head._nsched, None
    try:
        return head._pick_node(need, strategy, avoid)
    finally:
        head._nsched = saved


def test_parity_with_python_policy(ns):
    """Fuzz: the native decision matches the head's Python fallback on the
    same cluster state (both paths must be interchangeable)."""
    from ray_tpu._private.gcs import HeadService, NodeInfo

    head = HeadService.__new__(HeadService)
    head.nodes = {}
    head.pgs = {}
    head.pg_reserved = {}
    head._schedule_rr = 0
    head._nsched = None

    rng = random.Random(7)
    for i in range(12):
        res = {"CPU": rng.choice([2, 4, 8])}
        if rng.random() < 0.5:
            res["TPU"] = rng.choice([4, 8])
        labels = {"zone": rng.choice(["a", "b", "c"])}
        nid = f"node-{i:02d}"
        head.nodes[nid] = NodeInfo(
            node_id=nid, addr=("127.0.0.1", 0), resources=dict(res),
            available=dict(res), labels=dict(labels), conn=None,
        )
        ns.add_node(nid, res, labels)

    for _ in range(300):
        need = {"CPU": rng.choice([0.5, 1, 2, 4])}
        if rng.random() < 0.3:
            need["TPU"] = rng.choice([1, 4])
        strategy = {}
        if rng.random() < 0.25:
            strategy["labels"] = {"zone": rng.choice(["a", "b", "c"])}
        if rng.random() < 0.1:
            strategy["node_id"] = rng.choice(list(head.nodes))
        avoid = (
            set(rng.sample(list(head.nodes), 2)) if rng.random() < 0.2 else None
        )
        py = _python_pick(head, need, strategy, avoid)
        nat = ns.best_node(
            need,
            affinity_node=strategy.get("node_id"),
            labels=strategy.get("labels"),
            avoid=avoid or (),
        )
        assert (py.node_id if py else None) == nat, (need, strategy, avoid)
        if py is not None and rng.random() < 0.7:
            # acquire on both sides; sometimes release later
            from ray_tpu._private.gcs import _acquire, _release

            _acquire(py.available, need)
            ns.acquire(py.node_id, need)
            if rng.random() < 0.5:
                _release(py.available, need)
                ns.release(py.node_id, need)


@pytest.mark.parametrize(
    "rt_cluster", [dict(num_cpus=2, num_nodes=2)], indirect=True
)
def test_head_mirror_consistency(rt_cluster):
    """After real task/actor/PG traffic, the head's native availability view
    equals the Python mirror for every alive node."""
    rt, cluster = rt_cluster
    if cluster.head._nsched is None:
        pytest.skip("native scheduler unavailable")

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get([f.remote(i) for i in range(20)]) == list(range(1, 21))

    @rt.remote
    class A:
        def ping(self):
            return "pong"

    actors = [A.options(num_cpus=1).remote() for _ in range(2)]
    assert rt.get([a.ping.remote() for a in actors]) == ["pong", "pong"]

    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready()

    head = cluster.head
    for node in head.nodes.values():
        if not node.alive:
            continue
        for res, avail in node.available.items():
            nat = head._nsched.available(node.node_id, res)
            assert abs(nat - avail) < 1e-6, (node.node_id, res, nat, avail)

    remove_placement_group(pg)
    for a in actors:
        rt.kill(a)


@pytest.mark.parametrize("rt_cluster", [dict(num_cpus=2, num_nodes=1)],
                         indirect=True)
def test_pg_removed_with_outstanding_lease(rt_cluster):
    """Removing a PG while a leased task still runs inside a bundle must
    neither crash the lease release nor leak/oversubscribe node resources."""
    import time

    from ray_tpu.util.placement_group import (
        placement_group,
        remove_placement_group,
    )
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy,
    )

    rt, cluster = rt_cluster
    head = cluster.head

    @rt.remote
    def slow():
        time.sleep(1.5)
        return "done"

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready()
    ref = slow.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg, 0)
    ).remote()
    time.sleep(0.4)  # the task is running inside the bundle
    remove_placement_group(pg)
    assert rt.get(ref, timeout=10) == "done"
    # lease reaper returns the idle slot ~0.75s after the task finishes
    deadline = time.monotonic() + 5.0
    node = next(n for n in head.nodes.values() if n.alive)
    while time.monotonic() < deadline:
        if abs(node.available.get("CPU", 0) - 2.0) < 1e-6:
            break
        time.sleep(0.1)
    assert abs(node.available.get("CPU", 0) - 2.0) < 1e-6, node.available
    if head._nsched is not None:
        assert abs(head._nsched.available(node.node_id, "CPU") - 2.0) < 1e-6
