"""SAC (continuous control) and MARWIL/BC (offline) algorithms.

Reference analog: ``rllib/algorithms/sac/tests`` and
``rllib/algorithms/marwil|bc/tests`` — short learning/improvement runs on
toy problems plus checkpoint roundtrips.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import BCConfig, MARWILConfig, SACConfig


class TargetReachEnv:
    """1-step continuous env: reward = -(a - 0.5)^2 per dim. The optimal
    squashed-gaussian policy concentrates at a=0.5, return -> 0."""

    class _Space:
        def __init__(self, low, high, shape):
            self.low = np.full(shape, low, np.float32)
            self.high = np.full(shape, high, np.float32)
            self.shape = shape

    def __init__(self):
        self.observation_space = self._Space(-1, 1, (3,))
        self.action_space = self._Space(-1, 1, (1,))
        self._rng = np.random.RandomState(0)

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        return np.zeros(3, np.float32), {}

    def step(self, action):
        a = np.asarray(action, np.float32).ravel()
        reward = -float(np.sum((a - 0.5) ** 2))
        return np.zeros(3, np.float32), reward, True, False, {}

    def close(self):
        pass


@pytest.fixture
def rl_cluster():
    ray_tpu.init(num_cpus=6)
    yield
    ray_tpu.shutdown()


def _sac_config():
    return (
        SACConfig()
        .environment(env_creator=TargetReachEnv)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .debugging(seed=0)
        .training(lr=3e-3)
    )


def test_sac_learns_target(rl_cluster):
    cfg = _sac_config()
    cfg.min_replay_size = 200
    cfg.updates_per_step = 32
    algo = cfg.build_algo()
    try:
        first, last = None, None
        for _ in range(20):
            r = algo.train()
            if first is None and np.isfinite(r["episode_return_mean"]):
                first = r["episode_return_mean"]
            last = r["episode_return_mean"]
        # optimal return is 0; random tanh actions average about -0.58
        assert last > -0.25, f"SAC did not improve: first={first} last={last}"
        assert "alpha" in r and r["alpha"] > 0
    finally:
        algo.stop()


class WideBoundsEnv(TargetReachEnv):
    """Bounds [-2, 2], optimum at a=1.5 — unreachable unless the runner
    rescales tanh actions to the env's action space."""

    def __init__(self):
        super().__init__()
        self.action_space = self._Space(-2, 2, (1,))

    def step(self, action):
        a = np.asarray(action, np.float32).ravel()
        reward = -float(np.sum((a - 1.5) ** 2))
        return np.zeros(3, np.float32), reward, True, False, {}


def test_sac_rescales_actions_to_env_bounds(rl_cluster):
    cfg = (
        SACConfig()
        .environment(env_creator=WideBoundsEnv)
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=32)
        .debugging(seed=0)
        .training(lr=3e-3)
    )
    cfg.min_replay_size = 200
    cfg.updates_per_step = 32
    algo = cfg.build_algo()
    try:
        last = None
        for _ in range(20):
            last = algo.train()["episode_return_mean"]
        # without rescaling the best reachable return is -(1.5-1)^2 = -0.25
        assert last > -0.2, f"actions not rescaled to env bounds: {last}"
    finally:
        algo.stop()


def test_sac_rejects_discrete_env(rl_cluster):
    with pytest.raises(ValueError, match="continuous"):
        SACConfig().environment("CartPole-v1").build_algo()


def test_sac_checkpoint_roundtrip(rl_cluster, tmp_path):
    import jax

    cfg = _sac_config()
    cfg.min_replay_size = 100
    cfg.updates_per_step = 4
    algo = cfg.build_algo()
    try:
        for _ in range(3):
            algo.train()
        path = algo.save(str(tmp_path / "sac_ckpt"))
        w0 = algo.get_weights()
        algo2 = cfg.build_algo()
        try:
            algo2.restore(path)
            w1 = algo2.get_weights()
            for a, b in zip(jax.tree.leaves(w0), jax.tree.leaves(w1)):
                np.testing.assert_array_equal(a, b)
            assert algo2.iteration == algo.iteration
        finally:
            algo2.stop()
    finally:
        algo.stop()


# ----------------------------------------------------------------- offline


def _cartpole_expert_episodes(n_episodes=30, seed=0):
    """Scripted CartPole expert (push toward the pole's fall direction)."""
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    episodes = []
    for ep in range(n_episodes):
        obs, _ = env.reset(seed=seed * 1000 + ep)
        ep_obs, ep_act, ep_rew = [], [], []
        done = False
        t = 0
        while not done and t < 200:
            angle, ang_vel = obs[2], obs[3]
            action = 1 if (angle + 0.5 * ang_vel) > 0 else 0
            ep_obs.append(np.asarray(obs, np.float32))
            ep_act.append(action)
            nobs, rew, term, trunc, _ = env.step(action)
            ep_rew.append(float(rew))
            obs = nobs
            done = term or trunc
            t += 1
        episodes.append({
            "obs": np.stack(ep_obs),
            "actions": np.asarray(ep_act, np.int64),
            "rewards": np.asarray(ep_rew, np.float32),
        })
    env.close()
    return episodes


def test_bc_clones_cartpole_expert(rl_cluster):
    episodes = _cartpole_expert_episodes()
    cfg = (
        BCConfig()
        .environment("CartPole-v1")
        .env_runners(num_env_runners=2, num_envs_per_env_runner=4,
                     rollout_fragment_length=128)
        .debugging(seed=0)
        .training(lr=3e-3)
        .offline_data(episodes=episodes)
    )
    algo = cfg.build_algo()
    try:
        last = None
        for _ in range(12):
            r = algo.train()
            last = r
        # scripted expert scores ~180+; random policy ~20
        assert last["episode_return_mean"] > 60, last
        assert last["num_offline_transitions"] > 1000
    finally:
        algo.stop()


def test_marwil_runs_without_env():
    """Offline-only: no env configured, loss decreases on the data."""
    episodes = _cartpole_expert_episodes(n_episodes=10)
    cfg = MARWILConfig().debugging(seed=0).offline_data(episodes=episodes)
    cfg.updates_per_step = 16
    algo = cfg.build_algo()
    first = algo.training_step()["total_loss"]
    for _ in range(8):
        m = algo.training_step()
    assert m["total_loss"] < first
    # no eval env: train() must still work and report nan return
    r = algo.train()
    assert np.isnan(r["episode_return_mean"])


def test_marwil_dataset_input(rl_cluster):
    """Offline episodes arriving through the Data layer."""
    from ray_tpu import data as rt_data

    episodes = [
        {
            "obs": ep["obs"].tolist(),       # arrow-friendly nested lists
            "actions": ep["actions"].tolist(),
            "rewards": ep["rewards"].tolist(),
        }
        for ep in _cartpole_expert_episodes(n_episodes=6)
    ]
    ds = rt_data.from_items(episodes)
    cfg = MARWILConfig().debugging(seed=0).offline_data(dataset=ds)
    algo = cfg.build_algo()
    m = algo.training_step()
    assert m["num_offline_transitions"] > 100


def _pointmass_episodes(n_episodes=20, T=40, seed=0):
    """1-D regulator: x' = x + 0.1 a, r = -x'^2; behavior policy is a noisy
    expert (a = -clip(10 x, -1, 1) + noise). Good offline algorithms
    extract the de-noised regulator."""
    rng = np.random.RandomState(seed)
    eps = []
    for _ in range(n_episodes):
        x = rng.uniform(-1, 1)
        obs, acts, rews = [[x]], [], []
        for _ in range(T):
            a = float(np.clip(-10 * x, -1, 1) + rng.normal(0, 0.3))
            a = float(np.clip(a, -1, 1))
            x = x + 0.1 * a
            obs.append([x])
            acts.append([a])
            rews.append(-x * x)
        eps.append({
            "obs": np.asarray(obs, np.float32),
            "actions": np.asarray(acts, np.float32),
            "rewards": np.asarray(rews, np.float32),
            "terminated": False,
        })
    return eps


def test_iql_learns_regulator_offline():
    """IQL: expectile value + AWR extraction improves on the data without
    ever querying out-of-distribution actions."""
    from ray_tpu.rllib import IQLConfig
    from ray_tpu.rllib import module as rl_module

    cfg = IQLConfig().debugging(seed=0).offline_data(
        episodes=_pointmass_episodes()
    )
    cfg.updates_per_step = 64
    algo = cfg.build_algo()
    first = algo.training_step()
    for _ in range(12):
        m = algo.training_step()
    assert m["critic_loss"] < first["critic_loss"]
    # extracted policy regulates: mean action opposes the state
    import jax.numpy as jnp

    mean, _ = rl_module.forward_policy(
        algo.pi_params, algo.module_config, jnp.asarray([[0.5], [-0.5]])
    ), None
    mean = np.asarray(mean[0] if isinstance(mean, tuple) else mean)
    acts = np.tanh(mean[:, :1]) if mean.shape[-1] > 1 else np.tanh(mean)
    assert acts[0, 0] < 0 < acts[1, 0], f"policy not regulating: {acts}"


def test_cql_learns_conservative_critic_offline():
    """CQL: bellman + conservative penalty both optimize; the conservative
    gap (logsumexp - data Q) shrinks as OOD actions get pushed down."""
    from ray_tpu.rllib import CQLConfig

    cfg = CQLConfig().debugging(seed=0).offline_data(
        episodes=_pointmass_episodes()
    )
    cfg.updates_per_step = 48
    algo = cfg.build_algo()
    first = algo.training_step()
    for _ in range(10):
        m = algo.training_step()
    assert m["conservative_gap"] < first["conservative_gap"]
    assert np.isfinite(m["critic_loss"])


def test_cql_iql_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib import IQLConfig

    cfg = IQLConfig().debugging(seed=0).offline_data(
        episodes=_pointmass_episodes(n_episodes=4)
    )
    cfg.updates_per_step = 4
    algo = cfg.build_algo()
    algo.training_step()
    p = algo.save(str(tmp_path / "ck"))
    algo2 = IQLConfig().debugging(seed=1).offline_data(
        episodes=_pointmass_episodes(n_episodes=4)
    ).build_algo()
    algo2.restore(p)
    import jax

    a = jax.tree.leaves(algo.pi_params)
    b = jax.tree.leaves(algo2.pi_params)
    assert all(np.allclose(x, y) for x, y in zip(a, b))
