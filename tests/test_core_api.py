"""Core task/object API tests (reference analog: python/ray/tests/test_basic*.py)."""
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError, TaskError


def test_put_get(rt_start):
    ref = ray_tpu.put(42)
    assert ray_tpu.get(ref) == 42
    ref2 = ray_tpu.put({"a": [1, 2, 3], "b": "x"})
    assert ray_tpu.get(ref2) == {"a": [1, 2, 3], "b": "x"}


def test_put_get_large_numpy(rt_start):
    arr = np.arange(1_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_simple_task(rt_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_ref_args(rt_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    x = ray_tpu.put(10)
    y = add.remote(x, 5)
    z = add.remote(y, y)
    assert ray_tpu.get(z) == 30


def test_task_chain_many(rt_start):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = ray_tpu.put(0)
    for _ in range(20):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 20


def test_many_parallel_tasks(rt_start):
    @ray_tpu.remote
    def f(i):
        return i * i

    refs = [f.remote(i) for i in range(200)]
    assert ray_tpu.get(refs) == [i * i for i in range(200)]


def test_task_error_propagates(rt_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_num_returns(rt_start):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_get_timeout(rt_start):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    ref = slow.remote()
    with pytest.raises(GetTimeoutError):
        ray_tpu.get(ref, timeout=0.2)


def test_wait(rt_start):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    fast = sleepy.remote(0.05)
    slow = sleepy.remote(2.0)
    ready, not_ready = ray_tpu.wait([fast, slow], num_returns=1, timeout=5)
    assert ready == [fast]
    assert not_ready == [slow]


def test_nested_tasks(rt_start):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(10)) == 21


def test_large_return_via_shm(rt_start):
    @ray_tpu.remote
    def big():
        return np.ones((512, 1024), dtype=np.float32)

    out = ray_tpu.get(big.remote())
    assert out.shape == (512, 1024)
    assert out.dtype == np.float32
    assert float(out.sum()) == 512 * 1024


def test_options_override(rt_start):
    @ray_tpu.remote
    def f():
        return "ok"

    assert ray_tpu.get(f.options(num_cpus=2, name="custom").remote()) == "ok"


def test_runtime_env_env_vars(rt_start):
    import os as _os

    @ray_tpu.remote(runtime_env={"env_vars": {"RT_TEST_VAR": "hello"}})
    def read_env():
        return _os.environ.get("RT_TEST_VAR")

    assert ray_tpu.get(read_env.remote()) == "hello"


def test_cluster_resources(rt_start):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) >= 4


def test_runtime_context(rt_start):
    ctx = ray_tpu.get_runtime_context()
    assert ctx.is_driver
    assert ctx.get_job_id()

    @ray_tpu.remote
    def in_task():
        c = ray_tpu.get_runtime_context()
        return (c.is_driver, c.get_task_id() is not None)

    assert ray_tpu.get(in_task.remote()) == (False, True)


def test_put_nested_ref_pinned(rt_start):
    """Regression: a ref nested in a put() value pins the inner object."""
    import gc

    inner = ray_tpu.put(123)
    outer = ray_tpu.put([inner])
    del inner
    gc.collect()
    time.sleep(0.2)
    inner_again = ray_tpu.get(outer)[0]
    assert ray_tpu.get(inner_again, timeout=10) == 123


def test_util_queue(rt_start):
    from ray_tpu.util.queue import Empty, Full, Queue

    q = Queue(maxsize=2)
    try:
        q.put(1)
        q.put(2)
        assert q.qsize() == 2 and q.full()
        with pytest.raises(Full):
            q.put_nowait(3)
        assert q.get() == 1
        assert q.get() == 2
        assert q.empty()
        with pytest.raises(Empty):
            q.get_nowait()
        with pytest.raises(Empty):
            q.get(timeout=0.2)

        # producer/consumer across tasks (handle pickles)
        @ray_tpu.remote
        def produce(queue, n):
            for i in range(n):
                queue.put(i * 10)
            return True

        ref = produce.remote(q, 4)
        got = [q.get(timeout=30) for _ in range(4)]
        assert got == [0, 10, 20, 30]
        assert ray_tpu.get(ref)
    finally:
        q.shutdown()


def test_util_actor_pool(rt_start):
    from ray_tpu.util.actor_pool import ActorPool

    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            import time as _t

            _t.sleep(0.01 * (x % 3))
            return x * x

    pool = ActorPool([Sq.remote() for _ in range(3)])
    assert list(pool.map(lambda a, v: a.sq.remote(v), range(8))) == [
        v * v for v in range(8)
    ]
    unordered = list(
        pool.map_unordered(lambda a, v: a.sq.remote(v), range(8))
    )
    assert sorted(unordered) == sorted(v * v for v in range(8))
    # submit/get_next interleaving
    pool.submit(lambda a, v: a.sq.remote(v), 9)
    pool.submit(lambda a, v: a.sq.remote(v), 10)
    assert pool.get_next() == 81
    assert pool.get_next() == 100
    assert not pool.has_next()


# ------------------------------------------------------- streaming generators


def test_streaming_generator_task(rt_start):
    """num_returns="streaming": the task yields, the driver iterates refs
    (reference: streaming generator returns, task_manager.h)."""
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * i

    out = [ray_tpu.get(ref) for ref in gen.remote(6)]
    assert out == [0, 1, 4, 9, 16, 25]


def test_streaming_items_arrive_before_completion(rt_start):
    """Items are consumable while the generator is still running."""
    import time

    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        for i in range(4):
            yield i
            time.sleep(0.4)

    t0 = time.monotonic()
    it = slow_gen.remote()
    first = ray_tpu.get(next(it))
    first_latency = time.monotonic() - t0
    assert first == 0
    # total runtime is ~1.6s; the first item must arrive far sooner
    assert first_latency < 1.0, first_latency
    assert [ray_tpu.get(r) for r in it] == [1, 2, 3]


def test_streaming_large_items(rt_start):
    import numpy as np

    @ray_tpu.remote(num_returns="streaming")
    def big_gen():
        for i in range(3):
            yield np.full(100_000, float(i))

    outs = [ray_tpu.get(r) for r in big_gen.remote()]
    assert [float(o[0]) for o in outs] == [0.0, 1.0, 2.0]
    assert all(o.shape == (100_000,) for o in outs)


def test_streaming_error_mid_stream(rt_start):
    @ray_tpu.remote(num_returns="streaming")
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("stream broke")

    it = bad_gen.remote()
    assert ray_tpu.get(next(it)) == 1
    assert ray_tpu.get(next(it)) == 2
    with pytest.raises(TaskError, match="stream broke"):
        ray_tpu.get(next(it))
    with pytest.raises(StopIteration):
        next(it)


def test_streaming_requires_generator(rt_start):
    @ray_tpu.remote(num_returns="streaming")
    def not_a_gen():
        return 42

    it = not_a_gen.remote()
    with pytest.raises(TaskError, match="generator"):
        ray_tpu.get(next(it))


def test_streaming_flow_control(rt_start):
    """A fast producer may only run _STREAM_WINDOW items ahead of the
    consumer: the owner's memory stays bounded."""
    import time

    from ray_tpu._private.worker import CoreWorker, get_global_worker

    @ray_tpu.remote(num_returns="streaming")
    def firehose():
        for i in range(200):
            yield i

    w = get_global_worker()
    it = firehose.remote()
    first = ray_tpu.get(next(it))
    assert first == 0
    time.sleep(1.0)  # producer would finish instantly without the window
    tid = it._task_id.hex()
    rec = w._task_streams.get(tid)
    assert rec is not None and rec["count"] is None  # still throttled
    assert rec["produced"] <= 1 + CoreWorker._STREAM_WINDOW + 1
    # draining completes the stream
    rest = [ray_tpu.get(r) for r in it]
    assert rest == list(range(1, 200))


def test_streaming_abandonment_cleans_up(rt_start):
    """Dropping the generator frees unconsumed items and lets the producer
    finish instead of hanging on the credit window."""
    import gc
    import time

    from ray_tpu._private.worker import get_global_worker

    @ray_tpu.remote(num_returns="streaming")
    def many():
        for i in range(100):
            yield bytes(10)

    w = get_global_worker()
    it = many.remote()
    tid = it._task_id.hex()
    assert ray_tpu.get(next(it)) == bytes(10)
    del it
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if tid not in w._task_streams:
            break
        time.sleep(0.05)
    assert tid not in w._task_streams, "stream record leaked"


def test_refs_returned_from_task_outlive_container(rt_start):
    """Distributed refcounting: refs created by ray.put INSIDE a task and
    returned in a list must stay alive after the task's return object is
    freed — the holder's deserialize-time borrow pins them (reference:
    borrow registration in reference_counter.h). Regression for the
    shuffle map->reduce handoff: pieces vanished when the map's return
    object was GC'd, and a pending release-drain could consume decrements
    enqueued after an in-flight pin."""
    import gc
    import time

    @ray_tpu.remote
    def producer():
        return [ray_tpu.put(i * 11) for i in range(4)]

    @ray_tpu.remote
    def consumer(a, b):
        return a + b

    tmp = producer.remote()
    pieces = ray_tpu.get(tmp, timeout=30)
    del tmp  # frees the container return object
    gc.collect()
    time.sleep(0.3)  # let the release drain land at the owner
    assert ray_tpu.get(pieces, timeout=15) == [0, 11, 22, 33]
    # pieces usable as args to downstream tasks (the reduce pattern)
    assert ray_tpu.get(consumer.remote(pieces[1], pieces[3]), timeout=30) == 44
