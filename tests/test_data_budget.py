"""Data-executor resource budgets + backpressure policies (reference:
``data/_internal/execution/resource_manager.py`` +
``backpressure_policy/``): ingest is capped to its share of the cluster so
co-located train/serve actors still schedule."""
import threading
import time

import pytest

import ray_tpu
from ray_tpu.data.resource_manager import (
    ConcurrencyCapBackpressurePolicy,
    ReservedCpuBackpressurePolicy,
    ResourceManager,
)


def test_budget_split_and_caps(monkeypatch):
    monkeypatch.setenv("RT_DATA_CPU_FRACTION", "0.5")
    rm = ResourceManager()
    monkeypatch.setattr(
        ResourceManager, "global_limits",
        lambda self: __import__(
            "ray_tpu.data.resource_manager", fromlist=["ExecutionResources"]
        ).ExecutionResources(cpu=8.0, object_store_bytes=1 << 30),
    )
    a = rm.register_op("read", concurrency_cap=100)
    b = rm.register_op("map", concurrency_cap=100)
    # Two active ops split the 8-CPU budget 4/4.
    assert rm.op_budget(a).cpu == pytest.approx(4.0)
    for _ in range(4):
        assert rm.can_add_input(a)
        rm.on_task_submitted(a)
    assert not rm.can_add_input(a), "over its 4-CPU share"
    # The sibling op still has ITS share.
    assert rm.can_add_input(b)
    # Releasing one output re-admits.
    rm.on_task_output_consumed(a)
    assert rm.can_add_input(a)
    rm.unregister_op(b)
    # Sole remaining op inherits the whole data budget.
    assert rm.op_budget(a).cpu == pytest.approx(8.0)


def test_reserved_minimum_never_deadlocks(monkeypatch):
    rm = ResourceManager()
    monkeypatch.setattr(
        ResourceManager, "global_limits",
        lambda self: __import__(
            "ray_tpu.data.resource_manager", fromlist=["ExecutionResources"]
        ).ExecutionResources(cpu=1.0, object_store_bytes=1 << 20),
    )
    ops = [rm.register_op(f"op{i}", concurrency_cap=10,
                          cpu_per_task=4.0) for i in range(3)]
    # Each op's share (0.33 CPU) is below one task's demand, but the
    # reserved minimum admits exactly one task per op: progress, serially.
    for op in ops:
        assert rm.can_add_input(op)
        rm.on_task_submitted(op)
        assert not rm.can_add_input(op)


def test_concurrency_cap_policy():
    rm = ResourceManager(policies=[ConcurrencyCapBackpressurePolicy()])
    op = rm.register_op("m", concurrency_cap=2)
    assert rm.can_add_input(op)
    rm.on_task_submitted(op)
    rm.on_task_submitted(op)
    assert not rm.can_add_input(op)


def test_ingest_leaves_room_for_actors():
    """End to end: a streaming map over many blocks on a 4-CPU cluster
    (data fraction 0.5) must leave >=2 CPUs free, so a 2-CPU actor
    requested MID-PIPELINE schedules promptly instead of starving."""
    ray_tpu.init(num_cpus=4, num_nodes=1,
                 _system_config={"data_cpu_fraction": 0.5})
    try:
        from ray_tpu import data as rt_data

        def slow(batch):
            time.sleep(0.25)
            return batch

        ds = rt_data.range(24).map_batches(slow, batch_size=1)
        results = []
        done = threading.Event()

        def consume():
            results.extend(ds.take_all())
            done.set()

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.5)  # pipeline mid-flight

        @ray_tpu.remote(num_cpus=2)
        class Trainer:
            def ping(self):
                return "up"

        trainer = Trainer.remote()
        t0 = time.monotonic()
        assert ray_tpu.get(trainer.ping.remote(), timeout=20) == "up"
        ray_tpu.kill(trainer)
        assert done.wait(timeout=60), "pipeline never finished"
        assert len(results) == 24
    finally:
        ray_tpu.shutdown()
