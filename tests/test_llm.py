"""LLM layer: cached decode correctness, continuous batching, serving, batch.

Reference analog: ``python/ray/llm/tests`` (engine + serving + batch
processor coverage).
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.llm import (
    DecodeEngine,
    LLMConfig,
    SamplingParams,
    build_llm_processor,
    build_openai_app,
)

_SMALL = dict(
    vocab_size=128, max_seq_len=128, num_layers=2, num_heads=2,
    embed_dim=64, dtype="float32", max_batch_slots=4,
    prefill_buckets=(16, 32),
)


def _engine(**over):
    return DecodeEngine(LLMConfig(**{**_SMALL, **over}), seed=0)


def test_cached_decode_matches_full_forward():
    """Incremental KV-cache decoding must produce exactly the greedy tokens
    the full-context forward produces."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt2

    eng = _engine()
    prompt = [5, 9, 17, 33, 2, 7]
    n_new = 12
    got = eng.generate(prompt, SamplingParams(max_new_tokens=n_new))

    # reference: argmax over full forward, re-run per step
    cfg = eng.model_config
    seq = list(prompt)
    expect = []
    for _ in range(n_new):
        logits, _ = gpt2.forward(
            eng.params, jnp.asarray([seq], jnp.int32), cfg
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        expect.append(nxt)
        if nxt == eng.tokenizer.eos_id:
            break
        seq.append(nxt)
    # engine strips a trailing eos; align lengths
    assert got == [t for t in expect if t != eng.tokenizer.eos_id][: len(got)]
    assert len(got) >= 1


def test_continuous_batching_matches_sequential():
    """Interleaved requests (shared slots) must decode the same greedy
    outputs as one-at-a-time generation."""
    eng = _engine()
    prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [3, 5, 8, 9, 7]]
    p = SamplingParams(max_new_tokens=8)
    futs = [eng.submit(pr, p) for pr in prompts]  # all in flight together
    batched = [f.result(120) for f in futs]

    eng2 = _engine()
    sequential = [eng2.generate(pr, p) for pr in prompts]
    assert batched == sequential


def test_more_requests_than_slots():
    eng = _engine(max_batch_slots=2)
    p = SamplingParams(max_new_tokens=4)
    futs = [eng.submit([i + 2, i + 3], p) for i in range(7)]
    outs = [f.result(120) for f in futs]
    assert all(len(o) >= 1 for o in outs)
    assert eng.stats["requests"] == 7


def test_temperature_sampling_runs():
    eng = _engine()
    out = eng.generate(
        [4, 8, 15], SamplingParams(max_new_tokens=6, temperature=0.8, top_k=8)
    )
    assert 1 <= len(out) <= 6


def test_prompt_too_long_rejected():
    eng = _engine()
    with pytest.raises(ValueError):
        eng.generate(list(range(2, 60)), SamplingParams(max_new_tokens=2))


def test_byte_tokenizer_roundtrip():
    from ray_tpu.llm import ByteTokenizer

    tok = ByteTokenizer()
    s = "hello, wörld!"
    assert tok.decode(tok.encode(s)) == s


# ------------------------------------------------------------ integration


@pytest.fixture
def llm_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_openai_app_over_serve(llm_cluster):
    from ray_tpu import serve

    config = LLMConfig(**{**_SMALL, "vocab_size": 512})
    app = build_openai_app(config)
    handle = serve.run(app, name="llm", route_prefix="/v1")
    try:
        resp = handle.remote(
            {"prompt": "hi", "max_tokens": 4}
        ).result(timeout=120)
        assert resp["object"] == "text_completion"
        assert resp["usage"]["completion_tokens"] >= 1
        chat = handle.remote(
            {"messages": [{"role": "user", "content": "hey"}],
             "max_tokens": 4}
        ).result(timeout=120)
        assert chat["object"] == "chat.completion"
        assert isinstance(chat["choices"][0]["message"]["content"], str)
    finally:
        serve.shutdown()


def test_openai_http_endpoint(llm_cluster):
    import json
    import urllib.request

    from ray_tpu import serve

    config = LLMConfig(**{**_SMALL, "vocab_size": 512})
    app = build_openai_app(config)
    serve.run(app, name="llm", route_prefix="/v1")
    port = serve.start_http_proxy()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": "ok", "max_tokens": 3}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["choices"][0]["finish_reason"] == "stop"
    finally:
        serve.shutdown()


def test_batch_processor(llm_cluster):
    from ray_tpu import data

    config = LLMConfig(**{**_SMALL, "vocab_size": 512})
    ds = data.from_items([{"prompt": f"item {i}"} for i in range(6)])
    processor = build_llm_processor(
        config, sampling=SamplingParams(max_new_tokens=4), batch_size=3
    )
    out = processor(ds).take_all()
    assert len(out) == 6
    assert all(isinstance(r["generated_text"], str) for r in out)


def test_prefill_decode_disaggregation_matches_monolithic():
    """PD split: prefill_only state transferred into a separate engine must
    produce exactly the monolithic engine's greedy output."""
    eng_mono = _engine()
    prompt = [7, 3, 11, 19]
    p = SamplingParams(max_new_tokens=8)
    expect = eng_mono.generate(prompt, p)

    eng_prefill = _engine()
    eng_decode = _engine()
    prefilled = eng_prefill.prefill_only(prompt, p)
    # simulate the wire: numpy arrays survive a serialize round-trip
    import pickle

    prefilled = pickle.loads(pickle.dumps(prefilled))
    got = eng_decode.submit_prefilled(prefilled, p).result(120)
    assert got == expect


def test_pd_serving_app(llm_cluster):
    from ray_tpu import serve
    from ray_tpu.llm import build_pd_openai_app

    config = LLMConfig(**{**_SMALL, "vocab_size": 512})
    app = build_pd_openai_app(config)
    handle = serve.run(app, name="pd", route_prefix="/pd")
    try:
        out = handle.remote(
            {"prompt": "hello", "max_tokens": 4}
        ).result(timeout=120)
        assert out["disaggregated"] is True
        assert out["usage"]["completion_tokens"] >= 1
        # equals the monolithic engine's greedy result on the same weights
        eng = _engine(vocab_size=512)
        expect = eng.tokenizer.decode(
            eng.generate(eng.tokenizer.encode("hello"),
                         SamplingParams(max_new_tokens=4))
        )
        assert out["choices"][0]["text"] == expect
    finally:
        serve.shutdown()


# ------------------------------------------------------------ prefix caching


def test_prefix_cache_exact_hit_same_output():
    """Identical prompts: the second request skips prefill entirely and
    greedy output is unchanged."""
    eng = _engine(prefix_cache_size=4)
    try:
        prompt = list(range(2, 14))
        p = SamplingParams(max_new_tokens=6)
        out1 = eng.generate(prompt, p)
        assert eng.stats["prefix_hits"] == 0
        out2 = eng.generate(prompt, p)
        assert eng.stats["prefix_hits"] == 1
        assert out1 == out2
    finally:
        eng.shutdown()


def test_prefix_cache_partial_hit_matches_uncached():
    """A prompt sharing a cached prefix prefills only its tail — output must
    equal a cache-disabled engine's."""
    base = list(range(2, 18))           # 16 tokens: fills bucket 16
    longer = base + [30, 31, 32, 33]
    p = SamplingParams(max_new_tokens=6)

    ref_eng = _engine(prefix_cache_size=0)
    try:
        expected = ref_eng.generate(longer, p)
        assert ref_eng.stats["prefix_hits"] == 0
    finally:
        ref_eng.shutdown()

    eng = _engine(prefix_cache_size=4)
    try:
        eng.generate(base, p)           # seeds the prefix cache
        out = eng.generate(longer, p)
        assert eng.stats["prefix_partial_hits"] == 1
        assert out == expected
    finally:
        eng.shutdown()


def test_prefix_cache_lru_bound():
    eng = _engine(prefix_cache_size=2)
    try:
        p = SamplingParams(max_new_tokens=2)
        for start in (2, 20, 40):
            eng.generate([start, start + 1, start + 2], p)
        assert len(eng._prefix_cache) == 2  # oldest evicted
        # evicted prompt re-prefills without error
        eng.generate([2, 3, 4], p)
        assert eng.stats["prefix_hits"] == 0
    finally:
        eng.shutdown()


def test_prefix_cache_tail_overflow_falls_back():
    """When matched + bucket(tail) would exceed max_seq_len, the padded tail
    write would clamp and corrupt prefix KV — the engine must fall back to a
    full prefill and still produce the uncached output."""
    cfg = dict(
        vocab_size=128, max_seq_len=64, num_layers=2, num_heads=2,
        embed_dim=64, dtype="float32", max_batch_slots=2,
        prefill_buckets=(16, 64),
    )
    base = list(range(2, 18))          # 16 tokens -> cached boundary at 16
    longer = base + list(range(40, 84))  # 60 tokens; tail bucket = 64
    p = SamplingParams(max_new_tokens=3)

    ref = DecodeEngine(LLMConfig(prefix_cache_size=0, **cfg), seed=0)
    try:
        expected = ref.generate(longer, p)
    finally:
        ref.shutdown()

    eng = DecodeEngine(LLMConfig(prefix_cache_size=4, **cfg), seed=0)
    try:
        eng.generate(base, p)
        out = eng.generate(longer, p)  # 16 + bucket(44)=64 > 64: fallback
        assert eng.stats["prefix_partial_hits"] == 0
        assert out == expected
    finally:
        eng.shutdown()


# ------------------------------------------------------------------- MoE


def test_moe_cached_decode_matches_full_forward():
    """MoE (Mixtral-style) decode through the KV cache must reproduce the
    full-forward greedy tokens — the expert routing is per-token and must
    be identical in both paths."""
    import jax.numpy as jnp

    from ray_tpu.models import llama

    eng = _engine(
        model_family="llama", moe_num_experts=4, moe_top_k=2, num_layers=2,
    )
    assert eng.model_config.moe is not None
    prompt = [3, 11, 25, 40]
    n_new = 8
    got = eng.generate(prompt, SamplingParams(max_new_tokens=n_new))

    cfg = eng.model_config
    seq = list(prompt)
    expect = []
    for _ in range(n_new):
        logits, _ = llama.forward(
            eng.params, jnp.asarray([seq], jnp.int32), cfg
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        expect.append(nxt)
        if nxt == eng.tokenizer.eos_id:
            break
        seq.append(nxt)
    assert got == [t for t in expect if t != eng.tokenizer.eos_id][: len(got)]
    assert len(got) >= 1


def test_moe_openai_app(llm_cluster):
    """VERDICT round-1 item: a Mixtral-style MoE model served end-to-end
    through the OpenAI-compatible app."""
    from ray_tpu import serve

    config = LLMConfig(
        **{**_SMALL, "vocab_size": 256, "model_family": "llama",
           "moe_num_experts": 4, "moe_top_k": 2}
    )
    app = build_openai_app(config)
    handle = serve.run(app, name="llm-moe", route_prefix="/v1")
    try:
        resp = handle.remote(
            {"prompt": "hi", "max_tokens": 4}
        ).result(timeout=180)
        assert resp["object"] == "text_completion"
        assert resp["usage"]["completion_tokens"] >= 1
    finally:
        serve.shutdown()


# --------------------------------------------------- sampling param breadth


def test_sampling_seed_reproducible_and_varied():
    """Per-request seed: same seed -> identical stochastic output; the
    engine-global rng stays untouched for other requests."""
    eng = _engine()
    prompt = [5, 9, 17]
    p = SamplingParams(max_new_tokens=8, temperature=1.0, seed=7)
    out1 = eng.generate(prompt, p)
    out2 = eng.generate(prompt, p)
    assert list(out1) == list(out2)
    # a different seed changes the draw sequence; on this model at
    # temperature 1.0 at least one of a few seeds must diverge
    assert any(
        list(eng.generate(prompt, SamplingParams(
            max_new_tokens=8, temperature=1.0, seed=sd
        ))) != list(out1)
        for sd in (8, 9, 10)
    )


def test_sampling_top_p_restricts_support():
    """top_p -> only tokens from the nucleus can be drawn (checked against
    the model's actual next-token distribution)."""
    import jax.numpy as jnp

    eng = _engine()
    prompt = [5, 9, 17, 33]
    # collect the model's next-token distribution via logprobs
    probe = eng.generate(prompt, SamplingParams(
        max_new_tokens=1, temperature=1.0, logprobs=128, seed=0,
    ))
    logps = dict(probe.logprobs[0]["top_logprobs"])
    order = sorted(logps, key=lambda t: -logps[t])
    cum, nucleus = 0.0, set()
    for t in order:
        nucleus.add(t)
        cum += float(np.exp(logps[t]))
        if cum >= 0.5:
            break
    for seed in range(10):
        out = eng.generate(prompt, SamplingParams(
            max_new_tokens=1, temperature=1.0, top_p=0.5, seed=seed,
        ))
        if not out:  # the draw hit EOS (trimmed) — still nucleus-bound
            assert eng.tokenizer.eos_id in nucleus
            continue
        assert out[0] in nucleus, (out[0], nucleus)


def test_sampling_penalties_suppress_repeats():
    """A strong frequency penalty forbids re-drawing generated tokens
    (greedy without it repeats on a tiny random model)."""
    eng = _engine()
    prompt = [3, 3, 3, 3]
    base = eng.generate(prompt, SamplingParams(max_new_tokens=12))
    pen = eng.generate(prompt, SamplingParams(
        max_new_tokens=12, frequency_penalty=100.0,
    ))
    # with the huge penalty every generated token is distinct
    assert len(set(pen)) == len(pen), pen
    assert len(set(base)) <= len(base)


def test_sampling_logprobs_shape_and_consistency():
    eng = _engine()
    out = eng.generate([5, 9, 17], SamplingParams(
        max_new_tokens=5, logprobs=3,
    ))
    assert len(out.logprobs) == len(out)
    for tok, entry in zip(out, out.logprobs):
        assert entry["token"] == tok
        assert entry["logprob"] <= 0.0
        assert len(entry["top_logprobs"]) == 3
        # greedy: the chosen token IS the top-1
        assert entry["top_logprobs"][0][0] == tok


def test_stop_strings_trim_output():
    eng = _engine()
    prompt = [5, 9, 17, 33, 2, 7]
    full = eng.generate(prompt, SamplingParams(max_new_tokens=10))
    full_text = eng.tokenizer.decode(list(full))
    assert len(full_text) > 4
    needle = full_text[2:5]  # a substring the generation will hit
    out = eng.generate(prompt, SamplingParams(
        max_new_tokens=10, stop=(needle,),
    ))
    text = eng.tokenizer.decode(list(out))
    assert needle not in text
    assert len(out) < len(full)


def test_pd_disaggregation_logprobs_and_seed_alignment():
    """PD split preserves the sampling contract: logprob entries align
    1:1 with tokens (incl. the prefill server's first token), and a
    seeded stochastic request matches the monolithic engine exactly."""
    eng_prefill = _engine()
    eng_decode = _engine()
    eng_mono = _engine()
    prompt = [5, 9, 17, 33]
    p = SamplingParams(max_new_tokens=6, temperature=0.7, seed=11,
                       logprobs=2)
    prefilled = eng_prefill.prefill_only(prompt, p)
    got = eng_decode.submit_prefilled(prefilled, p).result(120)
    expect = eng_mono.generate(prompt, p)
    assert list(got) == list(expect)
    assert len(got.logprobs) == len(got)
    for tok, entry in zip(got, got.logprobs):
        assert entry["token"] == tok


def test_serving_returns_logprobs(rt_serve_cluster=None):
    """logprobs requested over the serving surface come back in the
    OpenAI response shape (they are not silently dropped)."""
    from ray_tpu.llm.serving import LLMServer

    srv = LLMServer.__new__(LLMServer)
    srv.config = LLMConfig(**_SMALL)
    srv.engine = _engine()
    resp = srv.completions({"prompt": "hi", "max_tokens": 4, "logprobs": 2})
    lp = resp["choices"][0]["logprobs"]
    assert len(lp["tokens"]) == resp["usage"]["completion_tokens"]
    assert all(v <= 0 for v in lp["token_logprobs"])
    assert all(len(d) == 2 for d in lp["top_logprobs"])


# -------------------------------------------------------------- streaming


def test_engine_stream_matches_generate():
    """submit_stream yields exactly the tokens generate() returns (greedy),
    and rejects string stops (their trim point needs the full output)."""
    eng = _engine()
    prompt = [5, 9, 17, 33]
    p = SamplingParams(max_new_tokens=8)
    expect = list(eng.generate(prompt, p))
    got = list(eng.submit_stream(prompt, p))
    assert got == expect
    with pytest.raises(ValueError, match="streamable"):
        eng.submit_stream(prompt, SamplingParams(stop=("x",)))


def test_openai_http_streaming_sse():
    """stream=true end-to-end over HTTP: SSE chunk lines whose concatenated
    deltas equal the non-streaming completion text, terminated by [DONE]."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm import build_openai_app

    ray_tpu.init(num_cpus=4)
    try:
        config = LLMConfig(**{**_SMALL, "vocab_size": 512})
        app = build_openai_app(config)
        handle = serve.run(app, name="llm-stream", route_prefix="/v1")
        port = serve.start_http_proxy(port=0)
        base = f"http://127.0.0.1:{port}"

        def post(payload):
            req = urllib.request.Request(
                base + "/v1/completions",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
            )
            return urllib.request.urlopen(req, timeout=120)

        plain = _json.loads(post(
            {"prompt": "hi", "max_tokens": 6}
        ).read())
        expect_text = plain["choices"][0]["text"]

        with post({"prompt": "hi", "max_tokens": 6, "stream": True}) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")
            raw = r.read().decode()
        lines = [l for l in raw.split("\n\n") if l.startswith("data: ")]
        assert lines[-1] == "data: [DONE]"
        deltas = []
        for line in lines[:-1]:
            chunk = _json.loads(line[len("data: "):])
            c = chunk["choices"][0]
            if c["finish_reason"] is None:
                deltas.append(c["text"])
        assert "".join(deltas) == expect_text

        # stream=true + string stops cannot stream (trim point unknown
        # until the end): the proxy returns plain JSON, never a broken
        # SSE body
        with post({"prompt": "hi", "max_tokens": 6, "stream": True,
                   "stop": ["zzz"]}) as r:
            assert r.headers["Content-Type"].startswith("application/json")
            body = _json.loads(r.read())
        assert body["choices"][0]["text"]
    finally:
        try:
            serve.shutdown()
        finally:
            ray_tpu.shutdown()


# --------------------------------------------- speculative (prompt lookup)


def test_speculative_ngram_matches_plain_greedy():
    """Opt-in prompt-lookup speculation produces EXACTLY the plain greedy
    output (acceptance only keeps tokens the full model agrees with) while
    accepting drafts on repetitive text — and streams/continuous-batches
    identically."""
    plain = _engine()
    spec = DecodeEngine(
        LLMConfig(**{**_SMALL, "speculative_ngram_k": 4}), seed=0
    )
    # repetitive prompts (n-gram lookup gold case) and a non-repetitive one
    prompts = [
        [5, 9, 5, 9, 5, 9, 5, 9],
        [3, 3, 3, 3, 3, 3],
        [7, 11, 13, 17, 19, 23],
    ]
    p = SamplingParams(max_new_tokens=16)
    for prompt in prompts:
        a = list(plain.generate(prompt, p))
        b = list(spec.generate(prompt, p))
        assert a == b, (prompt, a, b)
    assert spec.stats["spec_proposed"] > 0
    # model-generated text is itself repetitive on random tiny weights, so
    # some drafts must verify; ticks < tokens proves multi-token steps
    assert spec.stats["spec_accepted"] > 0
    assert spec.stats["ticks"] < spec.stats["tokens_generated"]

    # stochastic requests fall back to 1-token verification but still work
    sp = SamplingParams(max_new_tokens=8, temperature=1.0, seed=4)
    s1 = list(spec.generate(prompts[0], sp))
    s2 = list(DecodeEngine(
        LLMConfig(**{**_SMALL, "speculative_ngram_k": 4}), seed=0
    ).generate(prompts[0], sp))
    assert s1 == s2  # per-request seed still reproducible


def test_speculative_respects_sequence_end():
    """Slots near max_seq_len stop speculating (the padded verify write
    would clamp); generation still terminates correctly at the cap."""
    cfg = LLMConfig(**{**_SMALL, "max_seq_len": 40,
                       "prefill_buckets": (16,),
                       "speculative_ngram_k": 4})
    eng = DecodeEngine(cfg, seed=0)
    out = eng.generate([5, 9] * 6, SamplingParams(max_new_tokens=64))
    assert len(out) <= 40 - 12
