"""Device-plane object store + XLA collective backend (round 14).

Pins the device plane's contract the way ``test_submission_plane.py``
pins the submission plane's — by counting, not by vibes:

- metadata round-trip: a sharded ``jax.Array`` put() registers the
  PINNED directory schema ``{dtype, shape, nbytes, platform, sharding,
  placement}``; get() on the owner is a table hit (same object back);
- cross-process get materializes the consumer's value bit-equal to the
  ``np.asarray`` ground truth, with the CONSUMER's requested sharding
  applied via ``devstore.get_array``/``reshard``;
- call-counting economics: ZERO cloudpickle calls on the device put
  path, O(owners)=1 ``pull_device_shards`` RPC per consumer (repeat
  gets are cache hits), zero ``pull_object`` fallbacks on the happy
  path;
- ``device_objects=False`` restores the host cloudpickle path (and the
  host-staging ledger records device payloads that cross it);
- faultpoints: a failed/lost shard pull retries against the owner and
  completes; a lost registration degrades readers to pull-from-owner;
- memtrack: ``kind="device"`` rows/totals flow through memory_summary
  and the freed object leaves zero leak candidates;
- the registered ``"xla"`` collective backend matches the host backend
  bit-for-bit (float32) on allreduce/allgather/reduce_scatter/broadcast,
  lowering through jitted ``shard_map`` (stats pinned).

The single-node owner-side tests share ONE class-scoped cluster (the
device plane leaves no cross-test state: faultpoints are cleared by the
autouse fixture, env gates are read per call, freed objects leave the
directory) — a per-test cluster would multiply tier-1 wall time for no
isolation gain.
"""
import gc

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import devstore
from ray_tpu._private import faultpoints as fp
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.test_utils import wait_for_condition


@pytest.fixture(autouse=True)
def _fp_clean():
    fp.clear()
    yield
    fp.clear()


@pytest.fixture
def fast_rpc(monkeypatch):
    monkeypatch.setenv("RT_RPC_DEADLINE_S", "1")
    monkeypatch.setenv("RT_RPC_RETRIES", "4")


def _sharded(n_shards=2, shape=(8, 8)):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("x",))
    size = int(np.prod(shape))
    return jax.device_put(
        jnp.arange(size, dtype=jnp.float32).reshape(shape),
        NamedSharding(mesh, P("x")),
    )


class TestDevicePlane:
    """Single-node device-plane contract on one shared cluster."""

    @pytest.fixture(scope="class", autouse=True)
    def _cluster(self):
        ctx = ray_tpu.init(num_cpus=4)
        yield ctx
        ray_tpu.shutdown()

    # -------------------------------------------------- metadata roundtrip
    def test_put_registers_device_metadata_and_local_get_is_table_hit(self):
        w = worker_mod.global_worker
        arr = _sharded(n_shards=2)
        ref = ray_tpu.put(arr)
        hex_ = ref.id().hex()
        assert w.memory_store[hex_][0] == "dev"
        # Owner-side get: the very same array object, zero copies.
        assert ray_tpu.get(ref) is arr

        head = ray_tpu._internal_cluster().head
        wait_for_condition(
            lambda: hex_ in head.object_dir, timeout=10,
            message="device registration never reached the head",
        )
        meta = head.object_dir[hex_]
        assert meta["size"] == arr.nbytes
        assert list(meta["owner"]) == list(w.addr)
        spec = meta["device"]
        # The PINNED device-metadata schema (PARITY.md Round-14).
        assert set(spec) >= {"dtype", "shape", "nbytes", "platform",
                             "sharding", "placement"}
        assert spec["dtype"] == "float32"
        assert spec["shape"] == [8, 8]
        assert spec["platform"] == "cpu"
        assert spec["sharding"]["type"] == "named"
        assert spec["sharding"]["axes"] == [["x", 2]]
        assert len(spec["placement"]) == 2  # one entry per shard
        for shard in spec["placement"]:
            assert set(shard) >= {"shard", "device", "node", "index"}
        # Shard indices tile the global shape along axis 0.
        assert sorted(p["index"][0] for p in spec["placement"]) == [
            [0, 4], [4, 8]
        ]

    def test_consumer_requested_resharding(self):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        @ray_tpu.remote
        def produce():
            import jax as j
            import jax.numpy as jnp
            from jax.sharding import Mesh as M, NamedSharding as NS
            from jax.sharding import PartitionSpec as PS

            mesh = M(np.array(j.devices()[:2]), ("x",))
            return ray_tpu.put(j.device_put(
                jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                NS(mesh, PS("x")),
            ))

        ref = ray_tpu.get(produce.remote(), timeout=120)
        want = np.arange(64, dtype=np.float32).reshape(8, 8)
        # The consumer asks for a DIFFERENT layout: column-sharded over
        # its own pick of devices.
        target = NamedSharding(
            Mesh(np.array(jax.devices()[2:4]), ("y",)), P(None, "y")
        )
        out = devstore.get_array(ref, sharding=target)
        np.testing.assert_array_equal(np.asarray(out), want)
        assert out.sharding.is_equivalent_to(target, out.ndim)
        # Each of the 2 shards holds an (8, 4) column block.
        assert sorted(s.data.shape for s in out.addressable_shards) == [
            (8, 4), (8, 4)
        ]

    # ---------------------------------------------------- call economics
    def test_zero_cloudpickle_and_o_owners_pull_rpcs(self, monkeypatch):
        """The payload NEVER passes through cloudpickle on the device
        path, and a consumer pays exactly ONE pull_device_shards RPC
        (repeat gets are table hits; zero pull_object fallbacks)."""
        import ray_tpu._private.serialization as ser

        w = worker_mod.global_worker
        arr = _sharded(n_shards=2)
        want_sum = float(np.asarray(arr).sum())

        pickled = []
        orig_dumps = ser.cloudpickle.dumps

        def counting_dumps(obj, *a, **k):
            pickled.append(type(obj).__name__)
            return orig_dumps(obj, *a, **k)

        monkeypatch.setattr(ser.cloudpickle, "dumps", counting_dumps)
        ref = ray_tpu.put(arr)
        monkeypatch.setattr(ser.cloudpickle, "dumps", orig_dumps)
        assert pickled == [], f"device put cloudpickled: {pickled}"

        calls = {"dev_pull": 0, "obj_pull": 0}
        orig_dev = w.rpc_pull_device_shards
        orig_obj = w.rpc_pull_object

        async def counted_dev(h, frames, conn):
            calls["dev_pull"] += 1
            return await orig_dev(h, frames, conn)

        async def counted_obj(h, frames, conn):
            calls["obj_pull"] += 1
            return await orig_obj(h, frames, conn)

        # Instance-attr shadow (dispatch getattrs per call); restored
        # below — the cluster is shared.
        w.rpc_pull_device_shards = counted_dev
        w.rpc_pull_object = counted_obj

        @ray_tpu.remote
        class Consumer:
            def consume(self, refs):
                import numpy as _np

                return float(_np.asarray(ray_tpu.get(refs[0])).sum())

        try:
            c = Consumer.remote()
            assert ray_tpu.get(c.consume.remote([ref]),
                               timeout=120) == want_sum
            assert ray_tpu.get(c.consume.remote([ref]),
                               timeout=120) == want_sum
        finally:
            del w.rpc_pull_device_shards
            del w.rpc_pull_object
        assert calls["dev_pull"] == 1, calls  # O(owners); cached repeat
        assert calls["obj_pull"] == 0, calls  # directory hit, no fallback
        ray_tpu.kill(c)

    # -------------------------------------------------------- disabled mode
    def test_disabled_mode_falls_back_to_host_path(self, monkeypatch):
        """device_objects=False: byte-identical host cloudpickle behavior
        — the store entry is a host kind and the staging ledger records
        the device payload that crossed it."""
        monkeypatch.setenv("RT_DEVICE_OBJECTS", "0")
        w = worker_mod.global_worker
        arr = _sharded(n_shards=2)
        staged_before = devstore.host_staged_stats()
        ref = ray_tpu.put(arr)
        assert w.memory_store[ref.id().hex()][0] in ("mem", "shm")
        out = ray_tpu.get(ref)
        assert out is not arr  # host round-trip, not a table hit
        np.testing.assert_array_equal(np.asarray(out), np.asarray(arr))
        assert np.asarray(out).dtype == np.asarray(arr).dtype
        staged = devstore.host_staged_stats()
        assert staged["count"] == staged_before["count"] + 1
        assert staged["bytes"] == staged_before["bytes"] + arr.nbytes

    def test_nested_device_arrays_keep_host_semantics(self):
        """Only TOP-LEVEL device arrays route to the devstore (the pinned
        interception boundary): one nested in a container rides
        cloudpickle exactly as before the plane existed, byte-correct."""
        w = worker_mod.global_worker
        arr = _sharded(n_shards=2, shape=(4, 4))
        ref = ray_tpu.put({"weights": arr, "step": 3})
        assert w.memory_store[ref.id().hex()][0] in ("mem", "shm")
        out = ray_tpu.get(ref)
        assert out["step"] == 3
        np.testing.assert_array_equal(np.asarray(out["weights"]),
                                      np.asarray(arr))

    # ----------------------------------------------------------- faultpoints
    def test_shard_pull_error_is_retried_against_owner(self, fast_rpc):
        @ray_tpu.remote
        def produce():
            import jax.numpy as jnp

            return ray_tpu.put(jnp.ones((16, 4), jnp.float32))

        inner = ray_tpu.get(produce.remote(), timeout=120)
        # Two consumer-side failures, then success — retried against the
        # owner, never surfaced to the caller.
        fp.configure("devstore.shard_pull:error:1.0:2:7")
        out = ray_tpu.get(inner, timeout=120)
        assert float(np.asarray(out).sum()) == 64.0
        assert fp.stats()[0]["injected"] == 2

    def test_shard_pull_drop_rearms_instead_of_hanging(self, fast_rpc):
        @ray_tpu.remote
        def produce():
            import jax.numpy as jnp

            return ray_tpu.put(jnp.full((8, 8), 2.0, jnp.float32))

        inner = ray_tpu.get(produce.remote(), timeout=120)
        fp.configure("devstore.shard_pull:drop:1.0:1:5")
        out = ray_tpu.get(inner, timeout=120)
        assert float(np.asarray(out).sum()) == 128.0
        assert fp.stats()[0]["injected"] == 1

    def test_register_drop_degrades_to_owner_pull(self, fast_rpc):
        """A lost directory registration must not lose the object:
        readers miss the directory and pull from the owner (pull_object
        answers with the device spec, then the shard pull proceeds)."""
        fp.configure("devstore.register:drop:1.0:1:3")
        arr = _sharded(n_shards=2, shape=(4, 4))
        ref = ray_tpu.put(arr)
        hex_ = ref.id().hex()
        assert fp.stats()[0]["injected"] == 1
        fp.clear()
        head = ray_tpu._internal_cluster().head
        assert hex_ not in head.object_dir  # registration really dropped

        @ray_tpu.remote
        def consume(refs):
            import numpy as _np

            return float(_np.asarray(ray_tpu.get(refs[0])).sum())

        want = float(np.asarray(arr).sum())
        assert ray_tpu.get(consume.remote([ref]), timeout=120) == want

    # ---------------------------------------------------------- memtrack
    def test_device_rows_flow_through_memory_summary(self):
        from ray_tpu._private import memtrack
        from ray_tpu.util import state

        arr = _sharded(n_shards=2)
        ref = ray_tpu.put(arr)
        hex_ = ref.id().hex()
        head = ray_tpu._internal_cluster().head
        wait_for_condition(lambda: hex_ in head.object_dir, timeout=10)

        s = state.memory_summary()
        rows = {r["oid"]: r for r in s["rows"]}
        assert hex_ in rows
        assert rows[hex_]["kind"] == "device"
        assert rows[hex_]["bytes"] == arr.nbytes
        assert s["totals"]["device_bytes"] >= arr.nbytes
        w = worker_mod.global_worker
        node = str(w.node_id)[:12]
        assert s["reconcile"][node]["owner_device_bytes"] >= arr.nbytes
        assert s["reconcile"][node]["directory_device_bytes"] >= arr.nbytes

        # Gauge-tick coverage on the same cluster: device bytes aggregate
        # per (kind, node) and push_gauges handles the new kind.
        snap = memtrack.local_snapshot(w)
        agg = {(k, n): v for k, n, v in snap["bytes_by_kind_node"]}
        assert agg.get(("device", node), 0) >= arr.nbytes
        assert "device_host_staged" in snap
        memtrack.push_gauges(w)  # must not break the 2s tick

        # Freeing the last ref reclaims the device table entry, the
        # directory entry, and leaves ZERO leak candidates — the chaos
        # SLO for kind="device" matches every other kind.
        del ref
        gc.collect()
        wait_for_condition(
            lambda: hex_ not in head.object_dir, timeout=10,
            message="freed device object stuck in directory",
        )
        assert hex_ not in w._device_objects
        assert state.memory_summary(grace_s=0.5)["leaks"] == []


@pytest.mark.parametrize("rt_start", [dict(num_cpus=2, num_nodes=2)],
                         indirect=True)
def test_cross_process_get_matches_ground_truth(rt_start):
    arr = _sharded(n_shards=4, shape=(8, 8))
    want = np.asarray(arr)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def consume_arg(v):
        import jax as j
        import numpy as _np

        return (
            type(v).__name__,
            _np.asarray(v).tolist(),
            isinstance(v, j.Array) and len(v.sharding.device_set),
        )

    @ray_tpu.remote
    def consume_get(refs):
        import numpy as _np

        return _np.asarray(ray_tpu.get(refs[0])).tolist()

    name, got, n_dev = ray_tpu.get(consume_arg.remote(ref), timeout=120)
    assert name == "ArrayImpl"
    assert got == want.tolist()
    assert n_dev == 4  # producer-equivalent sharding rebuilt at consumer
    assert ray_tpu.get(consume_get.remote([ref]),
                       timeout=120) == want.tolist()


# ------------------------------------------------------------ xla backend
@ray_tpu.remote
class _ColMember:
    def __init__(self, world, rank, backend, name):
        from ray_tpu.util import collective as col

        self.col = col
        self.rank, self.world = rank, world
        col.init_collective_group(world, rank, backend=backend,
                                  group_name=name)
        self.g = name

    def allreduce(self):
        return self.col.allreduce(
            np.full((4,), float(self.rank + 1), np.float32), self.g
        )

    def allgather(self):
        return self.col.allgather(
            np.array([self.rank + 1], np.float32), self.g
        )

    def reducescatter(self):
        return self.col.reducescatter(
            np.arange(self.world * 3, dtype=np.float32) * (self.rank + 1),
            self.g,
        )

    def rs_max(self):
        from ray_tpu.util.collective.types import ReduceOp

        return self.col.reducescatter(
            np.arange(4, dtype=np.float32), self.g, op=ReduceOp.MAX
        )

    def broadcast(self):
        x = (np.arange(3, dtype=np.float32)
             if self.rank == 0 else np.zeros(3, np.float32))
        return self.col.broadcast(x, src_rank=0, group_name=self.g)

    def stats(self):
        from ray_tpu.util.collective.collective import _group_mgr

        return dict(_group_mgr.get_group(self.g).stats)


@pytest.mark.parametrize("rt_start", [dict(num_cpus=8)], indirect=True)
def test_xla_backend_bitwise_parity_with_host(rt_start):
    """backend="xla" on a CPU mesh: every collective matches the host
    backend bit-for-bit for exact float32 inputs through the lowered
    (shard_map) path — and a non-SUM reduce-scatter (psum_scatter cannot
    express it) falls back to the host path with identical results,
    explicitly counted."""
    world = 2
    xla = [_ColMember.remote(world, r, "xla", "par-x")
           for r in range(world)]
    host = [_ColMember.remote(world, r, "host", "par-h")
            for r in range(world)]
    for method in ("allreduce", "allgather", "reducescatter", "broadcast",
                   "rs_max"):
        got_x = ray_tpu.get(
            [getattr(m, method).remote() for m in xla], timeout=180
        )
        got_h = ray_tpu.get(
            [getattr(m, method).remote() for m in host], timeout=180
        )
        for a, b in zip(got_x, got_h):
            if isinstance(a, list):
                assert len(a) == len(b)
                for x, y in zip(a, b):
                    assert np.array_equal(np.asarray(x), np.asarray(y)), \
                        method
            else:
                assert np.array_equal(np.asarray(a), np.asarray(b)), method
    stats = ray_tpu.get(xla[0].stats.remote(), timeout=60)
    # 4 lowered collectives; rs_max is the explicit host fallback.
    assert stats["shard_map_calls"] == 4
    assert stats["host_fallbacks"] == 1
    for m in xla + host:
        ray_tpu.kill(m)
