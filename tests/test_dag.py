"""Compiled graphs: channels, eager DAGs, compiled exec loops.

Reference analog: ``python/ray/dag/tests`` (bind/execute/compile semantics,
channel buffering).
"""
import threading
import time
import uuid

import numpy as np
import pytest

import ray_tpu
from ray_tpu.dag import (
    Channel,
    ChannelClosedError,
    CompiledDAGRef,
    InputNode,
    MultiOutputNode,
)


# --------------------------------------------------------------- channels


class _Ctx:
    """Standalone serializer for channel unit tests (no cluster)."""

    def __init__(self):
        from ray_tpu._private.serialization import SerializationContext

        self._ctx = SerializationContext()

    def serialize(self, v):
        return self._ctx.serialize(v)

    def deserialize_frames(self, frames):
        return self._ctx.deserialize_frames(frames)


def _chname():
    return f"/rt_cht_{uuid.uuid4().hex[:12]}"


def test_channel_roundtrip():
    ctx = _Ctx()
    ch = Channel(_chname(), capacity=1 << 16, create=True)
    try:
        ch.write({"a": np.arange(100)}, ctx)
        out = ch.read(ctx)
        assert list(out) == ["a"]
        np.testing.assert_array_equal(out["a"], np.arange(100))
    finally:
        ch.close()


def test_channel_backpressure_and_order():
    ctx = _Ctx()
    ch = Channel(_chname(), capacity=1 << 14, create=True)
    got = []

    def reader():
        for _ in range(10):
            time.sleep(0.01)
            got.append(ch.read(ctx))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i in range(10):  # writer must block on the 1-slot buffer
            ch.write(i, ctx, timeout=10)
        t.join(timeout=10)
        assert got == list(range(10))
    finally:
        ch.close()


def test_channel_stop_unblocks_reader():
    ctx = _Ctx()
    ch = Channel(_chname(), capacity=1 << 14, create=True)
    err = []

    def reader():
        try:
            ch.read(ctx, timeout=30)
        except ChannelClosedError as e:
            err.append(e)

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.1)
    ch.set_stop()
    t.join(timeout=10)
    assert err, "reader not unblocked by stop"


# ------------------------------------------------------------ dag fixtures


@pytest.fixture
def dag_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class Adder:
    def __init__(self, inc):
        self.inc = inc
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.inc

    def combine(self, a, b):
        return a + b

    def num_calls(self):
        return self.calls


def test_eager_execute(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    ref = dag.execute(5)
    assert ray_tpu.get(ref) == 16


def test_compiled_linear_pipeline(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(5):
            ref = compiled.execute(i)
            assert isinstance(ref, CompiledDAGRef)
            assert ref.get() == i + 11
    finally:
        compiled.teardown()


def test_compiled_pipelining_overlap(dag_cluster):
    """Submit several inputs before collecting: per-edge backpressure allows
    stage overlap (the PP microbatch property)."""
    a = Adder.remote(100)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    try:
        refs = [compiled.execute(i) for i in range(2)]
        assert [r.get() for r in refs] == [100, 101]
    finally:
        compiled.teardown()


def test_compiled_fan_in(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(2)
    c = Adder.remote(0)
    with InputNode() as inp:
        dag = c.combine.bind(a.add.bind(inp), b.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(10).get() == 23  # (10+1) + (10+2)
    finally:
        compiled.teardown()


def test_compiled_multi_output(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(2)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp), b.add.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5).get() == [6, 7]
    finally:
        compiled.teardown()


def test_compiled_large_payload_spills(dag_cluster):
    a = Adder.remote(0.0)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile(channel_capacity=1 << 12)
    try:
        big = np.ones(100_000)  # ~800KB >> 4KB channel
        out = compiled.execute(big).get()
        np.testing.assert_array_equal(out, big)
    finally:
        compiled.teardown()


def test_teardown_then_execute_raises(dag_cluster):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(1).get() == 2
    compiled.teardown()
    with pytest.raises(RuntimeError):
        compiled.execute(2)
    # actor survives teardown and serves normal calls again
    assert ray_tpu.get(a.num_calls.remote()) >= 1


def test_constant_only_task_gated_per_execute(dag_cluster):
    """A task with no upstream edges must run exactly once per execute(),
    not free-run ahead (side effects gated by a driver trigger channel)."""
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def tick(self):
            self.n += 1
            return self.n

        def count(self):
            return self.n

    c = Counter.remote()
    dag = c.tick.bind()
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute().get() == 1
        assert compiled.execute().get() == 2
        time.sleep(0.5)  # free-running loop would keep ticking here
    finally:
        compiled.teardown()
    # (queried post-teardown: the exec loop holds the actor's only
    # concurrency slot while compiled)
    assert ray_tpu.get(c.count.remote(), timeout=30) == 2


def test_compiled_user_error_surfaces(dag_cluster):
    """An exception in a compiled task must reach the driver with the
    actor-side traceback, not a generic timeout."""
    @ray_tpu.remote
    class Boom:
        def go(self, x):
            if x == 2:
                raise ValueError("kaboom at 2")
            return x

    b = Boom.remote()
    with InputNode() as inp:
        dag = b.go.bind(inp)
    compiled = dag.experimental_compile()
    assert compiled.execute(1).get() == 1
    with pytest.raises(RuntimeError, match="kaboom at 2"):
        compiled.execute(2).get(timeout=30)


def test_eager_kwarg_upstream_resolved(dag_cluster):
    a = Adder.remote(1)
    b = Adder.remote(0)

    with InputNode() as inp:
        dag = b.combine.bind(0, b=a.add.bind(inp))
    assert ray_tpu.get(dag.execute(5)) == 6


def test_single_element_multioutput_consistency(dag_cluster):
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = MultiOutputNode([a.add.bind(inp)])
    assert dag.execute(5) == [6]  # eager: list
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5).get() == [6]  # compiled: also list
    finally:
        compiled.teardown()


# ------------------------------------------------------- in-DAG collectives


def test_eager_allreduce(dag_cluster):
    from ray_tpu.dag import allreduce

    ws = [Adder.remote(i) for i in (1, 2, 3)]
    with InputNode() as inp:
        contribs = [w.add.bind(inp) for w in ws]
        reduced = allreduce.bind(contribs, op="sum")
        dag = MultiOutputNode(reduced)
    out = dag.execute(10)
    # contributions 11, 12, 13 -> everyone sees 36
    assert out == [36, 36, 36]


def test_compiled_allreduce_sum_and_consume(dag_cluster):
    from ray_tpu.dag import allreduce

    ws = [Adder.remote(i) for i in (1, 2, 3)]
    with InputNode() as inp:
        contribs = [w.add.bind(inp) for w in ws]
        reduced = allreduce.bind(contribs, op="sum")
        outs = [w.add.bind(r) for w, r in zip(ws, reduced)]
        dag = MultiOutputNode(outs).experimental_compile()
    try:
        for x in (0, 5, 7):
            s = 3 * x + 6  # sum of (x+1, x+2, x+3)
            assert dag.execute(x).get() == [s + 1, s + 2, s + 3]
    finally:
        dag.teardown()


def test_compiled_allreduce_mean_arrays(dag_cluster):
    from ray_tpu.dag import allreduce

    @ray_tpu.remote
    class Vec:
        def __init__(self, scale):
            self.scale = scale

        def make(self, x):
            return np.full(4, float(x * self.scale))

    ws = [Vec.remote(s) for s in (1, 3)]
    with InputNode() as inp:
        reduced = allreduce.bind([w.make.bind(inp) for w in ws], op="mean")
        dag = MultiOutputNode(reduced).experimental_compile()
    try:
        out = dag.execute(2).get()
        np.testing.assert_allclose(out[0], np.full(4, 4.0))  # mean(2, 6)
        np.testing.assert_allclose(out[1], np.full(4, 4.0))
    finally:
        dag.teardown()


def test_allreduce_validation(dag_cluster):
    from ray_tpu.dag import allreduce

    a = Adder.remote(1)
    with InputNode() as inp:
        n1 = a.add.bind(inp)
        n2 = a.add.bind(inp)
        with pytest.raises(ValueError, match="distinct actors"):
            allreduce.bind([n1, n2])
    b = Adder.remote(2)
    with InputNode() as inp:
        reduced = allreduce.bind([a.add.bind(inp), b.add.bind(inp)])
        # dropping one participant's output must fail compile
        with pytest.raises(ValueError, match="unreachable"):
            reduced[0].experimental_compile()


# ----------------------------------------------------- device channels (TPU)


def test_device_channel_carries_arrays_out_of_band(dag_cluster):
    """DeviceChannel: array bytes ride the object store raw; pytree shape
    and non-array leaves survive; output is a jax.Array on a device."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.dag.channel import DeviceChannel

    name = f"/rt_dch_{uuid.uuid4().hex[:12]}"
    ch = DeviceChannel(name, create=True)
    payload = {
        "x": jnp.arange(200_000, dtype=jnp.float32).reshape(400, 500),
        "meta": {"step": 7},
        "bias": np.ones(3),
    }
    done = {}

    def reader():
        done["out"] = ch.read()

    t = threading.Thread(target=reader)
    t.start()
    ch.write(payload)
    t.join(30)
    out = done["out"]
    assert isinstance(out["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(payload["x"]))
    np.testing.assert_array_equal(np.asarray(out["bias"]), payload["bias"])
    assert out["meta"] == {"step": 7}
    ch.close()


def test_dag_tensor_transport_pipeline(dag_cluster):
    """VERDICT round-1 item: a 2-node pipeline DAG moving device arrays
    via with_tensor_transport — array payloads never ride the pickle
    mailbox (they exceed the tiny control capacity)."""
    import jax
    import jax.numpy as jnp

    cluster = ray_tpu._internal_cluster()
    cluster.add_node({"CPU": 2, "stage0": 1})
    cluster.add_node({"CPU": 2, "stage1": 1})
    time.sleep(0.5)

    @ray_tpu.remote(resources={"stage0": 0.5})
    class Stage0:
        def fwd(self, x):
            return jnp.asarray(x, jnp.float32) * 2.0

    @ray_tpu.remote(resources={"stage1": 0.5})
    class Stage1:
        def fwd(self, x):
            # x must already be a device array on this side
            assert isinstance(x, jax.Array), type(x)
            return x + 1.0

    a, b = Stage0.remote(), Stage1.remote()
    # Warm both actors first (cold jax import in each worker process takes
    # tens of seconds on tiny CI hosts; the DAG clock must not pay it).
    warm = ray_tpu.get(a.fwd.remote(np.ones((2, 2), np.float32)))
    ray_tpu.get(b.fwd.remote(warm))
    with InputNode() as inp:
        mid = a.fwd.bind(inp).with_tensor_transport()
        out = b.fwd.bind(mid).with_tensor_transport()
    dag = out.experimental_compile()
    try:
        for i in range(3):
            # 2MB payload: far beyond the 64KB device-channel mailbox
            x = np.full((512, 1024), float(i), np.float32)
            got = dag.execute(x).get(timeout=120)
            np.testing.assert_allclose(
                np.asarray(got), x * 2.0 + 1.0
            )
    finally:
        dag.teardown()


def test_device_channel_scalar_leaf_keeps_shape(dag_cluster):
    """0-d array leaves must arrive as 0-d (ascontiguousarray promotes to
    (1,) — the recorded shape wins)."""
    import jax.numpy as jnp

    from ray_tpu.dag.channel import DeviceChannel

    name = f"/rt_dch_{uuid.uuid4().hex[:12]}"
    ch = DeviceChannel(name, create=True)
    done = {}
    t = threading.Thread(target=lambda: done.update(out=ch.read()))
    t.start()
    ch.write({"loss": jnp.float32(3.5), "v": jnp.arange(3)})
    t.join(30)
    assert done["out"]["loss"].shape == ()
    assert float(done["out"]["loss"]) == 3.5
    assert done["out"]["v"].shape == (3,)
    ch.close()


def test_execute_async(dag_cluster):
    """asyncio integration (reference: execute_async/CompiledDAGFuture):
    awaited submissions pipeline, results arrive in order, and the loop is
    never blocked by channel reads."""
    import asyncio

    import ray_tpu
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Doubler:
        def run(self, x):
            return x * 2

    a = Doubler.remote()
    with InputNode() as inp:
        dag = a.run.bind(inp)
    compiled = dag.experimental_compile()
    try:
        async def main():
            # 1-slot channels bound the pipeline: keep a rolling window of
            # 2 in flight (the reference caps _max_inflight_executions the
            # same way)
            out = []
            window = []
            for i in range(5):
                window.append(await compiled.execute_async(i))
                if len(window) > 2:
                    out.append(await window.pop(0))
            for f in window:
                out.append(await f)
            return out

        out = asyncio.run(main())
        assert out == [0, 2, 4, 6, 8]

        # awaiting twice is an error (same contract as CompiledDAGRef.get)
        async def double_await():
            fut = await compiled.execute_async(1)
            assert await fut == 2
            try:
                await fut
            except ValueError:
                return True
            return False

        assert asyncio.run(double_await())
    finally:
        compiled.teardown()
