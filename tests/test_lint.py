"""ray_tpu.lint — user-code rules (Family A) and the decoration-time gate.

Every rule gets a positive case (minimal snippet that triggers it) and a
negative case (the fixed form passes). The reference engine only catches
these at runtime (serialization failure at submission, bounded-worker
deadlock, lost exceptions); here they fire statically.
"""
import textwrap

import pytest

from ray_tpu.lint import FAMILY_FRAMEWORK, FAMILY_USER, RULES, lint_source


def lint(src, families=(FAMILY_USER,), **kw):
    return lint_source(textwrap.dedent(src), "<test>", families=families,
                       **kw)


def rule_ids(findings):
    return sorted({f.rule for f in findings})


def test_registry_has_all_families():
    from ray_tpu.lint import PROJECT_RULES

    fams = {r.family for r in RULES.values()}
    assert fams == {"A", "B", "C"}
    assert len([r for r in RULES.values() if r.family == "A"]) >= 4
    assert len([r for r in RULES.values() if r.family == "B"]) >= 4
    assert len([r for r in RULES.values() if r.family == "C"]) >= 5
    # Family D is project-scope and lives in its own registry.
    assert {r.family for r in PROJECT_RULES.values()} == {"D"}
    assert len(PROJECT_RULES) >= 4


# ---------------------------------------------------------------- RT101
def test_rt101_lock_capture_flagged():
    findings = lint("""
        import threading
        import ray_tpu

        state_lock = threading.Lock()

        @ray_tpu.remote
        def task():
            with state_lock:
                return 1
    """)
    assert "RT101" in rule_ids(findings)
    assert "threading.Lock" in findings[0].message


def test_rt101_objectref_capture_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def produce():
            return 1

        ref = produce.remote()

        @ray_tpu.remote
        def consume():
            return ray_tpu.get(ref)
    """)
    assert "RT101" in rule_ids(findings)
    [f] = [f for f in findings if f.rule == "RT101"]
    assert "ObjectRef" in f.message


def test_rt101_clean_when_passed_as_argument():
    findings = lint("""
        import threading
        import ray_tpu

        @ray_tpu.remote
        def task(value):
            lock = threading.Lock()  # created inside: fine
            with lock:
                return value
    """)
    assert "RT101" not in rule_ids(findings)


# ---------------------------------------------------------------- RT102
def test_rt102_blocking_get_in_task_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def child():
            return 1

        @ray_tpu.remote
        def parent():
            return ray_tpu.get(child.remote())
    """)
    assert "RT102" in rule_ids(findings)


def test_rt102_wait_in_sync_actor_method_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        class Pool:
            def drain(self, refs):
                done, rest = ray_tpu.wait(refs, num_returns=1)
                return done
    """)
    assert "RT102" in rule_ids(findings)
    assert "actor method" in findings[0].message


def test_rt102_driver_get_not_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def child():
            return 1

        def driver():
            return ray_tpu.get(child.remote())
    """)
    assert "RT102" not in rule_ids(findings)


def test_rt102_from_import_alias_detected():
    findings = lint("""
        import ray_tpu
        from ray_tpu import get

        @ray_tpu.remote
        def parent(refs):
            return get(refs)
    """)
    assert "RT102" in rule_ids(findings)


# ---------------------------------------------------------------- RT103
def test_rt103_dropped_remote_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def side_effect():
            return 1

        def fire():
            side_effect.remote()
    """)
    assert "RT103" in rule_ids(findings)


def test_rt103_kept_ref_clean():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def side_effect():
            return 1

        def fire():
            refs = [side_effect.remote() for _ in range(3)]
            return ray_tpu.get(refs)
    """)
    assert "RT103" not in rule_ids(findings)


def test_rt103_suppression_comment():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote
        def side_effect():
            return 1

        def fire():
            side_effect.remote()  # raytpu: ignore[RT103]
    """)
    assert "RT103" not in rule_ids(findings)


# ---------------------------------------------------------------- RT104
def test_rt104_fractional_tpus_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote(num_tpus=0.5)
        def step():
            return 1
    """)
    assert "RT104" in rule_ids(findings)
    assert "fractional" in findings[0].message


def test_rt104_negative_resources_flagged():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote(num_cpus=-1)
        def step():
            return 1

        def submit():
            return step.options(resources={"CPU": -2}).remote()
    """)
    assert [f.rule for f in findings if f.rule == "RT104"] == [
        "RT104", "RT104"
    ]


def test_rt104_whole_tpus_clean():
    findings = lint("""
        import ray_tpu

        @ray_tpu.remote(num_tpus=4, num_cpus=1)
        def step():
            return 1
    """)
    assert "RT104" not in rule_ids(findings)


# --------------------------------------------------- decoration-time gate
@pytest.fixture
def lint_on(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LINT", "1")


def test_gate_off_by_default(monkeypatch):
    monkeypatch.delenv("RAY_TPU_LINT", raising=False)
    import ray_tpu

    @ray_tpu.remote
    def hazard(refs):
        return ray_tpu.get(refs)  # would be RT102 with the gate on

    assert hazard.underlying_function is not None


def test_gate_raises_on_blocking_get(lint_on):
    import ray_tpu
    from ray_tpu.exceptions import LintError

    with pytest.raises(LintError, match="RT102"):
        @ray_tpu.remote
        def parent(refs):
            return ray_tpu.get(refs)


def test_gate_raises_on_closure_lock(lint_on):
    import threading

    import ray_tpu
    from ray_tpu.exceptions import LintError

    held = threading.Lock()

    with pytest.raises(LintError, match="RT101"):
        @ray_tpu.remote
        def task():
            with held:
                return 1


def test_gate_raises_on_bad_options_via_options_chain(lint_on):
    import ray_tpu
    from ray_tpu.exceptions import LintError

    @ray_tpu.remote
    def clean():
        return 1

    with pytest.raises(LintError, match="RT104"):
        clean.options(num_tpus=2.5)


def test_gate_checks_actor_classes(lint_on):
    import ray_tpu
    from ray_tpu.exceptions import LintError

    with pytest.raises(LintError, match="RT102"):
        @ray_tpu.remote
        class Worker:
            def step(self, refs):
                return ray_tpu.wait(refs)


def test_gate_clean_task_unaffected(lint_on):
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def clean(x):
        return x + 1

    @ray_tpu.remote
    class CleanActor:
        def step(self, x):
            return x * 2

    assert clean.underlying_function(1) == 2
    assert CleanActor.underlying_class is not None


def test_gate_attribute_name_does_not_false_positive(lint_on):
    """An *attribute* access named like a denylisted module global must
    not trip the closure probe (co_names conflates the two; the probe
    disassembles for LOAD_GLOBAL instead)."""
    import ray_tpu

    @ray_tpu.remote
    def uses_attr(holder):
        with holder.state_lock:  # attribute, not the module global below
            return holder.value

    assert uses_attr.underlying_function is not None


# module global sharing the attribute's name; only a true LOAD_GLOBAL of
# it from a remote fn should matter
import threading as _threading  # noqa: E402

state_lock = _threading.Lock()


def test_gate_value_probe_honors_function_scope_suppression(lint_on):
    import threading

    import ray_tpu

    deliberate = threading.Lock()

    @ray_tpu.remote
    def knows_better():  # raytpu: ignore[RT101]
        return deliberate.locked()

    assert knows_better.underlying_function is not None


def test_gate_clean_task_executes(lint_on, rt_start):
    """A lint-clean task must run end-to-end with the gate enabled."""
    import ray_tpu

    @ray_tpu.remote
    def double(x):
        return x * 2

    assert ray_tpu.get(double.remote(21)) == 42
