"""Tune layer tests (reference test model: ``python/ray/tune/tests/``)."""
import os

import numpy as np

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import RunConfig


def _rc(tmp_path, name):
    return RunConfig(name=name, storage_path=str(tmp_path))


def test_grid_search_runs_all_variants(rt_start, tmp_path):
    def objective(config):
        tune.report({"score": config["a"] * 10 + config["b"]})

    grid = tune.Tuner(
        objective,
        param_space={"a": tune.grid_search([1, 2, 3]),
                     "b": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    max_concurrent_trials=3),
        run_config=_rc(tmp_path, "grid"),
    ).fit()
    assert len(grid) == 6
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.metrics["score"] == 31
    df = grid.get_dataframe()
    assert sorted(df["score"]) == [10, 11, 20, 21, 30, 31]


def test_random_search_domains(rt_start, tmp_path):
    def objective(config):
        assert 1e-4 <= config["lr"] <= 1e-1
        assert config["width"] in (32, 64)
        assert 1 <= config["depth"] < 4
        tune.report({"loss": config["lr"]})

    grid = tune.Tuner(
        objective,
        param_space={
            "lr": tune.loguniform(1e-4, 1e-1),
            "width": tune.choice([32, 64]),
            "depth": tune.randint(1, 4),
        },
        tune_config=tune.TuneConfig(metric="loss", mode="min", num_samples=4,
                                    seed=7, max_concurrent_trials=2),
        run_config=_rc(tmp_path, "rand"),
    ).fit()
    assert len(grid) == 4 and grid.num_errors == 0


def test_asha_stops_bad_trials_early(rt_start, tmp_path):
    def objective(config):
        import time

        for step in range(20):
            # trial quality is config["q"]: lower loss is better; the sleep
            # makes steps slow relative to controller polls (real training
            # steps always are) so early stopping can actually interrupt
            time.sleep(0.05)
            tune.report({"loss": config["q"] + 1.0 / (step + 1),
                         "training_iteration": step + 1})

    grid = tune.Tuner(
        objective,
        param_space={"q": tune.grid_search([0.0, 0.0, 5.0, 5.0, 9.0, 9.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=6,
            scheduler=tune.ASHAScheduler(
                metric="loss", mode="min", grace_period=2,
                reduction_factor=2, max_t=20,
            ),
        ),
        run_config=_rc(tmp_path, "asha"),
    ).fit()
    best = grid.get_best_result()
    assert best.metrics["loss"] < 1.0  # a q=0 trial ran to completion
    iters = [t.iteration for t in grid._trials]
    # at least one bad trial was cut before 20 iterations
    assert min(iters) < 20


def test_trial_error_isolated(rt_start, tmp_path):
    def objective(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"ok": config["x"]})

    grid = tune.Tuner(
        objective,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=tune.TuneConfig(metric="ok", mode="max"),
        run_config=_rc(tmp_path, "err"),
    ).fit()
    assert grid.num_errors == 1
    assert grid.get_best_result().metrics["ok"] == 2


def test_pbt_exploits_checkpoints(rt_start, tmp_path):
    """Bad-hyperparam trials should adopt good trials' checkpoints/configs."""
    import tempfile

    def objective(config):
        # resume accumulated score from checkpoint (what PBT transplants)
        score = 0.0
        ckpt = tune.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "score")) as f:
                score = float(f.read())
        import time

        for step in range(12):
            time.sleep(0.05)  # let controller polls interleave with steps
            score += config["rate"]  # higher rate = faster progress
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "score"), "w") as f:
                    f.write(str(score))
                tune.report(
                    {"score": score, "rate": config["rate"]},
                    checkpoint=tune.Checkpoint.from_directory(d),
                )

    grid = tune.Tuner(
        objective,
        param_space={"rate": tune.grid_search([0.1, 1.0])},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", max_concurrent_trials=2,
            scheduler=tune.PopulationBasedTraining(
                metric="score", mode="max", perturbation_interval=4,
                hyperparam_mutations={"rate": [0.1, 1.0]}, seed=3,
                quantile_fraction=0.5,
            ),
        ),
        run_config=_rc(tmp_path, "pbt"),
    ).fit()
    assert grid.num_errors == 0
    # the exploited lineage exists (a _pbt trial was spawned)
    assert any("_pbt" in t.trial_id for t in grid._trials)
    best = grid.get_best_result()
    assert best.metrics["score"] >= 12 * 1.0 - 4  # good lineage dominated


def test_optuna_search_validation():
    """Gate + argument validation (optuna itself is optional)."""
    from ray_tpu.tune import OptunaSearch

    try:
        import optuna  # noqa: F401
        have_optuna = True
    except ImportError:
        have_optuna = False

    if not have_optuna:
        with pytest.raises(ImportError, match="optuna"):
            OptunaSearch({}, metric="loss")
        return

    from ray_tpu.tune import choice, grid_search, loguniform, uniform

    with pytest.raises(ValueError, match="metric"):
        OptunaSearch({}, metric="")
    with pytest.raises(ValueError, match="mode"):
        OptunaSearch({}, metric="loss", mode="minimize")
    s = OptunaSearch(
        {"lr": loguniform(1e-4, 1e-1), "act": choice(["a", "b"]),
         "c": 3},
        metric="loss", num_samples=2, seed=0,
    )
    cfg = s.suggest("t0")
    assert 1e-4 <= cfg["lr"] <= 1e-1 and cfg["act"] in ("a", "b")
    assert cfg["c"] == 3
    s.on_trial_complete("t0", {"loss": 1.0})
    assert s.suggest("t1") is not None
    assert s.suggest("t2") is None  # num_samples exhausted
    assert s.set_search_properties("loss", "max", {}) is False  # frozen dir
    with pytest.raises(ValueError, match="grid_search"):
        OptunaSearch(
            {"x": grid_search([1, 2])}, metric="loss"
        ).suggest("t")


def test_pb2_gp_exploration_improves(rt_start):
    """PB2 (reference: schedulers/pb2.py): the GP-UCB exploration steers a
    population toward the good lr region of a quadratic objective."""
    from ray_tpu import tune

    def objective(config):
        # best lr at 0.01 (log-scaled bound); iterative so PBT can act
        best = 0.01
        for i in range(8):
            err = (np.log10(config["lr"]) - np.log10(best)) ** 2
            tune.report({"score": -err + 0.01 * i})

    sched = tune.PB2(
        metric="score", mode="max", perturbation_interval=2,
        hyperparam_bounds={"lr": (1e-5, 1.0)}, seed=0,
    )
    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-5, 1.0)},
        tune_config=tune.TuneConfig(
            num_samples=6, scheduler=sched, metric="score", mode="max",
        ),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.metrics["score"] > -4.0
    # GP observations were actually collected
    assert len(sched._y) > 0


def test_bayesopt_beats_random_on_quadratic():
    """Native GP-UCB searcher (no external deps) finds a better optimum
    than random search on a seeded quadratic within a fixed trial budget
    (reference capability: tune/search/bayesopt)."""
    from ray_tpu.tune import BayesOptSearch
    from ray_tpu.tune.search import BasicVariantGenerator, uniform

    def objective(cfg):
        return (cfg["x"] - 0.31) ** 2 + (cfg["y"] - 0.72) ** 2

    def run(searcher, n):
        best = float("inf")
        for i in range(n):
            cfg = searcher.suggest(f"t{i}")
            if cfg is None:
                break
            loss = objective(cfg)
            searcher.on_trial_complete(f"t{i}", {"loss": loss})
            best = min(best, loss)
        return best

    space = {"x": uniform(0, 1), "y": uniform(0, 1)}
    n, wins = 24, 0
    for seed in range(5):
        gp = run(
            BayesOptSearch(
                dict(space), metric="loss", mode="min", num_samples=n,
                seed=seed,
            ),
            n,
        )
        rnd = run(
            BasicVariantGenerator(dict(space), num_samples=n, seed=seed), n
        )
        wins += gp <= rnd
    assert wins >= 4, f"GP-UCB won only {wins}/5 seeds vs random"


def test_bayesopt_with_tuner(rt_start):
    from ray_tpu import tune
    from ray_tpu.tune import BayesOptSearch, Tuner

    def trainable(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2})

    space = {"x": tune.uniform(0, 1)}
    tuner = Tuner(
        trainable,
        param_space=space,
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=8,
            search_alg=BayesOptSearch(
                space, metric="loss", mode="min", num_samples=8, seed=0
            ),
        ),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    assert best.metrics["loss"] < 0.1


def test_gated_searchers():
    """HyperOpt/Nevergrad searchers: without the libs, construction
    raises an error naming built-in alternatives; with them present,
    the ask/tell happy path runs (reference:
    tune/search/hyperopt|nevergrad wrappers)."""
    from ray_tpu import tune as rt_tune

    space = {"lr": rt_tune.uniform(0.0, 1.0),
             "n": rt_tune.choice([1, 2, 3])}
    for cls, mod in ((rt_tune.HyperOptSearch, "hyperopt"),
                     (rt_tune.NevergradSearch, "nevergrad")):
        try:
            __import__(mod)
            available = True
        except ImportError:
            available = False
        if not available:
            with pytest.raises(ImportError, match=mod):
                cls(space, metric="loss")
            continue
        s = cls(space, metric="loss", num_samples=4, seed=0)
        for i in range(4):
            cfg = s.suggest(f"t{i}")
            assert 0.0 <= cfg["lr"] <= 1.0 and cfg["n"] in (1, 2, 3)
            s.on_trial_complete(f"t{i}", {"loss": (cfg["lr"] - 0.3) ** 2})
        assert s.suggest("t5") is None  # budget exhausted
