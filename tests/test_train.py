"""Train layer tests (reference test model: ``python/ray/train/tests/
test_data_parallel_trainer.py`` and v2 controller/worker-group tests —
in-process cluster, fake resources, no real accelerator; SURVEY.md §4)."""
import json
import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)


def _run_config(tmp_path, name, **kw):
    return RunConfig(name=name, storage_path=str(tmp_path), **kw)


def test_two_workers_report_ranks(rt_start, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        train.report(
            {"rank": ctx.get_world_rank(), "world": ctx.get_world_size(),
             "cfg": config["x"]}
        )

    result = DataParallelTrainer(
        train_fn,
        train_loop_config={"x": 41},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=_run_config(tmp_path, "ranks"),
    ).fit()
    # rank 0's report is the tracked metrics stream
    assert result.metrics["rank"] == 0
    assert result.metrics["world"] == 2
    assert result.metrics["cfg"] == 41
    assert result.error is None


def test_checkpointing_and_topk(rt_start, tmp_path):
    def train_fn(config):
        import tempfile

        for step in range(5):
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step))
                train.report(
                    {"score": step}, checkpoint=Checkpoint.from_directory(d)
                )

    result = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=_run_config(
            tmp_path, "topk",
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score"
            ),
        ),
    ).fit()
    run_dir = os.path.join(str(tmp_path), "topk")
    kept = sorted(d for d in os.listdir(run_dir) if d.startswith("checkpoint_"))
    assert len(kept) == 2
    with open(os.path.join(result.checkpoint.path, "step.txt")) as f:
        assert f.read() == "4"  # latest
    assert result.metrics["score"] == 4


def test_failure_retry_resumes_from_checkpoint(rt_start, tmp_path):
    marker = str(tmp_path / "fail_once")

    def train_fn(config):
        import tempfile

        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 6):
            with tempfile.TemporaryDirectory() as d:
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step))
                train.report(
                    {"step": step, "resumed_from": start},
                    checkpoint=Checkpoint.from_directory(d),
                )
            if step == 2 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                raise RuntimeError("injected failure at step 2")

    result = DataParallelTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=_run_config(
            tmp_path, "resume", failure_config=FailureConfig(max_failures=1)
        ),
    ).fit()
    assert result.metrics["step"] == 5
    assert result.metrics["resumed_from"] == 3  # resumed, not restarted


def test_failure_exhausted_raises(rt_start, tmp_path):
    def train_fn(config):
        raise ValueError("always fails")

    with pytest.raises(TrainingFailedError, match="always fails"):
        DataParallelTrainer(
            train_fn,
            scaling_config=ScalingConfig(num_workers=1),
            run_config=_run_config(
                tmp_path, "exhaust", failure_config=FailureConfig(max_failures=1)
            ),
        ).fit()


@pytest.mark.parametrize("rt_start", [{"num_cpus": 8}], indirect=True)
def test_jax_trainer_end_to_end(rt_start, tmp_path):
    """Full SPMD GPT-2 loop through the default train loop: loss decreases
    shape-wise (finite), checkpoints written, resume state round-trips."""
    result = JaxTrainer(
        train_loop_config={
            "model": {
                "vocab_size": 128, "max_seq_len": 32, "num_layers": 2,
                "num_heads": 2, "embed_dim": 32, "dtype": "float32",
                "attention_impl": "xla",
            },
            "mesh": {"data": -1},  # all local devices (8 on the test mesh)
            "num_steps": 3,
            "batch_size": 8,
            "seq_len": 16,
            "checkpoint_every": 0,
            "optimizer": {"warmup_steps": 1, "total_steps": 3},
        },
        scaling_config=ScalingConfig(num_workers=1),
        run_config=_run_config(tmp_path, "jax_e2e"),
    ).fit()
    import math

    assert math.isfinite(result.metrics["loss"])
    assert result.metrics["step"] == 3
    assert result.checkpoint is not None
    # checkpoint restores
    from ray_tpu.train import load_pytree

    state = load_pytree(result.checkpoint.path)
    assert int(state["step"]) == 3


def test_elastic_shrinks_after_node_death(rt_cluster, tmp_path):
    """Kill a node mid-training: the controller restarts the group at the
    smaller world size from the latest checkpoint (SURVEY.md §5 elastic
    training; reference: train/v2 elastic.py + chaos NodeKiller)."""
    ray_tpu_mod, cluster = rt_cluster

    def train_fn(config):
        import tempfile
        import time

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 8):
            if ctx.get_world_rank() == 0:
                with tempfile.TemporaryDirectory() as d:
                    with open(os.path.join(d, "step.txt"), "w") as f:
                        f.write(str(step))
                    train.report(
                        {"step": step, "world": ctx.get_world_size()},
                        checkpoint=Checkpoint.from_directory(d),
                    )
            else:
                train.report({"step": step, "world": ctx.get_world_size()})
            time.sleep(0.15)

    import threading

    def killer():
        import time

        time.sleep(1.2)
        cluster.kill_node(cluster.nodes[1])

    t = threading.Thread(target=killer, daemon=True)
    t.start()
    result = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(
            num_workers=2, min_workers=1,
            resources_per_worker={"CPU": 2},
            placement_strategy="SPREAD",
        ),
        run_config=_run_config(
            tmp_path, "elastic", failure_config=FailureConfig(max_failures=3)
        ),
    ).fit()
    t.join()
    assert result.metrics["step"] == 7
    worlds = {m["world"] for m in result.metrics_history}
    assert 1 in worlds, f"expected shrink to world=1, saw {worlds}"


def test_train_collectives(rt_start, tmp_path):
    """broadcast_from_rank_zero + barrier across a 2-worker group
    (reference: train/collective/collectives.py)."""
    from ray_tpu.train import DataParallelTrainer, ScalingConfig

    def loop(config):
        from ray_tpu.train.collective import barrier, broadcast_from_rank_zero
        from ray_tpu.train.context import get_context, report

        ctx = get_context()
        value = broadcast_from_rank_zero(
            {"master": "rank0-data"} if ctx.get_world_rank() == 0 else None
        )
        barrier()
        report({"got": value["master"], "rank": ctx.get_world_rank()})

    result = DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=_run_config(tmp_path, "collectives"),
    ).fit()
    assert result.metrics["got"] == "rank0-data"


def test_torch_trainer_ddp_gloo(rt_cluster, tmp_path):
    """TorchTrainer: gloo process group forms, DDP gradients sync
    (reference: train/torch TorchConfig + prepare_model). Needs one worker
    per host process (torch.distributed is per-process global), so the
    cluster fixture provides two nodes and workers SPREAD."""
    from ray_tpu.train import ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train.context import get_context, report
        from ray_tpu.train.torch import prepare_model

        ctx = get_context()
        assert dist.is_initialized()
        assert dist.get_world_size() == 2
        model = prepare_model(torch.nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        # rank-dependent data: DDP must average gradients across ranks
        x = torch.ones(8, 4) * (ctx.get_world_rank() + 1)
        y = torch.zeros(8, 1)
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        grad = model.module.weight.grad.clone()
        # allreduce(grad)/world must equal DDP's averaged grad already
        check = grad.clone()
        dist.all_reduce(check)
        assert torch.allclose(check / 2, grad, atol=1e-6)
        opt.step()
        report({"loss": float(loss), "rank": ctx.get_world_rank()})

    result = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=2, placement_strategy="SPREAD",
            resources_per_worker={"CPU": 2},
        ),
        run_config=_run_config(tmp_path, "torch_ddp"),
    ).fit()
    import math

    assert math.isfinite(result.metrics["loss"])


@pytest.mark.parametrize(
    "rt_cluster", [{"num_cpus": 2, "num_nodes": 2}], indirect=True
)
def test_elastic_grows_back_when_node_returns(rt_cluster, tmp_path):
    """2 -> 1 -> 2: kill a node (shrink), return capacity (grow-back from
    the latest checkpoint) — the round-trip the reference's elastic.py
    resize decisions cover (train/v2/.../scaling_policy/elastic.py:29)."""
    import threading
    import time as _t

    ray_tpu_mod, cluster = rt_cluster

    def train_fn(config):
        import tempfile
        import time

        ctx = train.get_context()
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt is not None:
            with open(os.path.join(ckpt.path, "step.txt")) as f:
                start = int(f.read()) + 1
        for step in range(start, 44):
            if ctx.get_world_rank() == 0:
                with tempfile.TemporaryDirectory() as d:
                    with open(os.path.join(d, "step.txt"), "w") as f:
                        f.write(str(step))
                    train.report(
                        {"step": step, "world": ctx.get_world_size()},
                        checkpoint=Checkpoint.from_directory(d),
                    )
            else:
                train.report({"step": step, "world": ctx.get_world_size()})
            time.sleep(0.25)

    def chaos():
        _t.sleep(2.0)
        cluster.kill_node(cluster.nodes[1])  # shrink to 1
        _t.sleep(3.0)
        cluster.add_node({"CPU": 2})  # capacity returns: grow back

    t = threading.Thread(target=chaos, daemon=True)
    t.start()
    result = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(
            num_workers=2, min_workers=1,
            resources_per_worker={"CPU": 2},
            placement_strategy="SPREAD",
        ),
        run_config=_run_config(
            tmp_path, "elastic_grow",
            failure_config=FailureConfig(max_failures=3),
        ),
    ).fit()
    t.join()
    assert result.metrics["step"] == 43
    worlds = [m["world"] for m in result.metrics_history]
    assert 1 in worlds, f"expected shrink to world=1, saw {set(worlds)}"
    # after the shrink, the world grew back to 2 and training RESUMED
    # (later steps at world=2 than the last world=1 step)
    last_w1 = max(i for i, w in enumerate(worlds) if w == 1)
    assert any(w == 2 for w in worlds[last_w1 + 1:]), (
        f"no grow-back after shrink: {worlds}"
    )


def test_megascale_env_rendezvous(tmp_path):
    """get_tpu_coordinator_env_vars output actually lets two simulated
    slices rendezvous: two processes run jax.distributed.initialize with
    the generated MEGASCALE/coordinator settings and agree on the process
    count (reference: util/tpu.py:205 + train/v2/jax/config.py)."""
    import socket
    import subprocess
    import sys

    from ray_tpu.util.tpu import get_tpu_coordinator_env_vars

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"

    script = """
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["RT_COORD"],
    num_processes=2,
    process_id=int(os.environ["RT_PID"]),
)
print(json.dumps({
    "procs": jax.process_count(),
    "idx": jax.process_index(),
    "megascale": {
        k: v for k, v in os.environ.items() if k.startswith("MEGASCALE")
    },
}), flush=True)
"""
    procs = []
    for slice_id in range(2):
        env = dict(
            os.environ,
            RT_COORD=coord,
            RT_PID=str(slice_id),
            JAX_PLATFORMS="cpu",
            **get_tpu_coordinator_env_vars(coord, 2, slice_id),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script],
            stdout=subprocess.PIPE, text=True, env=env,
        ))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=180)
        assert p.returncode == 0
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert {o["idx"] for o in outs} == {0, 1}
    assert all(o["procs"] == 2 for o in outs)
    assert all(
        o["megascale"]["MEGASCALE_COORDINATOR_ADDRESS"] == coord
        for o in outs
    )
    assert {o["megascale"]["MEGASCALE_SLICE_ID"] for o in outs} == {"0", "1"}
