"""Llama model family: RoPE/RMSNorm/SwiGLU/GQA correctness + train/decode.

Reference capability analog: the model families the reference serves via
vLLM passthrough (SURVEY.md §2.4 Ray LLM); here the family is in-framework,
so these tests pin down numerics (cache-consistency, GQA grouping) the way
the reference relies on vLLM's own tests to do.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import get_preset, llama, module_for
from ray_tpu.models.llama import LLAMA_TINY, LlamaConfig


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init_params(LLAMA_TINY, jax.random.PRNGKey(0))


def test_registry_dispatch():
    assert module_for(LLAMA_TINY) is llama
    assert get_preset("llama-tiny") is LLAMA_TINY
    with pytest.raises(KeyError):
        get_preset("nope")


def test_forward_shapes(tiny_params):
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits, aux = llama.forward(tiny_params, tokens, LLAMA_TINY)
    assert logits.shape == (2, 16, LLAMA_TINY.vocab_size)
    assert logits.dtype == jnp.float32
    assert float(aux) == 0.0


def test_param_axes_match_params(tiny_params):
    axes = llama.param_axes(LLAMA_TINY)
    flat_p = jax.tree.leaves(tiny_params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for p, a in zip(flat_p, flat_a):
        assert p.ndim == len(a), (p.shape, a)


def test_causality(tiny_params):
    """Changing a future token must not change past logits."""
    rng = np.random.RandomState(0)
    t1 = rng.randint(0, 512, (1, 12)).astype(np.int32)
    t2 = t1.copy()
    t2[0, -1] = (t2[0, -1] + 7) % 512
    l1, _ = llama.forward(tiny_params, jnp.asarray(t1), LLAMA_TINY)
    l2, _ = llama.forward(tiny_params, jnp.asarray(t2), LLAMA_TINY)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], rtol=2e-3, atol=2e-3)
    assert not np.allclose(l1[0, -1], l2[0, -1], atol=1e-3)


def test_cached_matches_uncached(tiny_params):
    """Prefill + per-token decode must reproduce the full forward logits
    (RoPE at absolute positions, GQA cache) — float32 for tight tolerance."""
    config = LlamaConfig(
        vocab_size=512, max_seq_len=64, num_layers=2, num_heads=4,
        num_kv_heads=2, embed_dim=64, dtype=jnp.float32, remat=False,
    )
    params = llama.init_params(config, jax.random.PRNGKey(1))
    rng = np.random.RandomState(1)
    T = 10
    tokens = jnp.asarray(rng.randint(0, 512, (1, T)), jnp.int32)

    full, _ = llama.forward(params, tokens, config)

    cache = llama.init_kv_cache(config, 1, 32, dtype=jnp.float32)
    # prefill the first 4 tokens at once, then decode one at a time
    logits_p, cache = llama.forward_cached(
        params, tokens[:, :4], cache, jnp.zeros((1,), jnp.int32), config
    )
    np.testing.assert_allclose(logits_p, full[:, :4], rtol=1e-4, atol=1e-4)
    for t in range(4, T):
        step_logits, cache = llama.forward_cached(
            params, tokens[:, t : t + 1], cache,
            jnp.full((1,), t, jnp.int32), config,
        )
        np.testing.assert_allclose(
            step_logits[:, 0], full[:, t], rtol=1e-4, atol=1e-4
        )


def test_gqa_equals_mha_when_groups_1():
    """num_kv_heads == num_heads must behave as plain MHA: the grouped
    einsum path in forward_cached equals forward for g=1 too."""
    config = LlamaConfig(
        vocab_size=128, max_seq_len=32, num_layers=1, num_heads=4,
        num_kv_heads=4, embed_dim=32, dtype=jnp.float32, remat=False,
    )
    params = llama.init_params(config, jax.random.PRNGKey(2))
    tokens = jnp.asarray([[5, 9, 2, 77, 31]], jnp.int32)
    full, _ = llama.forward(params, tokens, config)
    cache = llama.init_kv_cache(config, 1, 16, dtype=jnp.float32)
    cached, _ = llama.forward_cached(
        params, tokens, cache, jnp.zeros((1,), jnp.int32), config
    )
    np.testing.assert_allclose(cached, full, rtol=1e-4, atol=1e-4)


def test_train_step_loss_decreases():
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    config = LlamaConfig(
        vocab_size=256, max_seq_len=32, num_layers=2, num_heads=4,
        num_kv_heads=2, embed_dim=64, dtype=jnp.float32,
    )
    opt = OptimizerConfig(learning_rate=1e-2, warmup_steps=1).build()
    state = create_train_state(config, opt, jax.random.PRNGKey(0))
    step = make_train_step(config, opt)
    rng = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rng.randint(0, 256, (4, 33)), jnp.int32)}
    state, m0 = step(state, batch)
    for _ in range(10):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])


def test_train_step_sharded_mesh():
    """dp x tp mesh on the virtual 8-device CPU mesh."""
    from ray_tpu.parallel.mesh import MeshConfig
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    mesh = MeshConfig(data=2, tensor=4).build(jax.devices()[:8])
    config = LlamaConfig(
        vocab_size=256, max_seq_len=32, num_layers=2, num_heads=8,
        num_kv_heads=4, embed_dim=64, dtype=jnp.float32,
    )
    opt = OptimizerConfig().build()
    state = create_train_state(config, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(config, opt, mesh)
    rng = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rng.randint(0, 256, (4, 33)), jnp.int32)}
    state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_jax_trainer_llama(rt_start, tmp_path):
    """The public Trainer path trains a llama model (family dispatch)."""
    import math

    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    result = JaxTrainer(
        train_loop_config={
            "model": {
                "family": "llama", "vocab_size": 128, "max_seq_len": 32,
                "num_layers": 2, "num_heads": 4, "num_kv_heads": 2,
                "embed_dim": 32, "dtype": "float32",
                "attention_impl": "xla",
            },
            "mesh": {"data": -1},
            "num_steps": 3,
            "batch_size": 8,
            "seq_len": 16,
            "checkpoint_every": 0,
            "optimizer": {"warmup_steps": 1, "total_steps": 3},
        },
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="llama_e2e", storage_path=str(tmp_path)
        ),
    ).fit()
    assert math.isfinite(result.metrics["loss"])


def test_decode_engine_llama():
    from ray_tpu.llm.config import LLMConfig
    from ray_tpu.llm.engine import DecodeEngine, SamplingParams

    cfg = LLMConfig(
        model_id="llama-test", model_family="llama", vocab_size=300,
        max_seq_len=128, num_layers=2, num_heads=4, num_kv_heads=2,
        embed_dim=64, dtype="float32", max_batch_slots=2,
        prefill_buckets=(16, 32),
    )
    eng = DecodeEngine(cfg, seed=0)
    try:
        text = eng.generate_text("hello", SamplingParams(max_new_tokens=4))
        assert isinstance(text, str)
        ids = eng.generate(
            eng.tokenizer.encode("hi"), SamplingParams(max_new_tokens=3)
        )
        assert len(ids) == 3
    finally:
        eng.shutdown()


# ------------------------------------------------------------------ MoE


def test_llama_moe_forward_and_axes():
    """Mixtral-style llama: SwiGLU routed experts replace the dense FFN."""
    from ray_tpu.parallel.moe import MoEConfig

    config = LlamaConfig(
        vocab_size=256, max_seq_len=32, num_layers=2, num_heads=4,
        num_kv_heads=2, embed_dim=64, dtype=jnp.float32,
        moe=MoEConfig(num_experts=4, top_k=2, activation="swiglu"),
    )
    params = llama.init_params(config, jax.random.PRNGKey(0))
    assert "moe" in params["blocks"]
    assert "expert_gate" in params["blocks"]["moe"]  # swiglu experts
    assert "w_gate" not in params["blocks"]          # dense FFN dropped
    axes = llama.param_axes(config)
    flat_p = jax.tree.leaves(params)
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(flat_p) == len(flat_a)
    for p, a in zip(flat_p, flat_a):
        assert p.ndim == len(a), (p.shape, a)

    tokens = jnp.zeros((2, 8), jnp.int32)
    logits, aux = llama.forward(params, tokens, config)
    assert logits.shape == (2, 8, 256)
    assert float(aux) > 0.0  # load-balancing loss is active


def test_llama_moe_trains_on_expert_mesh():
    """EP: expert axis sharded over the virtual mesh; loss decreases."""
    from ray_tpu.parallel.mesh import MeshConfig
    from ray_tpu.parallel.moe import MoEConfig
    from ray_tpu.train.step import (
        OptimizerConfig,
        create_train_state,
        make_train_step,
    )

    mesh = MeshConfig(data=2, expert=4).build(jax.devices()[:8])
    config = LlamaConfig(
        vocab_size=256, max_seq_len=32, num_layers=2, num_heads=4,
        num_kv_heads=2, embed_dim=64, dtype=jnp.float32,
        moe=MoEConfig(num_experts=4, top_k=2, activation="swiglu"),
    )
    opt = OptimizerConfig(learning_rate=1e-2, warmup_steps=1).build()
    state = create_train_state(config, opt, jax.random.PRNGKey(0), mesh)
    step = make_train_step(config, opt, mesh)
    rng = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rng.randint(0, 256, (4, 33)), jnp.int32)}
    state, m0 = step(state, batch)
    for _ in range(8):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])
