"""Native object-transfer plane: C++ TCP server serving shm-backed objects.

Mirrors the reference's object_manager transfer tests
(src/ray/object_manager/: push/pull of chunked object payloads between
nodes) against the ctypes-wrapped src/xfer.cc: segment-backed and
arena-backed objects served over TCP into fresh local segments, plus the
worker-level fetch path.
"""
import os
import secrets

import pytest

from ray_tpu import native as rt_native
from ray_tpu._private.object_store import LocalShmStore
from ray_tpu.native import xfer as native_xfer

# A compile error with a working toolchain is a repo bug and must FAIL the
# suite (collection error), never skip — see test_native_build.py.
if rt_native.load_library() is None and rt_native.build_failure() is not None:
    raise RuntimeError(
        "native build FAILED (compile error, toolchain present):\n"
        + rt_native.build_failure()
    )


@pytest.fixture(scope="module")
def server_port():
    port = native_xfer.start_server("127.0.0.1")
    if port is None:
        # Compile error with a working toolchain = repo bug = FAIL, not skip.
        if rt_native.build_failure() is not None:
            pytest.fail(
                "native build FAILED (compile error, toolchain present):\n"
                + rt_native.build_failure()
            )
        pytest.skip("native toolchain unavailable")
    return port


def _hex() -> str:
    return secrets.token_hex(28)


def test_fetch_segment_roundtrip(server_port):
    src = LocalShmStore(prefix=f"rtsrc{os.getpid()}")
    dst = LocalShmStore(prefix=f"rtdst{os.getpid()}")
    oid = _hex()
    frames = [b"header", os.urandom(200_000), b"", b"tail"]
    meta = src.put_frames(oid, frames)
    try:
        new_meta = native_xfer.fetch_to_segment(
            "127.0.0.1", server_port, meta, oid, dst.seg_name(oid)
        )
        assert new_meta is not None
        assert new_meta["size"] == meta["size"]
        got = dst.get_frames(oid, new_meta)
        assert [bytes(f) for f in got] == frames
        # concurrent-fetcher race: destination exists -> size-0 success
        again = native_xfer.fetch_to_segment(
            "127.0.0.1", server_port, meta, oid, dst.seg_name(oid)
        )
        assert again is not None and again["size"] == 0
    finally:
        dst._created[oid] = True
        dst.free(oid)
        src.free(oid, meta)


def test_fetch_arena_object(server_port):
    from ray_tpu.native import load_library
    from ray_tpu.native.arena import NativeArenaStore

    if load_library() is None:
        pytest.skip("native arena unavailable")
    name = f"/rtx_test_{os.getpid()}_{secrets.token_hex(4)}"
    arena = NativeArenaStore(name, capacity=1 << 24)
    dst = LocalShmStore(prefix=f"rtad{os.getpid()}")
    oid = _hex()
    frames = [os.urandom(64_000), b"x"]
    meta = arena.put_frames(oid, frames)
    assert meta is not None and meta["arena"] == name
    try:
        new_meta = native_xfer.fetch_to_segment(
            "127.0.0.1", server_port, meta, oid, dst.seg_name(oid)
        )
        assert new_meta is not None and new_meta["size"] == meta["size"]
        got = dst.get_frames(oid, new_meta)
        assert [bytes(f) for f in got] == frames
    finally:
        dst._created[oid] = True
        dst.free(oid)
        arena.close_all()


def test_fetch_missing_object(server_port):
    dst = LocalShmStore(prefix=f"rtmiss{os.getpid()}")
    oid = _hex()
    out = native_xfer.fetch_to_segment(
        "127.0.0.1", server_port, {"seg": "rt_no_such_segment"}, oid,
        dst.seg_name(oid),
    )
    assert out is None
    # failed fetch must not leave a destination segment behind
    assert dst.get_frames(oid, {"seg": dst.seg_name(oid)}) is None


def test_fetch_unreachable_server():
    dst = LocalShmStore(prefix=f"rtun{os.getpid()}")
    oid = _hex()
    out = native_xfer.fetch_to_segment(
        "127.0.0.1", 1, {"seg": "rt_x"}, oid, dst.seg_name(oid)
    )
    assert out is None


def test_worker_native_fetch_path():
    """The worker's _native_fetch materializes a foreign segment (one its
    own store cannot resolve — the cross-machine case) via the C++ plane."""
    import ray_tpu

    ray_tpu.init(num_cpus=2, num_nodes=1)
    try:
        w = ray_tpu._private.worker.get_global_worker()
        if w.xfer_addr is None:
            pytest.skip("native xfer unavailable")
        # "remote" object: lives under a prefix the worker's store does not
        # use, so shm.get_frames(meta) would fail but the transfer plane
        # serves it by segment name.
        src = LocalShmStore(prefix=f"rtF{os.getpid()}")
        oid = _hex()
        frames = [b"abc", os.urandom(100_000)]
        meta = dict(src.put_frames(oid, frames), xfer=list(w.xfer_addr))
        try:
            assert w.shm.get_frames(oid, {"seg": "rt_bogus"}) is None
            got = w.run_sync(w._native_fetch(oid, meta))
            assert got is not None
            assert [bytes(f) for f in got] == frames
        finally:
            src.free(oid, meta)
    finally:
        ray_tpu.shutdown()


def test_worker_meta_carries_xfer_addr():
    """Large puts register directory metadata stamped with the owner's
    transfer address, and the cluster still round-trips objects."""
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=2, num_nodes=1)
    try:
        w = ray_tpu._private.worker.get_global_worker()
        if w.xfer_addr is None:
            pytest.skip("native xfer unavailable")
        big = np.arange(300_000, dtype=np.int64)
        ref = ray_tpu.put(big)
        entry = w.memory_store.get(ref.id().hex())
        assert entry[0] == "shm"
        assert entry[1].get("xfer") == list(w.xfer_addr)
        np.testing.assert_array_equal(ray_tpu.get(ref), big)

        @ray_tpu.remote
        def make():
            return np.ones(200_000, dtype=np.float64)

        out_ref = make.remote()
        out = ray_tpu.get(out_ref)
        assert out.shape == (200_000,)
    finally:
        ray_tpu.shutdown()
