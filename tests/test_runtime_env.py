"""Runtime environments: pip venvs, py_modules packaging, plugin validation
(reference: ``python/ray/_private/runtime_env/`` pip/uv/packaging +
worker-pool-per-env)."""
import os
import subprocess
import sys
import zipfile

import pytest

import ray_tpu
from ray_tpu import exceptions as rt_exc


@pytest.fixture
def rt(tmp_path, monkeypatch):
    monkeypatch.setenv("RT_RUNTIME_ENV_DIR", str(tmp_path / "renv"))
    ray_tpu.init(num_cpus=2, num_nodes=1)
    yield
    ray_tpu.shutdown()


def _make_wheel(tmp_path, name="rt_envtest_pkg", version="0.1",
                body="MAGIC = 'wheel-born'\n"):
    """Hand-rolled wheel (a zip + dist-info): fully offline."""
    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as z:
        z.writestr(f"{name}/__init__.py", body)
        z.writestr(
            f"{dist}/METADATA",
            f"Metadata-Version: 2.1\nName: {name}\nVersion: {version}\n",
        )
        z.writestr(
            f"{dist}/WHEEL",
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n",
        )
        z.writestr(f"{dist}/RECORD", "")
    return str(whl)


def test_pip_env_task_runs_with_absent_package(rt, tmp_path):
    """VERDICT round-1 item: a task runs with a package the parent env does
    not have (installed into a cached venv from a local wheel)."""
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    def probe():
        import rt_envtest_pkg

        return rt_envtest_pkg.MAGIC

    # the parent interpreter must NOT see the package
    r = subprocess.run(
        [sys.executable, "-c", "import rt_envtest_pkg"], capture_output=True
    )
    assert r.returncode != 0, "package unexpectedly present in parent env"
    assert ray_tpu.get(probe.remote(), timeout=180) == "wheel-born"


def test_pip_env_venv_is_cached(rt, tmp_path):
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    def pyexe():
        import sys as s

        return s.executable

    first = ray_tpu.get(pyexe.remote(), timeout=180)
    second = ray_tpu.get(pyexe.remote(), timeout=60)
    assert first == second, "same env spec must reuse the cached venv"
    assert first != sys.executable


def test_py_modules_ships_local_module(rt, tmp_path):
    mod = tmp_path / "shipped_mod"
    mod.mkdir()
    (mod / "__init__.py").write_text("VALUE = 41\n")
    (mod / "extra.py").write_text("def f():\n    return 1\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def use():
        import shipped_mod
        from shipped_mod.extra import f

        return shipped_mod.VALUE + f()

    assert ray_tpu.get(use.remote(), timeout=60) == 42


def test_unknown_plugin_fails_loudly(rt):
    @ray_tpu.remote(runtime_env={"conda": ["something"]})
    def nope():
        return 1

    with pytest.raises(rt_exc.RayTpuError):
        ray_tpu.get(nope.remote(), timeout=60)


def test_env_vars_still_work(rt):
    @ray_tpu.remote(runtime_env={"env_vars": {"RT_TEST_FLAG": "on"}})
    def read():
        return os.environ.get("RT_TEST_FLAG")

    assert ray_tpu.get(read.remote(), timeout=60) == "on"


def test_pip_task_print_does_not_corrupt_protocol(rt, tmp_path):
    """Task prints ride stderr in the venv child; the result pipe stays
    clean."""
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    def chatty():
        print("this goes to stderr, not the protocol pipe")
        import rt_envtest_pkg

        return rt_envtest_pkg.MAGIC

    assert ray_tpu.get(chatty.remote(), timeout=180) == "wheel-born"


def test_pip_env_vars_apply_per_call(rt, tmp_path):
    """Cached executors must not bake in the first task's env_vars."""
    wheel = _make_wheel(tmp_path)

    def read_flag():
        return os.environ.get("RT_PIP_FLAG")

    a = ray_tpu.remote(
        runtime_env={"pip": [wheel], "env_vars": {"RT_PIP_FLAG": "A"}}
    )(read_flag)
    b = ray_tpu.remote(
        runtime_env={"pip": [wheel], "env_vars": {"RT_PIP_FLAG": "B"}}
    )(read_flag)
    assert ray_tpu.get(a.remote(), timeout=180) == "A"
    assert ray_tpu.get(b.remote(), timeout=60) == "B"


def test_pip_unpicklable_result_is_task_error(rt, tmp_path):
    wheel = _make_wheel(tmp_path)

    @ray_tpu.remote(runtime_env={"pip": [wheel]})
    def bad():
        import threading

        return threading.Lock()  # not serializable

    with pytest.raises(rt_exc.RayTpuError, match="serializable"):
        ray_tpu.get(bad.remote(), timeout=120)


def test_conda_image_uri_plugins_validate_and_gate(rt_start):
    """conda/image_uri are accepted plugins (reference:
    runtime_env/conda.py, image_uri.py); without their binaries on PATH
    the task fails LOUDLY with the binary requirement, never silently
    running outside the requested env."""
    import shutil as _shutil

    import pytest as _pytest

    from ray_tpu._private import runtime_env as renv_mod

    # validation accepts both (unknown plugins still rejected)
    renv_mod.validate({"conda": ["scipy"]})
    renv_mod.validate({"image_uri": "python:3.12-slim"})
    with _pytest.raises(Exception, match="not supported"):
        renv_mod.validate({"bogus_plugin": 1})

    @ray_tpu.remote(runtime_env={"conda": ["scipy"]})
    def in_conda():
        return 1

    if _shutil.which("conda") is None:
        with _pytest.raises(Exception, match="conda"):
            ray_tpu.get(in_conda.remote(), timeout=60)

    @ray_tpu.remote(runtime_env={"image_uri": "python:3.12-slim"})
    def in_container():
        return 1

    if _shutil.which("docker") is None and _shutil.which("podman") is None:
        with _pytest.raises(Exception, match="podman or docker"):
            ray_tpu.get(in_container.remote(), timeout=60)


def test_conda_env_key_stable():
    from ray_tpu._private.runtime_env.conda import conda_env_key

    assert conda_env_key(["a", "b"]) == conda_env_key(["a", "b"])
    assert conda_env_key(["a"]) != conda_env_key(["b"])
    assert conda_env_key({"dependencies": ["x"]}).startswith("conda-")


def _hook_counter():
    import os

    os.environ["RT_TEST_HOOK_RAN"] = str(
        int(os.environ.get("RT_TEST_HOOK_RAN", "0")) + 1
    )


def test_worker_process_setup_hook_runs_once(rt):
    """A pickled setup hook runs once per worker process before the first
    task of that env (reference: runtime_env/setup_hook.py)."""

    @ray_tpu.remote
    def probe():
        import os

        return os.environ.get("RT_TEST_HOOK_RAN")

    renv = {"worker_process_setup_hook": _hook_counter}
    r1 = ray_tpu.get(probe.options(runtime_env=renv).remote())
    r2 = ray_tpu.get(probe.options(runtime_env=renv).remote())
    assert r1 == "1"
    assert r2 == "1"  # once per process, not per task


def test_worker_process_setup_hook_failure_fails_task(rt):
    def boom():
        raise RuntimeError("hook exploded")

    @ray_tpu.remote
    def probe():
        return 1

    with pytest.raises(Exception, match="hook exploded"):
        ray_tpu.get(probe.options(
            runtime_env={"worker_process_setup_hook": boom}
        ).remote())


def test_worker_process_setup_hook_module_path(rt):
    @ray_tpu.remote
    def probe():
        import os

        return os.environ.get("RT_TEST_HOOK_RAN", "0")

    out = ray_tpu.get(probe.options(runtime_env={
        "worker_process_setup_hook":
            "tests.test_runtime_env._hook_counter"
    }).remote())
    assert int(out) >= 1


def test_setup_hook_runs_after_env_vars_and_py_modules(rt, tmp_path):
    """The hook sees the env it was shipped with (reference semantics:
    setup hook runs after the rest of the env is prepared)."""

    def hook():
        import os

        assert os.environ.get("HOOK_NEEDS_THIS") == "yes"
        os.environ["HOOK_SAW_ENV"] = "1"

    @ray_tpu.remote
    def probe():
        import os

        return os.environ.get("HOOK_SAW_ENV")

    out = ray_tpu.get(probe.options(runtime_env={
        "env_vars": {"HOOK_NEEDS_THIS": "yes"},
        "worker_process_setup_hook": hook,
    }).remote())
    assert out == "1"


def test_setup_hook_runs_in_venv_child(rt):
    """pip-isolated tasks run the hook inside the env-executor child
    (the process that actually executes the task)."""

    def hook():
        import os

        os.environ["CHILD_HOOK"] = f"pid-{os.getpid()}"

    @ray_tpu.remote
    def probe():
        import os

        return os.environ.get("CHILD_HOOK"), os.getpid()

    marker, pid = ray_tpu.get(probe.options(runtime_env={
        "pip": [], "worker_process_setup_hook": hook,
    }).remote())
    assert marker == f"pid-{pid}"  # ran in the same process as the task
