"""Tune experiment state + Tuner.restore.

Reference analog: ``tune/execution/experiment_state.py`` (resumable
experiment checkpointing) + ``Tuner.restore`` — interrupted/failed trials
resume from their recorded state and checkpoints; finished trials are not
re-run.
"""
import json
import os

import pytest

import ray_tpu
from ray_tpu import train as rt_train
from ray_tpu import tune
from ray_tpu.tune.tuner import _STATE_FILE


@pytest.fixture
def tune_cluster():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _make_trainable(marker_dir: str):
    def trainable(config):
        # count executions per trial so the test can see what re-ran
        runs_file = os.path.join(marker_dir, f"runs_{config['name']}")
        n_prior = 0
        if os.path.exists(runs_file):
            with open(runs_file) as f:
                n_prior = int(f.read() or 0)
        with open(runs_file, "w") as f:
            f.write(str(n_prior + 1))
        for i in range(3):
            if (
                config["name"] == "bad"
                and i == 1
                and not os.path.exists(os.path.join(marker_dir, "fixed"))
            ):
                raise RuntimeError("transient failure")
            rt_train.report({"score": config["base"] + i})

    return trainable


def test_restore_resumes_errored_not_finished(tune_cluster, tmp_path):
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    trainable = _make_trainable(marker_dir)

    tuner = tune.Tuner(
        trainable,
        param_space={
            "name": tune.grid_search(["good", "bad"]),
            "base": 10,
        },
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=1),
        run_config=rt_train.RunConfig(
            name="restore_exp", storage_path=str(tmp_path)
        ),
    )
    grid = tuner.fit()
    assert grid.num_errors == 1
    run_dir = str(tmp_path / "restore_exp")
    assert os.path.exists(os.path.join(run_dir, _STATE_FILE))
    with open(os.path.join(run_dir, _STATE_FILE)) as f:
        state = json.load(f)
    statuses = {t["trial_id"]: t["status"] for t in state["trials"]}
    assert sorted(statuses.values()) == ["ERROR", "TERMINATED"]

    # fix the transient failure, then resume
    open(os.path.join(marker_dir, "fixed"), "w").close()
    grid2 = tune.Tuner.restore(
        run_dir, trainable, resume_errored=True
    ).fit()
    assert grid2.num_errors == 0
    best = grid2.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] == 12

    # the finished trial ran once; the errored one ran twice; and no new
    # trials were minted on restore
    with open(os.path.join(marker_dir, "runs_good")) as f:
        assert f.read() == "1"
    with open(os.path.join(marker_dir, "runs_bad")) as f:
        assert f.read() == "2"
    assert len(grid2) == 2


def test_restore_without_resume_errored_keeps_failure(tune_cluster, tmp_path):
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    trainable = _make_trainable(marker_dir)
    tune.Tuner(
        trainable,
        param_space={"name": tune.grid_search(["good", "bad"]), "base": 0},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=rt_train.RunConfig(name="exp2", storage_path=str(tmp_path)),
    ).fit()
    grid = tune.Tuner.restore(
        str(tmp_path / "exp2"), trainable, resume_errored=False
    ).fit()
    assert grid.num_errors == 1  # stays failed; nothing re-ran
    with open(os.path.join(marker_dir, "runs_bad")) as f:
        assert f.read() == "1"


def test_restore_missing_state_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        tune.Tuner.restore(str(tmp_path / "nope"), lambda c: None)
