"""Serve controller actor: deployment/replica state machines + autoscaling.

Reference analogs: ``python/ray/serve/_private/controller.py:126``
(ServeController, reconcile loop :506), ``deployment_state.py`` (replica
state machine), ``autoscaling_policy.py`` (+ ``_private/autoscaling_state``:
scale on ongoing-request metrics), ``_private/deployment_scheduler.py``.
Runs as a named actor; handles query it for the live replica set.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "__serve_controller"


class _DeploymentState:
    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec             # serialized target + config fields
        self.replicas: List[dict] = []  # {"actor": handle, "id": str}
        # Replicas removed from the routable set but still finishing
        # in-flight requests (graceful drain); entries carry
        # "drain_deadline" and "drain_zero" (consecutive idle probes).
        self.draining: List[dict] = []
        self.target_replicas = spec["num_replicas"]
        self.counter = 0
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.deleted = False


class ServeController:
    """Async actor: one reconcile loop drives every deployment."""

    HANDLE_METRIC_TTL_S = 3.0

    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._apps: Dict[str, List[str]] = {}  # app name -> deployment names
        self._routes: Dict[str, str] = {}      # route_prefix -> deployment
        # deployment -> {handle_id: (ongoing, monotonic ts)}; pushed by
        # handle routers (queued + executing requests they have issued).
        self._handle_metrics: Dict[str, Dict[str, tuple]] = {}
        self._loop_task = None
        self._running = True
        self._reconcile_lock = asyncio.Lock()
        # Serializes whole deploy() calls (incl. the post-reconcile-lock
        # reconfigure fan-out) so two concurrent deploys of one deployment
        # can't interleave reconfigure RPCs (last-deploy-wins, not
        # last-RPC-wins). Separate from _reconcile_lock on purpose: holding
        # THAT across the bounded 30s gather would stall health checks.
        self._deploy_lock = asyncio.Lock()

    def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._reconcile_loop()
            )

    # ------------------------------------------------------------ deploy API

    async def deploy(self, app_name: str, deployments: List[dict],
                     route_prefix: Optional[str], ingress: str) -> dict:
        """deployments: [{name, serialized_target, init_args_ser,
        num_replicas, max_ongoing, actor_options, user_config,
        autoscaling (dict|None), version}]"""
        self._ensure_loop()
        async with self._deploy_lock:
            return await self._deploy_inner(
                app_name, deployments, route_prefix, ingress
            )

    async def _deploy_inner(self, app_name: str, deployments: List[dict],
                            route_prefix: Optional[str], ingress: str) -> dict:
        names = []
        to_reconfigure = []
        # Hold the reconcile lock: an in-flight reconcile pass may be mid
        # _start_replica and would append an old-version replica after the
        # teardown below. Replica reconfigure RPCs run AFTER release — they
        # can queue behind saturated replicas, and holding the lock across
        # that await would wedge the whole controller.
        async with self._reconcile_lock:
            for spec in deployments:
                name = spec["name"]
                names.append(name)
                existing = self._deployments.get(name)
                if existing is None:
                    self._deployments[name] = _DeploymentState(name, spec)
                else:
                    old_version = existing.spec.get("version")
                    existing.spec = spec
                    existing.target_replicas = spec["num_replicas"]
                    if spec.get("version") != old_version:
                        # rolling update: retire old-version replicas
                        # GRACEFULLY (stop routing now, let in-flight
                        # requests finish up to the drain deadline); the
                        # reconcile loop starts fresh ones immediately
                        for r in existing.replicas:
                            self._begin_drain(r)
                            existing.draining.append(r)
                        existing.replicas = []
                    elif spec.get("user_config") is not None:
                        to_reconfigure.extend(
                            (r, spec["user_config"])
                            for r in existing.replicas
                        )
        if to_reconfigure:
            async def _one(r, user_config):
                try:
                    await asyncio.wait_for(
                        self._call(r, "reconfigure", user_config), timeout=30
                    )
                except Exception:
                    pass

            await asyncio.gather(
                *(_one(r, cfg) for r, cfg in to_reconfigure)
            )
        self._apps[app_name] = names
        if route_prefix:
            self._routes[route_prefix] = ingress
        await self._reconcile_once()
        return {"ok": True, "deployments": names}

    async def delete_app(self, app_name: str) -> dict:
        for name in self._apps.pop(app_name, []):
            st = self._deployments.get(name)
            if st:
                st.deleted = True
                st.target_replicas = 0
        self._routes = {
            k: v for k, v in self._routes.items()
            if v in {d for ds in self._apps.values() for d in ds}
        }
        await self._reconcile_once()
        return {"ok": True}

    # ------------------------------------------------------------- query API

    def get_replicas(self, deployment: str) -> List[str]:
        st = self._deployments.get(deployment)
        if st is None:
            return []
        return [r["id"] for r in st.replicas]

    def get_handles(self, deployment: str) -> List[Any]:
        st = self._deployments.get(deployment)
        if st is None:
            return []
        return [r["actor"] for r in st.replicas]

    def get_router_info(self, deployment: str) -> dict:
        """Everything a handle router needs in ONE call: the replica
        handles plus routing config (load-shed cap)."""
        st = self._deployments.get(deployment)
        if st is None:
            return {"handles": [], "max_queued": -1, "max_ongoing": 16}
        mq = st.spec.get("max_queued")
        return {
            "handles": [r["actor"] for r in st.replicas],
            # no `or -1`: an explicit 0 (reject-all/drain) must survive
            "max_queued": -1 if mq is None else int(mq),
            "max_ongoing": int(st.spec.get("max_ongoing", 16)),
        }

    def _publish_replica_change(self, name: str):
        """Push-invalidate every handle's cached replica set (the
        long-poll fan-out analog, reference: serve/_private/long_poll.py
        — here a head pubsub message; handles re-fetch on receipt instead
        of polling at a tight interval)."""
        try:
            from ray_tpu._private.worker import get_global_worker

            get_global_worker().gcs.notify(
                "publish",
                {"channel": f"serve_replicas:{name}", "data": {}},
            )
        except Exception as e:
            # push is an optimization; the poll fallback covers it
            logger.debug("replica-change publish for %s dropped: %s",
                         name, e)

    def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    def status(self) -> dict:
        return {
            name: {
                "target": st.target_replicas,
                "running": len(st.replicas),
                "draining": len(st.draining),
                "deleted": st.deleted,
            }
            for name, st in self._deployments.items()
        }

    async def shutdown(self) -> bool:
        self._running = False
        async with self._reconcile_lock:  # wait out an in-flight pass
            for st in self._deployments.values():
                for r in st.replicas + st.draining:
                    await self._stop_replica(r)
                st.replicas = []
                st.draining = []
        return True

    # --------------------------------------------------------- reconcile

    async def _reconcile_loop(self):
        while self._running:
            try:
                await self._reconcile_once()
                await self._autoscale()
            except Exception:
                pass
            await asyncio.sleep(0.25)

    async def _reconcile_once(self):
        # Serialized: deploy() also reconciles, and two interleaved passes
        # would both see len < target and double-start replicas.
        async with self._reconcile_lock:
            await self._reconcile_inner()

    async def _reconcile_inner(self):
        if not self._running:
            # A pass queued behind shutdown() must not resurrect replicas
            # that shutdown just killed.
            return
        for st in list(self._deployments.values()):
            before = [r["id"] for r in st.replicas]
            while len(st.replicas) < st.target_replicas:
                r = await self._start_replica(st)
                if r is None:
                    break
                st.replicas.append(r)
            while len(st.replicas) > st.target_replicas:
                # Graceful scale-down: leave the routable set NOW (the
                # publish below makes handles re-fetch), finish in-flight
                # work, stop later — zero dropped requests.
                r = st.replicas.pop()
                self._begin_drain(r)
                st.draining.append(r)
            await self._process_draining(st)
            if st.deleted and not st.replicas and not st.draining:
                self._deployments.pop(st.name, None)
            if [r["id"] for r in st.replicas] != before:
                self._publish_replica_change(st.name)
        # health: drop dead replicas so the loop replaces them. A gang
        # replica is healthy only if EVERY member answers (scale-as-a-unit);
        # a failed gang is torn down whole so its surviving members and the
        # placement group's reservations don't leak.
        for st in self._deployments.values():
            alive = []
            for r in st.replicas:
                members = r.get("members") or [r["actor"]]
                try:
                    await asyncio.gather(*(
                        asyncio.wait_for(
                            self._await_ref(m.health_check.remote()),
                            timeout=5,
                        )
                        for m in members
                    ))
                    alive.append(r)
                except Exception:
                    await self._stop_replica(r)  # reconcile restarts it
            if len(alive) != len(st.replicas):
                self._publish_replica_change(st.name)
            st.replicas = alive

    def _begin_drain(self, r: dict):
        """Stamp the drain horizon (reference: proxy/replica draining —
        ``serve/_private/proxy_state.py`` is_drained + replica
        graceful_shutdown_timeout_s)."""
        from ray_tpu._private.config import rt_config

        r["drain_deadline"] = (
            time.monotonic() + float(rt_config.serve_drain_deadline_s)
        )
        r["drain_zero"] = 0

    async def _process_draining(self, st: _DeploymentState):
        """Stop a draining replica once idle or past its deadline. A
        replica counts as idle only after TWO consecutive zero probes one
        reconcile tick apart: a request routed just before the handles saw
        the replica-change push can still be invisible in the actor
        mailbox on the first read."""
        async def _judge(r: dict) -> bool:
            """True when the replica should stop now (idle twice, dead, or
            past its deadline)."""
            if time.monotonic() > r["drain_deadline"]:
                return True
            try:
                probe = await asyncio.wait_for(
                    self._call(r, "drain"), timeout=5
                )
                if probe["ongoing"] == 0 and probe["streams"] == 0:
                    r["drain_zero"] += 1
                else:
                    r["drain_zero"] = 0
            except asyncio.TimeoutError:
                # SLOW is not DEAD: a replica busy past the probe window
                # (GIL-bound user code, big serialization) may still be
                # finishing real requests — cutting it here would drop
                # them. The drain deadline is the only slowness horizon.
                logger.debug("drain probe for %s timed out", r["id"])
                r["drain_zero"] = 0
            except Exception as e:
                logger.debug("drain probe for %s failed: %s", r["id"], e)
                return True  # replica dead/unreachable: nothing to wait for
            return r["drain_zero"] >= 2

        # Probe concurrently (style of _check_replicas): N unreachable
        # draining replicas must cost one 5s probe window per reconcile
        # pass, not N serialized timeouts stalling every deployment.
        verdicts = await asyncio.gather(
            *(_judge(r) for r in st.draining), return_exceptions=True
        )
        still: List[dict] = []
        for r, stop in zip(st.draining, verdicts):
            if isinstance(stop, BaseException) or stop:
                await self._stop_replica(r)
            else:
                still.append(r)
        st.draining = still

    async def _start_replica(self, st: _DeploymentState) -> Optional[dict]:
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        spec = st.spec
        rid = f"{st.name}#{st.counter}"
        st.counter += 1
        opts = dict(spec.get("actor_options") or {})
        opts.setdefault("max_concurrency", max(spec["max_ongoing"], 2))
        # Health checks / queue-len polls ride their own executor lane so a
        # replica whose request slots are all busy still answers the
        # controller and router (reference: Serve replicas run control
        # methods on a dedicated concurrency group). Merged (not
        # setdefault): Replica's decorated methods hard-require "control",
        # so user-supplied groups must not clobber it.
        opts["concurrency_groups"] = {
            **(opts.get("concurrency_groups") or {}), "control": 2
        }
        gang = int(spec.get("gang_size") or 1)
        if gang > 1:
            return await self._start_gang_replica(st, rid, opts, gang)
        try:
            actor_cls = ray_tpu.remote(Replica)
            actor = actor_cls.options(**opts).remote(
                spec["serialized_target"],
                spec.get("init_args", ()),
                spec.get("init_kwargs", {}),
                spec.get("user_config"),
            )
            # wait (bounded) for construction to finish or raise; a wedged
            # start must not stall the reconcile loop forever
            try:
                await asyncio.wait_for(
                    self._await_ref(actor.health_check.remote()), timeout=60
                )
            except BaseException:
                await self._stop_replica({"actor": actor})  # don't leak it
                raise
            return {"actor": actor, "id": rid}
        except Exception:
            return None

    async def _start_gang_replica(self, st, rid, opts, gang):
        """One replica = a gang of actors co-reserved via a placement group
        (reference: ``serve/gang.py:9 GangContext`` + gang autoscaling — a
        multi-host model replica, e.g. one ICI slice, scales as a unit).
        Rank 0 serves requests; every member gets a GangContext."""
        import ray_tpu
        from ray_tpu.serve.replica import Replica
        from ray_tpu.util.placement_group import (
            placement_group,
            remove_placement_group,
        )
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
        )

        from ray_tpu.remote_function import _build_resources

        spec = st.spec
        # Bundles must reserve EXACTLY what the member actors will request
        # (num_tpus/num_gpus included), or the in-pg lease can never fit.
        bundle = _build_resources(opts)
        pg = None
        actors = []
        loop = asyncio.get_running_loop()
        try:
            # PACK by default (works single-host); multi-host slice gangs
            # pass gang_strategy="STRICT_SPREAD" to force one host per rank.
            # Both pg calls block in run_sync — keep them off this shared
            # async-actor loop.
            pg = await loop.run_in_executor(
                None,
                lambda: placement_group(
                    [dict(bundle) for _ in range(gang)],
                    strategy=spec.get("gang_strategy") or "PACK",
                ),
            )
            if not await loop.run_in_executor(None, pg.ready, 60.0):
                raise RuntimeError(f"gang pg for {rid} not placeable")
            actor_cls = ray_tpu.remote(Replica)
            for rank in range(gang):
                a_opts = dict(opts)
                a_opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=rank
                )
                actors.append(actor_cls.options(**a_opts).remote(
                    spec["serialized_target"],
                    spec.get("init_args", ()),
                    spec.get("init_kwargs", {}),
                    spec.get("user_config"),
                    gang_ctx={
                        "rank": rank, "world_size": gang,
                        "replica_id": rid, "pg_id": pg.id,
                    },
                ))
            await asyncio.gather(*(
                asyncio.wait_for(
                    self._await_ref(a.health_check.remote()), timeout=60
                )
                for a in actors
            ))
            return {"actor": actors[0], "id": rid, "members": actors,
                    "pg": pg}
        except BaseException as e:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            if pg is not None:
                try:
                    await loop.run_in_executor(
                        None, remove_placement_group, pg
                    )
                except Exception:
                    pass
            if not isinstance(e, Exception):
                raise  # CancelledError etc. must propagate after cleanup
            return None

    async def _stop_replica(self, r: dict):
        import ray_tpu

        for actor in r.get("members") or [r["actor"]]:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        if r.get("pg") is not None:
            try:
                from ray_tpu.util.placement_group import (
                    remove_placement_group,
                )

                await asyncio.get_running_loop().run_in_executor(
                    None, remove_placement_group, r["pg"]
                )
            except Exception:
                pass

    async def _call(self, r: dict, method: str, *args):
        ref = getattr(r["actor"], method).remote(*args)
        return await self._await_ref(ref)

    async def _await_ref(self, ref):
        from ray_tpu._private.worker import get_global_worker

        return await get_global_worker().as_asyncio_future(ref)

    # --------------------------------------------------------- autoscaling

    def record_handle_metrics(self, deployment: str, handle_id: str,
                              ongoing: int) -> int:
        """Ack codes: 1 = stored; 0 = deployment unknown (transient — e.g.
        mid-redeploy or controller restart; keep pushing); -1 = deployment
        doesn't autoscale (permanent — the handle stops pushing; nothing is
        stored, since unbounded handle-id churn would grow the map forever)."""
        st = self._deployments.get(deployment)
        if st is None:
            return 0
        if not st.spec.get("autoscaling"):
            self._handle_metrics.pop(deployment, None)
            return -1
        self._handle_metrics.setdefault(deployment, {})[handle_id] = (
            ongoing, time.monotonic()
        )
        return 1

    def _handle_reported_total(self, deployment: str) -> int:
        now = time.monotonic()
        metrics = self._handle_metrics.get(deployment, {})
        for hid in [h for h, (_, ts) in metrics.items()
                    if now - ts > self.HANDLE_METRIC_TTL_S]:
            metrics.pop(hid, None)
        return sum(n for n, _ in metrics.values())

    async def _autoscale(self):
        for st in self._deployments.values():
            asc = st.spec.get("autoscaling")
            if not asc or st.deleted or not st.replicas:
                continue
            # Replica-reported executing count can undercount (queued
            # requests are invisible in the actor mailbox), so take the max
            # with the handle-reported in-flight totals.
            total = 0
            for r in st.replicas:
                try:
                    total += await asyncio.wait_for(
                        self._call(r, "queue_len"), timeout=2
                    )
                except Exception:
                    pass
            total = max(total, self._handle_reported_total(st.name))
            import math

            desired = math.ceil(total / asc["target_ongoing_requests"]) or 1
            desired = min(max(desired, asc["min_replicas"]),
                          asc["max_replicas"])
            now = time.monotonic()
            if desired > st.target_replicas and (
                now - st.last_scale_up > asc["upscale_delay_s"]
            ):
                st.target_replicas = desired
                st.last_scale_up = now
            elif desired < st.target_replicas and (
                now - st.last_scale_down > asc["downscale_delay_s"]
            ):
                st.target_replicas = max(desired, asc["min_replicas"])
                st.last_scale_down = now
