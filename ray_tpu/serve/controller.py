"""Serve controller actor: deployment/replica state machines + autoscaling.

Reference analogs: ``python/ray/serve/_private/controller.py:126``
(ServeController, reconcile loop :506), ``deployment_state.py`` (replica
state machine), ``autoscaling_policy.py`` (+ ``_private/autoscaling_state``:
scale on ongoing-request metrics), ``_private/deployment_scheduler.py``.
Runs as a named actor; handles query it for the live replica set.
"""
from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional

CONTROLLER_NAME = "__serve_controller"


class _DeploymentState:
    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec             # serialized target + config fields
        self.replicas: List[dict] = []  # {"actor": handle, "id": str}
        self.target_replicas = spec["num_replicas"]
        self.counter = 0
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.deleted = False


class ServeController:
    """Async actor: one reconcile loop drives every deployment."""

    HANDLE_METRIC_TTL_S = 3.0

    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._apps: Dict[str, List[str]] = {}  # app name -> deployment names
        self._routes: Dict[str, str] = {}      # route_prefix -> deployment
        # deployment -> {handle_id: (ongoing, monotonic ts)}; pushed by
        # handle routers (queued + executing requests they have issued).
        self._handle_metrics: Dict[str, Dict[str, tuple]] = {}
        self._loop_task = None
        self._running = True
        self._reconcile_lock = asyncio.Lock()
        # Serializes whole deploy() calls (incl. the post-reconcile-lock
        # reconfigure fan-out) so two concurrent deploys of one deployment
        # can't interleave reconfigure RPCs (last-deploy-wins, not
        # last-RPC-wins). Separate from _reconcile_lock on purpose: holding
        # THAT across the bounded 30s gather would stall health checks.
        self._deploy_lock = asyncio.Lock()

    def _ensure_loop(self):
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._reconcile_loop()
            )

    # ------------------------------------------------------------ deploy API

    async def deploy(self, app_name: str, deployments: List[dict],
                     route_prefix: Optional[str], ingress: str) -> dict:
        """deployments: [{name, serialized_target, init_args_ser,
        num_replicas, max_ongoing, actor_options, user_config,
        autoscaling (dict|None), version}]"""
        self._ensure_loop()
        async with self._deploy_lock:
            return await self._deploy_inner(
                app_name, deployments, route_prefix, ingress
            )

    async def _deploy_inner(self, app_name: str, deployments: List[dict],
                            route_prefix: Optional[str], ingress: str) -> dict:
        names = []
        to_reconfigure = []
        # Hold the reconcile lock: an in-flight reconcile pass may be mid
        # _start_replica and would append an old-version replica after the
        # teardown below. Replica reconfigure RPCs run AFTER release — they
        # can queue behind saturated replicas, and holding the lock across
        # that await would wedge the whole controller.
        async with self._reconcile_lock:
            for spec in deployments:
                name = spec["name"]
                names.append(name)
                existing = self._deployments.get(name)
                if existing is None:
                    self._deployments[name] = _DeploymentState(name, spec)
                else:
                    old_version = existing.spec.get("version")
                    existing.spec = spec
                    existing.target_replicas = spec["num_replicas"]
                    if spec.get("version") != old_version:
                        # rolling update: retire old-version replicas; the
                        # reconcile loop will start fresh ones
                        for r in existing.replicas:
                            await self._stop_replica(r)
                        existing.replicas = []
                    elif spec.get("user_config") is not None:
                        to_reconfigure.extend(
                            (r, spec["user_config"])
                            for r in existing.replicas
                        )
        if to_reconfigure:
            async def _one(r, user_config):
                try:
                    await asyncio.wait_for(
                        self._call(r, "reconfigure", user_config), timeout=30
                    )
                except Exception:
                    pass

            await asyncio.gather(
                *(_one(r, cfg) for r, cfg in to_reconfigure)
            )
        self._apps[app_name] = names
        if route_prefix:
            self._routes[route_prefix] = ingress
        await self._reconcile_once()
        return {"ok": True, "deployments": names}

    async def delete_app(self, app_name: str) -> dict:
        for name in self._apps.pop(app_name, []):
            st = self._deployments.get(name)
            if st:
                st.deleted = True
                st.target_replicas = 0
        self._routes = {
            k: v for k, v in self._routes.items()
            if v in {d for ds in self._apps.values() for d in ds}
        }
        await self._reconcile_once()
        return {"ok": True}

    # ------------------------------------------------------------- query API

    def get_replicas(self, deployment: str) -> List[str]:
        st = self._deployments.get(deployment)
        if st is None:
            return []
        return [r["id"] for r in st.replicas]

    def get_handles(self, deployment: str) -> List[Any]:
        st = self._deployments.get(deployment)
        if st is None:
            return []
        return [r["actor"] for r in st.replicas]

    def get_routes(self) -> Dict[str, str]:
        return dict(self._routes)

    def status(self) -> dict:
        return {
            name: {
                "target": st.target_replicas,
                "running": len(st.replicas),
                "deleted": st.deleted,
            }
            for name, st in self._deployments.items()
        }

    async def shutdown(self) -> bool:
        self._running = False
        async with self._reconcile_lock:  # wait out an in-flight pass
            for st in self._deployments.values():
                for r in st.replicas:
                    await self._stop_replica(r)
                st.replicas = []
        return True

    # --------------------------------------------------------- reconcile

    async def _reconcile_loop(self):
        while self._running:
            try:
                await self._reconcile_once()
                await self._autoscale()
            except Exception:
                pass
            await asyncio.sleep(0.25)

    async def _reconcile_once(self):
        # Serialized: deploy() also reconciles, and two interleaved passes
        # would both see len < target and double-start replicas.
        async with self._reconcile_lock:
            await self._reconcile_inner()

    async def _reconcile_inner(self):
        if not self._running:
            # A pass queued behind shutdown() must not resurrect replicas
            # that shutdown just killed.
            return
        for st in list(self._deployments.values()):
            while len(st.replicas) < st.target_replicas:
                r = await self._start_replica(st)
                if r is None:
                    break
                st.replicas.append(r)
            while len(st.replicas) > st.target_replicas:
                await self._stop_replica(st.replicas.pop())
            if st.deleted and not st.replicas:
                self._deployments.pop(st.name, None)
        # health: drop dead replicas so the loop replaces them
        for st in self._deployments.values():
            alive = []
            for r in st.replicas:
                try:
                    ok = await asyncio.wait_for(
                        self._call(r, "health_check"), timeout=5
                    )
                    alive.append(r)
                except Exception:
                    pass  # dead → not re-added; reconcile restarts
            st.replicas = alive

    async def _start_replica(self, st: _DeploymentState) -> Optional[dict]:
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        spec = st.spec
        rid = f"{st.name}#{st.counter}"
        st.counter += 1
        opts = dict(spec.get("actor_options") or {})
        opts.setdefault("max_concurrency", max(spec["max_ongoing"], 2))
        try:
            actor_cls = ray_tpu.remote(Replica)
            actor = actor_cls.options(**opts).remote(
                spec["serialized_target"],
                spec.get("init_args", ()),
                spec.get("init_kwargs", {}),
                spec.get("user_config"),
            )
            # wait (bounded) for construction to finish or raise; a wedged
            # start must not stall the reconcile loop forever
            try:
                await asyncio.wait_for(
                    self._await_ref(actor.health_check.remote()), timeout=60
                )
            except BaseException:
                await self._stop_replica({"actor": actor})  # don't leak it
                raise
            return {"actor": actor, "id": rid}
        except Exception:
            return None

    async def _stop_replica(self, r: dict):
        import ray_tpu

        try:
            ray_tpu.kill(r["actor"])
        except Exception:
            pass

    async def _call(self, r: dict, method: str, *args):
        ref = getattr(r["actor"], method).remote(*args)
        return await self._await_ref(ref)

    async def _await_ref(self, ref):
        from ray_tpu._private.worker import get_global_worker

        return await get_global_worker().as_asyncio_future(ref)

    # --------------------------------------------------------- autoscaling

    def record_handle_metrics(self, deployment: str, handle_id: str,
                              ongoing: int) -> int:
        """Ack codes: 1 = stored; 0 = deployment unknown (transient — e.g.
        mid-redeploy or controller restart; keep pushing); -1 = deployment
        doesn't autoscale (permanent — the handle stops pushing; nothing is
        stored, since unbounded handle-id churn would grow the map forever)."""
        st = self._deployments.get(deployment)
        if st is None:
            return 0
        if not st.spec.get("autoscaling"):
            self._handle_metrics.pop(deployment, None)
            return -1
        self._handle_metrics.setdefault(deployment, {})[handle_id] = (
            ongoing, time.monotonic()
        )
        return 1

    def _handle_reported_total(self, deployment: str) -> int:
        now = time.monotonic()
        metrics = self._handle_metrics.get(deployment, {})
        for hid in [h for h, (_, ts) in metrics.items()
                    if now - ts > self.HANDLE_METRIC_TTL_S]:
            metrics.pop(hid, None)
        return sum(n for n, _ in metrics.values())

    async def _autoscale(self):
        for st in self._deployments.values():
            asc = st.spec.get("autoscaling")
            if not asc or st.deleted or not st.replicas:
                continue
            # Replica-reported executing count can undercount (queued
            # requests are invisible in the actor mailbox), so take the max
            # with the handle-reported in-flight totals.
            total = 0
            for r in st.replicas:
                try:
                    total += await asyncio.wait_for(
                        self._call(r, "queue_len"), timeout=2
                    )
                except Exception:
                    pass
            total = max(total, self._handle_reported_total(st.name))
            import math

            desired = math.ceil(total / asc["target_ongoing_requests"]) or 1
            desired = min(max(desired, asc["min_replicas"]),
                          asc["max_replicas"])
            now = time.monotonic()
            if desired > st.target_replicas and (
                now - st.last_scale_up > asc["upscale_delay_s"]
            ):
                st.target_replicas = desired
                st.last_scale_up = now
            elif desired < st.target_replicas and (
                now - st.last_scale_down > asc["downscale_delay_s"]
            ):
                st.target_replicas = max(desired, asc["min_replicas"])
                st.last_scale_down = now
