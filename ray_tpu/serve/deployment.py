"""Deployment declaration and binding.

Reference analogs: ``python/ray/serve/deployment.py`` (Deployment),
``python/ray/serve/api.py:869`` (serve.run), autoscaling config
(``serve/config.py AutoscalingConfig``). ``.bind()`` builds a composition
graph: bound deployments appearing in another deployment's init args are
deployed too and replaced with handles (reference: handle-based model
composition).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 16
    # Handle-side load shedding (reference: Serve max_queued_requests):
    # when this many requests are already in flight across the handle's
    # replicas, further submissions raise BackPressureError (the HTTP
    # proxy maps it to 503) instead of queueing without bound. -1 = off.
    max_queued_requests: int = -1
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    user_config: Optional[dict] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    version: Optional[str] = None
    gang_size: int = 1  # multi-host replica groups (reference: serve/gang.py)
    gang_strategy: Optional[str] = None  # PACK (default) | STRICT_SPREAD


class Deployment:
    def __init__(self, cls_or_fn, name: str, config: DeploymentConfig):
        self._target = cls_or_fn
        self._name = name
        self._config = config

    @property
    def name(self) -> str:
        return self._name

    @property
    def config(self) -> DeploymentConfig:
        return self._config

    @property
    def target(self):
        return self._target

    def options(self, **kwargs) -> "Deployment":
        import dataclasses

        cfg = dataclasses.replace(self._config)
        name = kwargs.pop("name", self._name)
        for k, v in kwargs.items():
            if not hasattr(cfg, k):
                raise ValueError(f"unknown deployment option '{k}'")
            setattr(cfg, k, v)
        return Deployment(self._target, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self._name})"


class Application:
    """A bound deployment (+ its bound dependencies)."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def dependencies(self) -> List["Application"]:
        deps = []

        def scan(v):
            if isinstance(v, Application):
                deps.append(v)
        for a in self.args:
            scan(a)
        for a in self.kwargs.values():
            scan(a)
        return deps


def deployment(cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 16,
               max_queued_requests: int = -1,
               ray_actor_options: Optional[dict] = None,
               user_config: Optional[dict] = None,
               autoscaling_config: Optional[AutoscalingConfig] = None,
               version: Optional[str] = None,
               gang_size: int = 1,
               gang_strategy: Optional[str] = None,
               health_check_period_s: float = 2.0):
    """``@serve.deployment`` decorator (reference: ``serve/api.py``)."""

    def wrap(target):
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            ray_actor_options=dict(ray_actor_options or {}),
            user_config=user_config,
            autoscaling_config=asc,
            version=version,
            gang_size=gang_size,
            gang_strategy=gang_strategy,
            health_check_period_s=health_check_period_s,
        )
        return Deployment(target, name or target.__name__, cfg)

    if cls_or_fn is not None:
        return wrap(cls_or_fn)
    return wrap
