"""Model multiplexing: many models behind one deployment.

Reference analog: ``python/ray/serve/multiplex.py`` (``_ModelMultiplexWrapper``)
+ ``api.py @serve.multiplexed`` + ``get_multiplexed_model_id``. A deployment
decorates an async ``get_model(model_id)`` loader; each replica keeps an LRU
of up to ``max_num_models_per_replica`` loaded models, and the router prefers
replicas that already hold the requested model (falling back to
power-of-two-choices — the model then loads where the request lands).
"""
from __future__ import annotations

import asyncio
import contextvars
import inspect
import weakref
from collections import OrderedDict
from typing import Any, Dict, List

_model_id_var: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """Inside a request: the model id the caller routed with (may be "")."""
    return _model_id_var.get()


def _set_request_model_id(model_id: str):
    _model_id_var.set(model_id or "")


class _ModelCache:
    """LRU of loaded models with per-key load dedup."""

    def __init__(self, loader, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: Dict[str, asyncio.Future] = {}
        self._lock = asyncio.Lock()

    def ids(self) -> List[str]:
        return list(self._models.keys())

    async def get(self, model_id: str):
        async with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            fut = self._loading.get(model_id)
            if fut is None:
                fut = asyncio.get_running_loop().create_future()
                self._loading[model_id] = fut
                owner = True
            else:
                owner = False
        if not owner:
            return await asyncio.shield(fut)
        try:
            model = self._loader(model_id)
            if inspect.isawaitable(model):
                model = await model
        except Exception as e:
            async with self._lock:
                self._loading.pop(model_id, None)
            fut.set_exception(e)
            raise
        async with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            evicted = []
            while len(self._models) > self._max:
                _, old = self._models.popitem(last=False)
                evicted.append(old)
            self._loading.pop(model_id, None)
        for old in evicted:
            # best-effort unload hook (reference: __del__ on eviction)
            try:
                if hasattr(old, "__serve_multiplex_unload__"):
                    old.__serve_multiplex_unload__()
                del old
            except Exception:
                pass
        fut.set_result(model)
        return model


def multiplexed(fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate an async ``get_model(self, model_id)`` loader
    (reference: ``serve.multiplexed``). The wrapper LRU-caches models
    per replica and dedups concurrent loads of the same id."""

    def wrap(f):
        # owner id(instance) -> cache (0 for free functions). Entries die
        # with their instance (weakref.finalize) so replaced replicas
        # co-hosted in the same worker process don't pin models forever.
        caches: Dict[int, _ModelCache] = {}

        async def wrapper(self_or_id, *args):
            if args:  # method: (self, model_id)
                inst, model_id = self_or_id, args[0]
                owner = id(inst)
                loader = f.__get__(inst)
            else:  # free function: (model_id,)
                inst, owner, model_id = None, 0, self_or_id
                loader = f
            cache = caches.get(owner)
            if cache is None:
                cache = caches[owner] = _ModelCache(
                    loader, max_num_models_per_replica
                )
                if inst is not None:
                    try:
                        weakref.finalize(inst, caches.pop, owner, None)
                    except TypeError:
                        pass  # non-weakrefable instance: cache rides the class
            return await cache.get(model_id)

        wrapper._is_serve_multiplexed = True
        wrapper._rt_caches = caches
        return wrapper

    if fn is not None:
        return wrap(fn)
    return wrap


def instance_model_ids(instance: Any) -> List[str]:
    """Model ids held by THIS deployment instance (the replica's report —
    never other actors co-hosted in the process)."""
    out: List[str] = []
    if getattr(instance, "_is_serve_multiplexed", False):
        cache = getattr(instance, "_rt_caches", {}).get(0)
        if cache is not None:
            out.extend(cache.ids())
        return out
    for name in dir(instance):
        if name.startswith("__"):
            continue
        try:
            attr = getattr(instance, name)
        except Exception:
            continue
        if getattr(attr, "_is_serve_multiplexed", False):
            cache = getattr(attr, "_rt_caches", {}).get(id(instance))
            if cache is not None:
                out.extend(cache.ids())
    return out
