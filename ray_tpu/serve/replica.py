"""Replica actor: hosts one instance of a deployment's callable.

Reference analog: ``python/ray/serve/_private/replica.py``. Tracks ongoing
requests (the router's and autoscaler's load signal), supports async and
sync callables, ``reconfigure`` (user_config updates without restart), and
dynamic batching via :func:`batch`.
"""
from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.actor import method as _actor_method


class GangContext:
    """Rank/world view for one member of a gang replica (reference:
    ``serve/gang.py:9 GangContext``)."""

    def __init__(self, rank: int, world_size: int, replica_id: str,
                 pg_id: str):
        self.rank = rank
        self.world_size = world_size
        self.replica_id = replica_id
        self.placement_group_id = pg_id


# Gang members can share one host process (actors are threads there), so the
# context must never be a bare module global: it rides a ContextVar — set in
# the constructing thread around target construction (contextvars are
# per-thread for raw threads, and each actor has its own pool) and set again
# per request in handle_request (copied into executor threads). No lock:
# serializing constructions would deadlock PACK gangs whose constructors
# rendezvous with each other.
import contextvars as _contextvars

_gang_ctx_var: "_contextvars.ContextVar[Optional[GangContext]]" = (
    _contextvars.ContextVar("rt_gang_ctx", default=None)
)


def get_gang_context() -> Optional[GangContext]:
    """Inside a gang replica member: its GangContext (None otherwise)."""
    return _gang_ctx_var.get()


class Replica:
    """Created via ray_tpu.remote with max_concurrency > 1 so requests
    overlap; ``_ongoing`` is the live load metric."""

    def __init__(self, serialized_target, init_args, init_kwargs,
                 user_config=None, gang_ctx: Optional[dict] = None):
        import cloudpickle

        self._gang_ctx = GangContext(**gang_ctx) if gang_ctx else None
        target = cloudpickle.loads(serialized_target)
        self._is_function = not inspect.isclass(target)
        if self._is_function:
            self._instance = target
        else:
            token = _gang_ctx_var.set(self._gang_ctx)
            try:
                self._instance = target(*init_args, **init_kwargs)
            finally:
                _gang_ctx_var.reset(token)
        self._ongoing = 0
        self._total = 0
        # live generator streams: stream_id -> [iter, last_access, model_id]
        self._streams: Dict[str, list] = {}
        self._stream_seq = 0
        # Graceful drain: the controller stopped routing to this replica
        # and is waiting for _ongoing + _streams to reach zero before
        # stopping it (requests already in the mailbox still run — zero
        # dropped requests on scale-down).
        self._draining = False
        if user_config is not None:
            self.reconfigure(user_config)

    @_actor_method(concurrency_group="control")
    def reconfigure(self, user_config) -> bool:
        if hasattr(self._instance, "reconfigure"):
            self._instance.reconfigure(user_config)
        return True

    @_actor_method(concurrency_group="control")
    def health_check(self) -> bool:
        if hasattr(self._instance, "check_health"):
            self._instance.check_health()
        return True

    @_actor_method(concurrency_group="control")
    def queue_len(self) -> int:
        return self._ongoing

    @_actor_method(concurrency_group="control")
    def stats(self) -> dict:
        return {"ongoing": self._ongoing, "total": self._total,
                "streams": len(self._streams),
                "draining": self._draining}

    @_actor_method(concurrency_group="control")
    def drain(self) -> dict:
        """Controller drain probe (reference: replica graceful shutdown —
        ``_private/replica.py`` perform_graceful_shutdown): marks the
        replica draining and reports live load. The control concurrency
        group keeps this answerable while request lanes are saturated."""
        self._draining = True
        return {"ongoing": self._ongoing, "streams": len(self._streams)}

    @_actor_method(concurrency_group="control")
    def multiplexed_ids(self) -> List[str]:
        """Model ids THIS replica's instance holds (router affinity;
        reference: replica-side model-id reporting in ``serve/multiplex.py``)."""
        from ray_tpu.serve.multiplex import instance_model_ids

        return instance_model_ids(self._instance)

    # ------------------------------------------------------------ streaming

    def _register_stream(self, gen, model_id: Optional[str]) -> dict:
        self._stream_seq += 1
        sid = f"s{self._stream_seq}"
        self._streams[sid] = [gen, time.monotonic(), model_id]
        return {"__rt_stream__": sid}

    @_actor_method(concurrency_group="control")
    async def cancel_stream(self, stream_id: str) -> bool:
        """Release an abandoned stream NOW (client disconnected): pop the
        record and close the generator so its finally blocks run and the
        slot frees immediately instead of waiting for the 10-minute idle
        sweep. Idempotent — unknown/finished ids return False. Rides the
        control group: when the request lanes are saturated is exactly
        when freeing a slot matters most, so the cancel must not queue
        behind the wedge it is relieving."""
        rec = self._streams.pop(stream_id, None)
        if rec is None:
            return False
        gen = rec[0]
        # The cancel usually races an in-flight next_chunks pull (a
        # stream spends most of its wall time inside __anext__): closing
        # a RUNNING generator raises "already executing/running" and the
        # user finally blocks would never run. Retry until the current
        # pull yields the frame back (bounded; the idle sweep is the
        # backstop for a generator that never yields again).
        import logging

        log = logging.getLogger(__name__)
        deadline = time.monotonic() + 5.0
        while True:
            try:
                if inspect.isasyncgen(gen):
                    await gen.aclose()
                elif hasattr(gen, "close"):
                    # sync generator: close() runs its finally block; keep
                    # any blocking cleanup off this event loop
                    await asyncio.get_running_loop().run_in_executor(
                        None, gen.close
                    )
                return True
            except (RuntimeError, ValueError) as e:
                if "already" in str(e) and time.monotonic() < deadline:
                    await asyncio.sleep(0.05)
                    continue
                log.debug("stream %s generator close raised: %s",
                          stream_id, e)
                return True
            except Exception as e:
                log.debug("stream %s generator close raised: %s",
                          stream_id, e)
                return True

    async def next_chunks(self, stream_id: str, max_n: int = 16):
        """Pull up to max_n chunks; returns (chunks, done). Abandoned
        streams are swept after 10 minutes idle; pulling a swept (or
        unknown) stream raises instead of faking a clean end."""
        now = time.monotonic()
        for sid in [
            s for s, rec in self._streams.items() if now - rec[1] > 600
        ]:
            self._streams.pop(sid, None)
        rec = self._streams.get(stream_id)
        if rec is None:
            raise ValueError(
                f"stream {stream_id} unknown or expired (streams idle "
                f">600s are swept); chunks may have been lost"
            )
        gen, _, model_id = rec
        rec[1] = now
        # The generator body runs in THIS task (async gen) or an executor
        # thread (sync gen), not the handle_request task that created the
        # stream — restore its request context here.
        if self._gang_ctx is not None:
            _gang_ctx_var.set(self._gang_ctx)
        if model_id is not None:
            from ray_tpu.serve.multiplex import _set_request_model_id

            _set_request_model_id(model_id)
        chunks: List[Any] = []
        try:
            if inspect.isasyncgen(gen):
                while len(chunks) < max_n:
                    try:
                        chunks.append(await gen.__anext__())
                    except StopAsyncIteration:
                        self._streams.pop(stream_id, None)
                        return chunks, True
            else:
                import contextvars

                loop = asyncio.get_running_loop()

                def pull():
                    out = []
                    try:
                        while len(out) < max_n:
                            out.append(next(gen))
                    except StopIteration:
                        return out, True
                    return out, False

                call_ctx = contextvars.copy_context()
                chunks, done = await loop.run_in_executor(
                    None, lambda: call_ctx.run(pull)
                )
                if done:
                    self._streams.pop(stream_id, None)
                return chunks, done
        except Exception:
            self._streams.pop(stream_id, None)
            raise
        return chunks, False

    async def handle_request(self, method: str, args, kwargs,
                             model_id: Optional[str] = None,
                             stream: bool = False):
        if self._gang_ctx is not None:
            _gang_ctx_var.set(self._gang_ctx)
        if model_id is not None:
            from ray_tpu.serve.multiplex import _set_request_model_id

            _set_request_model_id(model_id)
        self._ongoing += 1
        self._total += 1
        try:
            if self._is_function:
                fn = self._instance
            else:
                fn = getattr(self._instance, method)
            if inspect.isasyncgenfunction(fn) or (
                stream and inspect.isgeneratorfunction(fn)
            ):
                return self._register_stream(fn(*args, **kwargs), model_id)
            if inspect.iscoroutinefunction(fn) or (
                hasattr(fn, "_is_serve_batch")
            ):
                out = await fn(*args, **kwargs)
                if stream and inspect.isgenerator(out):
                    return self._register_stream(out, model_id)
                return out
            # Sync callables run on an executor thread: they may block (e.g.
            # a composition handle's .result()) and must not stall this
            # replica's event loop. copy_context carries the GangContext var
            # into the thread (run_in_executor alone would not).
            import contextvars

            loop = asyncio.get_running_loop()
            call_ctx = contextvars.copy_context()
            out = await loop.run_in_executor(
                None, lambda: call_ctx.run(fn, *args, **kwargs)
            )
            if inspect.isawaitable(out):
                out = await out
            if stream and inspect.isgenerator(out):
                return self._register_stream(out, model_id)
            return out
        finally:
            self._ongoing -= 1


class _BatchQueue:
    """Accumulates calls until max_batch_size or batch_wait_timeout_s."""

    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queue: List[tuple] = []
        self._flush_task: Optional[asyncio.Task] = None

    async def submit(self, item):
        fut = asyncio.get_running_loop().create_future()
        self._queue.append((item, fut))
        if len(self._queue) >= self._max:
            await self._flush()
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = asyncio.get_running_loop().create_task(
                self._delayed_flush()
            )
        return await fut

    async def _delayed_flush(self):
        await asyncio.sleep(self._timeout)
        await self._flush()

    async def _flush(self):
        if not self._queue:
            return
        batch, self._queue = self._queue, []
        items = [b[0] for b in batch]
        try:
            outs = self._fn(items)
            if inspect.isawaitable(outs):
                outs = await outs
            if len(outs) != len(items):
                raise ValueError(
                    f"batched fn returned {len(outs)} results for "
                    f"{len(items)} inputs"
                )
            for (_, fut), out in zip(batch, outs):
                if not fut.done():
                    fut.set_result(out)
        except Exception as e:  # propagate to every waiter
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: N concurrent single-item calls → one list call
    (reference: ``python/ray/serve/batching.py``). Decorate an async method
    taking a list and returning an equal-length list."""

    def wrap(f):
        queues: Dict[int, _BatchQueue] = {}

        async def wrapper(self_or_item, *args):
            # methods: (self, item); free functions: (item,)
            if args:
                owner, item = id(self_or_item), args[0]
                bound = f.__get__(self_or_item)  # bind self
            else:
                owner, item = 0, self_or_item
                bound = f
            q = queues.get(owner)
            if q is None:
                q = queues[owner] = _BatchQueue(
                    bound, max_batch_size, batch_wait_timeout_s
                )
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if fn is not None:
        return wrap(fn)
    return wrap
