"""HTTP ingress proxy (reference: ``python/ray/serve/_private/proxy.py`` —
per-node ProxyActor routing HTTP to replicas via the router).

An aiohttp server inside an async actor. Routes come from the controller's
route table (longest-prefix match); request bodies pass to the ingress
deployment's ``__call__`` as a dict: ``{"body": bytes, "path": str,
"query": dict, "headers": dict, "method": str}`` — JSON responses are
serialized automatically.
"""
from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._handles: Dict[str, object] = {}
        self._runner = None
        self._site = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self._host, self._port)
        await self._site.start()
        port = self._site._server.sockets[0].getsockname()[1]
        self._port = port
        return port

    def port(self) -> int:
        return self._port

    def _route_for(self, path: str) -> Optional[str]:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        routes = ray_tpu.get(
            ray_tpu.get_actor(CONTROLLER_NAME).get_routes.remote(), timeout=10
        )
        best = None
        for prefix, deployment in routes.items():
            if path.startswith(prefix) and (
                best is None or len(prefix) > len(best[0])
            ):
                best = (prefix, deployment)
        return None if best is None else best[1]

    async def _handle(self, request):
        from aiohttp import web

        deployment = self._route_for(request.path)
        if deployment is None:
            return web.Response(status=404, text="no route")
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(deployment)
        if handle is None:
            handle = self._handles[deployment] = DeploymentHandle(deployment)
        body = await request.read()
        payload = {
            "body": body,
            "path": request.path,
            "query": dict(request.query),
            "headers": dict(request.headers),
            "method": request.method,
        }
        loop = asyncio.get_running_loop()
        # SSE streaming: a JSON body with "stream": true rides the serve
        # streaming protocol (replica-side generator) and is forwarded as
        # text/event-stream chunks (reference: Serve HTTP streaming
        # responses / OpenAI stream=true).
        wants_stream = False
        try:
            parsed = json.loads(body or b"{}")
            wants_stream = bool(
                isinstance(parsed, dict) and parsed.get("stream")
            )
        except json.JSONDecodeError:
            pass
        if wants_stream:
            return await self._handle_stream(
                request, handle.options(stream=True), payload, loop
            )
        try:
            resp = handle.remote(payload)
            out = await loop.run_in_executor(None, resp.result, 60)
        except Exception as e:
            from ray_tpu.serve.handle import BackPressureError

            if isinstance(e, BackPressureError):
                # saturated replicas: shed load (reference: Serve returns
                # 503 when max_queued_requests is exceeded)
                return web.Response(
                    status=503, text=str(e),
                    headers={"Retry-After": "1"},
                )
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out))
        if isinstance(out, str):
            return web.Response(text=out)
        return web.json_response(out)

    async def _handle_stream(self, request, handle, payload, loop):
        import logging

        from aiohttp import web

        logger = logging.getLogger(__name__)
        done = object()  # StopIteration cannot cross an executor Future
        try:
            gen = handle.remote(payload)
            it = await loop.run_in_executor(None, iter, gen)
            # Per-chunk deadline: a wedged replica must terminate the
            # connection (the non-streaming path bounds result() at 60s)
            first = await asyncio.wait_for(
                loop.run_in_executor(None, next, it, done), timeout=300
            )
        except Exception as e:
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if first is not done and not isinstance(first, (str, bytes,
                                                        bytearray)):
            # The deployment chose not to stream (e.g. stream=true with
            # options the endpoint serves non-incrementally): a plain
            # object response comes back as JSON, not a broken SSE body.
            return web.json_response(first)
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        chunk = first
        try:
            while chunk is not done:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                elif not isinstance(chunk, (bytes, bytearray)):
                    # generic generator deployments may yield objects:
                    # frame them as JSON lines rather than dropping them
                    chunk = (json.dumps(chunk) + "\n").encode()
                await resp.write(chunk)
                chunk = await asyncio.wait_for(
                    loop.run_in_executor(None, next, it, done), timeout=300
                )
        except Exception:
            # mid-stream failure: the stream ends early — log it, a silent
            # truncation is indistinguishable from success
            logger.exception("stream to %s ended on error", request.path)
        await resp.write_eof()
        return resp

    async def stop(self) -> bool:
        if self._runner is not None:
            await self._runner.cleanup()
        return True
