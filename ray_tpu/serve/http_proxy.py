"""HTTP ingress proxy (reference: ``python/ray/serve/_private/proxy.py`` —
per-node ProxyActor routing HTTP to replicas via the router).

An aiohttp server inside an async actor. Routes come from the controller's
route table (longest-prefix match); request bodies pass to the ingress
deployment's ``__call__`` as a dict: ``{"body": bytes, "path": str,
"query": dict, "headers": dict, "method": str}`` — JSON responses are
serialized automatically.
"""
from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional


class HTTPProxy:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._handles: Dict[str, object] = {}
        self._runner = None
        self._site = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self._host, self._port)
        await self._site.start()
        port = self._site._server.sockets[0].getsockname()[1]
        self._port = port
        return port

    def port(self) -> int:
        return self._port

    def _route_for(self, path: str) -> Optional[str]:
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        routes = ray_tpu.get(
            ray_tpu.get_actor(CONTROLLER_NAME).get_routes.remote(), timeout=10
        )
        best = None
        for prefix, deployment in routes.items():
            if path.startswith(prefix) and (
                best is None or len(prefix) > len(best[0])
            ):
                best = (prefix, deployment)
        return None if best is None else best[1]

    async def _handle(self, request):
        from aiohttp import web

        deployment = self._route_for(request.path)
        if deployment is None:
            return web.Response(status=404, text="no route")
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(deployment)
        if handle is None:
            handle = self._handles[deployment] = DeploymentHandle(deployment)
        body = await request.read()
        payload = {
            "body": body,
            "path": request.path,
            "query": dict(request.query),
            "headers": dict(request.headers),
            "method": request.method,
        }
        loop = asyncio.get_running_loop()
        try:
            resp = handle.remote(payload)
            out = await loop.run_in_executor(None, resp.result, 60)
        except Exception as e:
            from ray_tpu.serve.handle import BackPressureError

            if isinstance(e, BackPressureError):
                # saturated replicas: shed load (reference: Serve returns
                # 503 when max_queued_requests is exceeded)
                return web.Response(
                    status=503, text=str(e),
                    headers={"Retry-After": "1"},
                )
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out))
        if isinstance(out, str):
            return web.Response(text=out)
        return web.json_response(out)

    async def stop(self) -> bool:
        if self._runner is not None:
            await self._runner.cleanup()
        return True
