"""HTTP ingress proxy (reference: ``python/ray/serve/_private/proxy.py`` —
per-node ProxyActor routing HTTP to replicas via the router).

An aiohttp server inside an async actor. Routes come from the controller's
route table (longest-prefix match); request bodies pass to the ingress
deployment's ``__call__`` as a dict: ``{"body": bytes, "path": str,
"query": dict, "headers": dict, "method": str}`` — JSON responses are
serialized automatically.

Production semantics (reference: the proxy's request lifecycle):

- **Admission control**: a global in-flight cap (``rt_config.
  serve_max_inflight``) sheds excess load with 503 + ``Retry-After``
  before any routing work happens.
- **Deadlines**: per-request result deadline (``serve_request_timeout_s``)
  maps to 504 + ``Retry-After``; per-chunk stream deadline
  (``serve_stream_chunk_timeout_s``) bounds wedged streams.
- **Typed status mapping**: infra failures the client may retry
  (saturation, replica death mid-request) are 503 + ``Retry-After``;
  deadlines are 504; only APPLICATION errors are 500.
- **Streams fail loudly**: a mid-stream failure emits a terminal
  ``event: error`` SSE frame instead of silently truncating, and a client
  disconnect cancels the replica-side generator so its slot frees now.
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

_ROUTE_TTL_S = 1.0  # controller route-table cache horizon


def _classify_error(e: BaseException) -> str:
    """'retryable' | 'deadline' | 'app' — the ONE classification both
    ingresses map from (HTTP 503/504/500, gRPC UNAVAILABLE/
    DEADLINE_EXCEEDED/INTERNAL). Retryable infra classes and deadlines
    never surface as bare application errors."""
    from ray_tpu.exceptions import GetTimeoutError
    from ray_tpu.serve.handle import ServeRetryableError

    if isinstance(e, ServeRetryableError):
        return "retryable"
    if isinstance(e, (GetTimeoutError, TimeoutError, asyncio.TimeoutError)):
        return "deadline"
    return "app"


def _error_status(e: BaseException):
    """(status, retry_after) for an exception escaping a handle call."""
    return {
        "retryable": (503, "1"),
        "deadline": (504, "1"),
        "app": (500, None),
    }[_classify_error(e)]


class ProxyBase:
    """Ingress-agnostic half of a serve proxy: route resolution with a
    short cache, admission counters, and stream teardown. Both the HTTP
    and gRPC proxies inherit it — the pieces live ONCE, with real `self`
    ownership of the state they touch (each proxy renders rejections in
    its own protocol)."""

    def __init__(self):
        # Admission control + observability counters (single event loop:
        # plain ints are race-free).
        self._inflight = 0
        self._shed = 0
        self._handles: Dict[str, object] = {}
        self._routes_cache = (-10.0, {})

    def stats(self) -> dict:
        """Live admission-control counters (bench/tests)."""
        return {"inflight": self._inflight, "shed": self._shed}

    def _over_cap(self) -> bool:
        """Admission check: True when the request must be shed (counts
        the shed); the caller renders the 503 / RESOURCE_EXHAUSTED."""
        from ray_tpu._private.config import rt_config

        cap = int(rt_config.serve_max_inflight)
        if cap > 0 and self._inflight >= cap:
            self._shed += 1
            return True
        return False

    def _route_for(self, path: str) -> Optional[str]:
        import ray_tpu
        from ray_tpu._private import faultpoints
        from ray_tpu.serve.controller import CONTROLLER_NAME

        if faultpoints.ACTIVE:
            faultpoints.fire("serve.proxy.route", err=ConnectionError)

        def fetch():
            routes = ray_tpu.get(
                ray_tpu.get_actor(CONTROLLER_NAME).get_routes.remote(),
                timeout=10,
            )
            self._routes_cache = (time.monotonic(), routes)
            return routes

        def match(routes):
            best = None
            for prefix, deployment in routes.items():
                if path.startswith(prefix) and (
                    best is None or len(prefix) > len(best[0])
                ):
                    best = (prefix, deployment)
            return None if best is None else best[1]

        fetched_at, routes = self._routes_cache
        fresh = time.monotonic() - fetched_at <= _ROUTE_TTL_S
        if not fresh:
            routes = fetch()
        found = match(routes)
        if found is None and fresh:
            # Miss on a warm cache: a route registered moments ago must
            # not 404 for the cache TTL — refetch once before giving up.
            found = match(fetch())
        return found

    def _close_stream(self, it):
        """Release the handle-side stream iterator (settles the router
        slot and cancels the replica generator); safe on non-stream
        iterators and None."""
        close = getattr(it, "close", None)
        if close is not None:
            try:
                close()
            except Exception as e:
                logger.debug("stream close raised: %s", e)


class HTTPProxy(ProxyBase):
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        super().__init__()
        self._host = host
        self._port = port
        self._runner = None
        self._site = None

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self._host, self._port)
        await self._site.start()
        port = self._site._server.sockets[0].getsockname()[1]
        self._port = port
        return port

    def port(self) -> int:
        return self._port

    async def _handle(self, request):
        from aiohttp import web
        from ray_tpu._private.config import rt_config

        # Admission control: shed BEFORE any routing work. Saturation must
        # degrade to fast typed rejections, not queue collapse.
        if self._over_cap():
            return web.Response(
                status=503,
                text=f"proxy saturated: {self._inflight} requests in "
                     f"flight >= serve_max_inflight="
                     f"{int(rt_config.serve_max_inflight)}",
                headers={"Retry-After": "1"},
            )
        self._inflight += 1
        try:
            return await self._handle_admitted(request)
        finally:
            self._inflight -= 1

    async def _handle_admitted(self, request):
        from aiohttp import web

        loop = asyncio.get_running_loop()
        try:
            # The controller RPC blocks; keep it off the proxy event loop.
            deployment = await loop.run_in_executor(
                None, self._route_for, request.path
            )
        except Exception as e:
            # Route resolution is infra, not the app: a controller blip or
            # injected fault is a retryable 503, never a bare 500.
            return web.Response(
                status=503, text=f"route resolution failed: {e}",
                headers={"Retry-After": "1"},
            )
        if deployment is None:
            return web.Response(status=404, text="no route")
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(deployment)
        if handle is None:
            handle = self._handles[deployment] = DeploymentHandle(deployment)
        body = await request.read()
        payload = {
            "body": body,
            "path": request.path,
            "query": dict(request.query),
            "headers": dict(request.headers),
            "method": request.method,
        }
        # SSE streaming: a JSON body with "stream": true rides the serve
        # streaming protocol (replica-side generator) and is forwarded as
        # text/event-stream chunks (reference: Serve HTTP streaming
        # responses / OpenAI stream=true).
        wants_stream = False
        try:
            parsed = json.loads(body or b"{}")
            wants_stream = bool(
                isinstance(parsed, dict) and parsed.get("stream")
            )
        except json.JSONDecodeError:
            pass
        if wants_stream:
            return await self._handle_stream(
                request, handle.options(stream=True), payload, loop
            )
        from ray_tpu._private.config import rt_config

        timeout = float(rt_config.serve_request_timeout_s)
        try:
            # Submission may briefly block (router pick / controller
            # refresh): keep it off the loop. The WAIT is fully async —
            # parking a blocked executor thread per in-flight request
            # starves co-located replicas (all actors in a worker process
            # share one default executor) and deadlocks under bursts.
            resp = await loop.run_in_executor(
                None, lambda: handle.remote(payload)
            )
            out = await resp.result_async(timeout)
        except Exception as e:
            status, retry_after = _error_status(e)
            headers = {"Retry-After": retry_after} if retry_after else None
            return web.Response(
                status=status, text=f"{type(e).__name__}: {e}",
                headers=headers,
            )
        if isinstance(out, (bytes, bytearray)):
            return web.Response(body=bytes(out))
        if isinstance(out, str):
            return web.Response(text=out)
        return web.json_response(out)

    async def _handle_stream(self, request, handle, payload, loop):
        from aiohttp import web
        from ray_tpu._private.config import rt_config

        from ray_tpu.serve.handle import _StreamIterator

        done = object()  # stream-exhausted sentinel
        # wait_for horizon sits ABOVE the handle's own per-chunk pull
        # deadline so the typed handle-side error wins over a raw timeout.
        chunk_timeout = float(rt_config.serve_stream_chunk_timeout_s) + 30

        async def _next():
            # __anext__ applies the handle-side per-chunk deadline and
            # maps replica death to the typed retryable class; the outer
            # wait_for is the backstop if the pull itself wedges.
            try:
                return await asyncio.wait_for(it.__anext__(), chunk_timeout)
            except StopAsyncIteration:
                return done

        it = None
        try:
            # Submission off-loop (may briefly block on the router); the
            # stream registration wait and every chunk pull are async —
            # an open stream costs a coroutine, not a blocked executor
            # thread (co-located replicas share the executor).
            gen = await loop.run_in_executor(
                None, lambda: handle.remote(payload)
            )
            # Registration (time-to-first-response) is bounded by the
            # REQUEST deadline like the unary path; only chunk pulls get
            # the longer streaming horizon.
            out = await gen.result_async(
                float(rt_config.serve_request_timeout_s)
            )
            if not isinstance(out, _StreamIterator):
                # The deployment chose not to stream (e.g. stream=true
                # with options the endpoint serves non-incrementally): a
                # plain response comes back shaped like the unary path,
                # not a broken SSE body.
                if isinstance(out, (bytes, bytearray)):
                    return web.Response(body=bytes(out))
                if isinstance(out, str):
                    return web.Response(text=out)
                return web.json_response(out)
            it = out
            first = await _next()
        except Exception as e:
            self._close_stream(it)
            status, retry_after = _error_status(e)
            headers = {"Retry-After": retry_after} if retry_after else None
            return web.Response(
                status=status, text=f"{type(e).__name__}: {e}",
                headers=headers,
            )
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        chunk = first
        try:
            while chunk is not done:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                elif not isinstance(chunk, (bytes, bytearray)):
                    # generic generator deployments may yield objects:
                    # frame them as JSON lines rather than dropping them
                    chunk = (json.dumps(chunk) + "\n").encode()
                await resp.write(chunk)
                chunk = await _next()
        except (ConnectionResetError, ConnectionError) as e:
            # CLIENT went away mid-stream: cancel the replica-side
            # generator so its slot frees now, not at the idle sweep.
            logger.debug("client left stream %s: %s", request.path, e)
        except Exception as e:
            # Mid-stream upstream failure: a silent truncation is
            # indistinguishable from success — emit a terminal typed
            # error event so the client KNOWS (and knows whether to
            # retry), then end the stream.
            logger.warning("stream to %s ended on error: %s: %s",
                           request.path, type(e).__name__, e)
            from ray_tpu.serve.handle import ServeRetryableError

            frame = {
                "error": type(e).__name__,
                "message": str(e),
                "retryable": isinstance(
                    e, (ServeRetryableError, TimeoutError,
                        asyncio.TimeoutError)
                ),
            }
            try:
                await resp.write(
                    b"event: error\ndata: "
                    + json.dumps(frame).encode() + b"\n\n"
                )
            except Exception as we:
                logger.debug("terminal error frame not delivered: %s", we)
        finally:
            self._close_stream(it)
        try:
            await resp.write_eof()
        except Exception as e:
            logger.debug("eof after disconnect: %s", e)
        return resp

    async def stop(self) -> bool:
        if self._runner is not None:
            await self._runner.cleanup()
        return True
