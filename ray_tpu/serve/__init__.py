"""ray_tpu.serve: model serving with replicas, routing, and autoscaling.

Reference analog: ``python/ray/serve``::

    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, request):
            return {"answer": ...}

    handle = serve.run(Model.bind(), name="app", route_prefix="/model")
    handle.remote({"x": 1}).result()
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import cloudpickle

from ray_tpu.serve.deployment import (
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentConfig,
    deployment,
)
from ray_tpu.serve.handle import (
    BackPressureError,
    DeploymentHandle,
    DeploymentResponse,
    ReplicaDiedError,
    ServeRetryableError,
)
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.replica import GangContext, batch, get_gang_context

__all__ = [
    "BackPressureError",
    "ReplicaDiedError",
    "ServeRetryableError",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "DeploymentResponse",
    "GangContext",
    "batch",
    "get_gang_context",
    "get_multiplexed_model_id",
    "multiplexed",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "run",
    "shutdown",
    "start_http_proxy",
    "start_grpc_proxy",
    "status",
]

_proxy = None
_grpc_proxy = None


def _get_or_start_controller():
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController

    actor_cls = ray_tpu.remote(max_concurrency=64)(ServeController)
    return actor_cls.options(
        name=CONTROLLER_NAME, get_if_exists=True
    ).remote()


def _collect_specs(app: Application, specs: Dict[str, dict],
                   order: List[str]):
    """DFS over the composition graph; dependency init args become handles."""
    d = app.deployment
    if d.name in specs:
        return
    init_args = []
    for a in app.args:
        if isinstance(a, Application):
            _collect_specs(a, specs, order)
            init_args.append(DeploymentHandle(a.deployment.name))
        else:
            init_args.append(a)
    init_kwargs = {}
    for k, a in app.kwargs.items():
        if isinstance(a, Application):
            _collect_specs(a, specs, order)
            init_kwargs[k] = DeploymentHandle(a.deployment.name)
        else:
            init_kwargs[k] = a
    cfg = d.config
    asc = None
    if cfg.autoscaling_config is not None:
        a = cfg.autoscaling_config
        asc = {
            "min_replicas": a.min_replicas,
            "max_replicas": a.max_replicas,
            "target_ongoing_requests": a.target_ongoing_requests,
            "upscale_delay_s": a.upscale_delay_s,
            "downscale_delay_s": a.downscale_delay_s,
        }
    specs[d.name] = {
        "name": d.name,
        "serialized_target": cloudpickle.dumps(d.target),
        "init_args": tuple(init_args),
        "init_kwargs": init_kwargs,
        "num_replicas": cfg.num_replicas,
        "max_ongoing": cfg.max_ongoing_requests,
        "max_queued": cfg.max_queued_requests,
        "actor_options": cfg.ray_actor_options,
        "user_config": cfg.user_config,
        "autoscaling": asc,
        "version": cfg.version,
        "gang_size": cfg.gang_size,
        "gang_strategy": cfg.gang_strategy,
    }
    order.append(d.name)


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/",
        local_testing_mode: bool = False,
        _blocking_timeout: float = 60.0) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment
    (reference: ``serve.run`` ``api.py:869``). ``local_testing_mode=True``
    instantiates the graph in-process without a cluster (reference:
    ``_private/local_testing_mode.py``)."""
    if local_testing_mode:
        from ray_tpu.serve.local_testing import run_local

        return run_local(app)
    import ray_tpu

    controller = _get_or_start_controller()
    specs: Dict[str, dict] = {}
    order: List[str] = []
    _collect_specs(app, specs, order)
    ingress = app.deployment.name
    ray_tpu.get(
        controller.deploy.remote(
            name, [specs[n] for n in order], route_prefix, ingress
        ),
        timeout=_blocking_timeout,
    )
    # block until every deployment has its replicas (jittered poll: many
    # drivers deploying at once must not hammer the controller in lockstep)
    from ray_tpu._private.backoff import Backoff

    poll = Backoff(base=0.05, cap=0.5)
    deadline = time.time() + _blocking_timeout
    while time.time() < deadline:
        st = ray_tpu.get(controller.status.remote(), timeout=30)
        if all(
            st.get(n, {}).get("running", 0) >= min(specs[n]["num_replicas"], 1)
            for n in order
        ):
            break
        poll.sleep()
    return DeploymentHandle(ingress)


def get_deployment_handle(deployment_name: str, app_name: str = "default"
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    import ray_tpu

    controller = _get_or_start_controller()
    routes = ray_tpu.get(controller.get_routes.remote(), timeout=30)
    for _, dep in routes.items():
        return DeploymentHandle(dep)
    raise ValueError(f"app '{name}' has no routed ingress")


def status() -> dict:
    import ray_tpu

    return ray_tpu.get(
        _get_or_start_controller().status.remote(), timeout=30
    )


def delete(name: str):
    import ray_tpu

    ray_tpu.get(
        _get_or_start_controller().delete_app.remote(name), timeout=60
    )


def shutdown():
    global _proxy, _grpc_proxy
    import ray_tpu

    try:
        from ray_tpu.serve.controller import CONTROLLER_NAME

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:
        pass
    if _proxy is not None:
        try:
            ray_tpu.get(_proxy.stop.remote(), timeout=10)
            ray_tpu.kill(_proxy)
        except Exception:
            pass
        _proxy = None
    if _grpc_proxy is not None:
        try:
            ray_tpu.get(_grpc_proxy.stop.remote(), timeout=10)
            ray_tpu.kill(_grpc_proxy)
        except Exception:
            pass
        _grpc_proxy = None


def start_http_proxy(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP ingress actor; returns the bound port (reference:
    per-node ProxyActor; one proxy here — the head node's)."""
    global _proxy
    import ray_tpu
    from ray_tpu.serve.http_proxy import HTTPProxy

    actor_cls = ray_tpu.remote(max_concurrency=64)(HTTPProxy)
    _proxy = actor_cls.options(name="__serve_proxy", get_if_exists=True).remote(
        host, port
    )
    return ray_tpu.get(_proxy.start.remote(), timeout=30)


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the gRPC ingress actor; returns the bound port (reference:
    the dual-protocol ProxyActor — ``serve/_private/proxy.py:11``; msgpack
    payloads over generic method handlers, see ``grpc_proxy.py``)."""
    global _grpc_proxy
    import ray_tpu
    from ray_tpu.serve.grpc_proxy import GRPCProxy

    actor_cls = ray_tpu.remote(max_concurrency=64)(GRPCProxy)
    _grpc_proxy = actor_cls.options(
        name="__serve_grpc_proxy", get_if_exists=True
    ).remote(host, port)
    return ray_tpu.get(_grpc_proxy.start.remote(), timeout=30)
