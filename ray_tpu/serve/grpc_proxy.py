"""gRPC ingress proxy (reference: ``python/ray/serve/_private/proxy.py:11``
— the reference ProxyActor serves HTTP *and* gRPC; this is the gRPC half).

Runs a ``grpc.aio`` server inside an async actor, sharing the SAME routing
machinery as the HTTP proxy (controller route table + DeploymentHandle's
power-of-two-choices router). The service is registered with *generic*
method handlers — no protoc codegen — and speaks msgpack payloads:

    service rayserve.v1.RayServe {
      rpc Predict(bytes) returns (bytes);            // unary
      rpc PredictStream(bytes) returns (stream bytes);  // generator apps
    }

Request payload (msgpack map):
    {"route": "/app", "method": "__call__"?, "data": <any>,
     "multiplexed_model_id": str?}
Response payload (msgpack): the deployment's return value. Errors map to
gRPC status codes (NOT_FOUND for unknown routes, INTERNAL for user errors),
matching the reference proxy's status semantics.

The ``serve-multiplexed-model-id`` request metadata key is honored like the
reference's gRPC proxy, taking precedence over the payload field.
"""
from __future__ import annotations

import asyncio
from typing import Dict, Optional

import msgpack

SERVICE = "rayserve.v1.RayServe"


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True, default=str)


def _unpack(data: bytes):
    return msgpack.unpackb(data, raw=False)


class GRPCProxy:
    """Async actor hosting the gRPC ingress (reference: ProxyActor's gRPC
    server sharing the Router with the HTTP side)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._handles: Dict[str, object] = {}
        self._server = None

    async def start(self) -> int:
        import grpc

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method
                if name == f"/{SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._predict,
                        request_deserializer=None,
                        response_serializer=None,
                    )
                if name == f"/{SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._predict_stream,
                        request_deserializer=None,
                        response_serializer=None,
                    )
                return None

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_Handler(),))
        self._port = self._server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        await self._server.start()
        return self._port

    def port(self) -> int:
        return self._port

    # ------------------------------------------------------------- routing

    def _route_for(self, path: str) -> Optional[str]:
        # Shared with the HTTP proxy: one longest-prefix resolver against
        # the controller's route table.
        from ray_tpu.serve.http_proxy import HTTPProxy

        return HTTPProxy._route_for(self, path)

    async def _handle_for(self, req: dict, context):
        """Resolve the deployment handle + per-request options, or abort."""
        import grpc

        route = req.get("route") or "/"
        # The controller RPC blocks; it must not stall the grpc.aio loop.
        deployment = await asyncio.get_running_loop().run_in_executor(
            None, self._route_for, route
        )
        if deployment is None:
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(f"no route for {route!r}")
            return None, None
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(deployment)
        if handle is None:
            handle = self._handles[deployment] = DeploymentHandle(deployment)
        model_id = req.get("multiplexed_model_id") or ""
        for key, value in context.invocation_metadata() or ():
            if key == "serve-multiplexed-model-id" and value:
                model_id = value
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        return handle, req.get("method") or "__call__"

    async def _predict(self, request: bytes, context) -> bytes:
        import grpc

        try:
            req = _unpack(request)
        except Exception as e:
            context.set_code(grpc.StatusCode.INVALID_ARGUMENT)
            context.set_details(f"bad msgpack request: {e}")
            return b""
        handle, method = await self._handle_for(req, context)
        if handle is None:
            return b""
        loop = asyncio.get_running_loop()
        try:
            caller = (
                handle if method == "__call__" else getattr(handle, method)
            )
            resp = caller.remote(req.get("data"))
            out = await loop.run_in_executor(None, resp.result, 60)
        except Exception as e:
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(f"{type(e).__name__}: {e}")
            return b""
        return _pack(out)

    async def _predict_stream(self, request: bytes, context):
        import grpc

        try:
            req = _unpack(request)
        except Exception as e:
            context.set_code(grpc.StatusCode.INVALID_ARGUMENT)
            context.set_details(f"bad msgpack request: {e}")
            return
        handle, method = await self._handle_for(req, context)
        if handle is None:
            return
        handle = handle.options(stream=True)
        loop = asyncio.get_running_loop()
        try:
            caller = (
                handle if method == "__call__" else getattr(handle, method)
            )
            gen = caller.remote(req.get("data"))
            # __iter__ resolves the response (blocking): keep it off-loop.
            it = await loop.run_in_executor(None, iter, gen)
            done = object()  # StopIteration cannot cross an executor Future
            while True:
                chunk = await loop.run_in_executor(None, next, it, done)
                if chunk is done:
                    break
                yield _pack(chunk)
        except Exception as e:
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(f"{type(e).__name__}: {e}")

    async def stop(self) -> bool:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
        return True
