"""gRPC ingress proxy (reference: ``python/ray/serve/_private/proxy.py:11``
— the reference ProxyActor serves HTTP *and* gRPC; this is the gRPC half).

Runs a ``grpc.aio`` server inside an async actor, sharing the SAME routing
machinery as the HTTP proxy (controller route table + DeploymentHandle's
power-of-two-choices router). The service is registered with *generic*
method handlers — no protoc codegen — and speaks msgpack payloads:

    service rayserve.v1.RayServe {
      rpc Predict(bytes) returns (bytes);            // unary
      rpc PredictStream(bytes) returns (stream bytes);  // generator apps
    }

Request payload (msgpack map):
    {"route": "/app", "method": "__call__"?, "data": <any>,
     "multiplexed_model_id": str?}
Response payload (msgpack): the deployment's return value. Errors map to
gRPC status codes (NOT_FOUND for unknown routes, INTERNAL for user errors),
matching the reference proxy's status semantics.

The ``serve-multiplexed-model-id`` request metadata key is honored like the
reference's gRPC proxy, taking precedence over the payload field.
"""
from __future__ import annotations

import asyncio
from typing import Dict, Optional

import msgpack

from ray_tpu.serve.http_proxy import ProxyBase

SERVICE = "rayserve.v1.RayServe"


def _pack(obj) -> bytes:
    return msgpack.packb(obj, use_bin_type=True, default=str)


def _unpack(data: bytes):
    return msgpack.unpackb(data, raw=False)


class GRPCProxy(ProxyBase):
    """Async actor hosting the gRPC ingress (reference: ProxyActor's gRPC
    server sharing the Router with the HTTP side). Route resolution,
    admission counters, and stream teardown come from ProxyBase — shared
    with the HTTP proxy; only the protocol rendering differs."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__()
        self._host = host
        self._port = port
        self._server = None

    async def start(self) -> int:
        import grpc

        proxy = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method
                if name == f"/{SERVICE}/Predict":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._predict,
                        request_deserializer=None,
                        response_serializer=None,
                    )
                if name == f"/{SERVICE}/PredictStream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._predict_stream,
                        request_deserializer=None,
                        response_serializer=None,
                    )
                return None

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((_Handler(),))
        self._port = self._server.add_insecure_port(
            f"{self._host}:{self._port}"
        )
        await self._server.start()
        return self._port

    def port(self) -> int:
        return self._port

    # ------------------------------------------------------------- routing

    async def _handle_for(self, req: dict, context):
        """Resolve the deployment handle + per-request options, or abort."""
        import grpc

        route = req.get("route") or "/"
        try:
            # The controller RPC blocks; it must not stall the grpc.aio loop.
            deployment = await asyncio.get_running_loop().run_in_executor(
                None, self._route_for, route
            )
        except Exception as e:
            # Route resolution is infra: retryable UNAVAILABLE, not INTERNAL.
            context.set_code(grpc.StatusCode.UNAVAILABLE)
            context.set_details(f"route resolution failed: {e}")
            return None, None
        if deployment is None:
            context.set_code(grpc.StatusCode.NOT_FOUND)
            context.set_details(f"no route for {route!r}")
            return None, None
        from ray_tpu.serve.handle import DeploymentHandle

        handle = self._handles.get(deployment)
        if handle is None:
            handle = self._handles[deployment] = DeploymentHandle(deployment)
        model_id = req.get("multiplexed_model_id") or ""
        for key, value in context.invocation_metadata() or ():
            if key == "serve-multiplexed-model-id" and value:
                model_id = value
        if model_id:
            handle = handle.options(multiplexed_model_id=model_id)
        return handle, req.get("method") or "__call__"

    def _admit(self, context) -> bool:
        """Global in-flight admission check (ProxyBase._over_cap); sheds
        with RESOURCE_EXHAUSTED — the gRPC analog of 503 + Retry-After."""
        import grpc

        from ray_tpu._private.config import rt_config

        if self._over_cap():
            context.set_code(grpc.StatusCode.RESOURCE_EXHAUSTED)
            context.set_details(
                f"proxy saturated: {self._inflight} >= "
                f"serve_max_inflight={int(rt_config.serve_max_inflight)}"
            )
            return False
        return True

    @staticmethod
    def _status_for(e: BaseException):
        """Retryable infra -> UNAVAILABLE, deadline -> DEADLINE_EXCEEDED,
        application error -> INTERNAL: the gRPC rendering of the shared
        classification (one mapping to maintain, both ingresses agree)."""
        import grpc

        from ray_tpu.serve.http_proxy import _classify_error

        return {
            "retryable": grpc.StatusCode.UNAVAILABLE,
            "deadline": grpc.StatusCode.DEADLINE_EXCEEDED,
            "app": grpc.StatusCode.INTERNAL,
        }[_classify_error(e)]

    async def _predict(self, request: bytes, context) -> bytes:
        import grpc

        from ray_tpu._private.config import rt_config

        try:
            req = _unpack(request)
        except Exception as e:
            context.set_code(grpc.StatusCode.INVALID_ARGUMENT)
            context.set_details(f"bad msgpack request: {e}")
            return b""
        if not self._admit(context):
            return b""
        self._inflight += 1
        try:
            handle, method = await self._handle_for(req, context)
            if handle is None:
                return b""
            loop = asyncio.get_running_loop()
            try:
                caller = (
                    handle if method == "__call__"
                    else getattr(handle, method)
                )
                # Submission off-loop (router pick may briefly block);
                # the WAIT is fully async — a blocked executor thread per
                # in-flight request starves co-located replicas (shared
                # per-process default executor) and deadlocks under
                # bursts.
                resp = await loop.run_in_executor(
                    None, lambda: caller.remote(req.get("data"))
                )
                out = await resp.result_async(
                    float(rt_config.serve_request_timeout_s)
                )
            except Exception as e:
                context.set_code(self._status_for(e))
                context.set_details(f"{type(e).__name__}: {e}")
                return b""
            return _pack(out)
        finally:
            self._inflight -= 1

    async def _predict_stream(self, request: bytes, context):
        import grpc

        from ray_tpu._private.config import rt_config

        try:
            req = _unpack(request)
        except Exception as e:
            context.set_code(grpc.StatusCode.INVALID_ARGUMENT)
            context.set_details(f"bad msgpack request: {e}")
            return
        if not self._admit(context):
            return
        self._inflight += 1
        it = None
        try:
            handle, method = await self._handle_for(req, context)
            if handle is None:
                return
            handle = handle.options(stream=True)
            loop = asyncio.get_running_loop()
            try:
                from ray_tpu.serve.handle import _StreamIterator

                caller = (
                    handle if method == "__call__"
                    else getattr(handle, method)
                )
                # Submission off-loop; registration wait and chunk pulls
                # are async (see _predict: blocked executor threads
                # deadlock co-located replicas).
                gen = await loop.run_in_executor(
                    None, lambda: caller.remote(req.get("data"))
                )
                # Registration is bounded by the request deadline (unary
                # parity); chunk pulls get the streaming horizon.
                out = await gen.result_async(
                    float(rt_config.serve_request_timeout_s)
                )
                if isinstance(out, _StreamIterator):
                    it = out
                    async for chunk in it:
                        yield _pack(chunk)
                else:
                    # non-streaming result under stream=true: a single
                    # well-formed message, not an error
                    yield _pack(out)
            except Exception as e:
                # Typed terminal status, never a hang: UNAVAILABLE tells
                # the client a retry may succeed (replica died mid-stream).
                context.set_code(self._status_for(e))
                context.set_details(f"{type(e).__name__}: {e}")
        finally:
            # ProxyBase: settles the router slot + cancels the
            # replica-side generator
            self._close_stream(it)
            self._inflight -= 1

    async def stop(self) -> bool:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
        return True
