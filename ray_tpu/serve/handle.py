"""Deployment handles + power-of-two-choices routing.

Reference analogs: ``python/ray/serve/handle.py`` (DeploymentHandle /
DeploymentResponse), ``_private/router.py:516`` + ``request_router/
pow_2_router.py:27`` (pick 2 random replicas, route to the lower queue
length). The router tracks its *own* in-flight counts per replica (no
per-request RPC to ask replicas their length; counts refresh lazily).
"""
from __future__ import annotations

import logging
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class ServeRetryableError(Exception):
    """Base for infra-level request failures the CLIENT may safely retry
    (the proxies map these to 503 + ``Retry-After`` / gRPC UNAVAILABLE,
    never a bare 500). Application exceptions raised by user code are NOT
    retryable and keep their own types (reference: Serve's retryable
    ``BackPressureError``/503 vs 500 semantics)."""

    retryable = True


class ReplicaDiedError(ServeRetryableError):
    """The replica died (or became unreachable) while this request may
    already have reached user code: the handle must not replay it
    transparently — re-execution safety is the caller's call. Surfaced
    as HTTP 503 + ``Retry-After`` (a terminal ``error`` event on open
    streams) so well-behaved clients retry (reference: RayActorError ->
    retryable 503 mapping in Serve's proxy)."""


def _is_infra_failure(e: BaseException) -> bool:
    """Replica-death / transport class, as opposed to an application
    error raised by user code (TaskError) or a deadline (GetTimeoutError,
    which the proxies map to 504, not 503)."""
    from ray_tpu import exceptions as exc
    from ray_tpu._private import protocol

    return isinstance(
        e,
        (
            exc.ActorError,
            exc.WorkerCrashedError,
            exc.NodeDiedError,
            exc.ObjectLostError,
            protocol.RpcError,
            ConnectionError,
        ),
    )


class _StreamIterator:
    """Pulls chunks of a replica-side generator (reference: streaming
    DeploymentResponses / StreamingResponse). Iterating drives
    ``next_chunks`` pulls; the router slot settles on exhaustion."""

    def __init__(self, replica, stream_id: str, settle, router=None,
                 replica_key=None):
        self._replica = replica
        self._stream_id = stream_id
        self._settle = settle
        self._router = router
        self._key = replica_key
        self._buf: list = []
        self._done = False

    def __iter__(self):
        return self

    def _pull_failed(self, e: BaseException):
        """Terminal bookkeeping for a failed chunk pull; returns the typed
        error to raise for infra failures (mid-stream replica death must
        surface retryably — never a hang, never an anonymous transport
        exception), or None to re-raise the original."""
        self._done = True
        if _is_infra_failure(e):
            if self._router is not None and self._key is not None:
                # evict ONLY, no settle: evict pops the count, and a
                # settle enqueued lock-free could outlive it and later
                # decrement the re-added replica's fresh count (_done
                # already blocks the close()/__del__ settle path)
                self._router.evict(self._key)
            else:
                self._settle()
            return ReplicaDiedError(
                f"stream {self._stream_id} lost its replica "
                f"mid-stream: {type(e).__name__}: {e}"
            )
        self._settle()
        return None

    def _ingest(self, chunks, done: bool):
        self._buf.extend(chunks)
        if done:
            self._done = True
            self._settle()

    def __next__(self):
        import ray_tpu
        from ray_tpu._private import faultpoints
        from ray_tpu._private.config import rt_config

        while not self._buf:
            if self._done:
                raise StopIteration
            try:
                if faultpoints.ACTIVE:
                    faultpoints.fire(
                        "serve.replica.stream", err=ConnectionError
                    )
                chunks, done = ray_tpu.get(
                    self._replica.next_chunks.remote(self._stream_id),
                    timeout=float(rt_config.serve_stream_chunk_timeout_s),
                )
            except Exception as e:
                mapped = self._pull_failed(e)
                if mapped is not None:
                    raise mapped from e
                raise
            self._ingest(chunks, done)
        return self._buf.pop(0)

    def __aiter__(self):
        return self

    async def __anext__(self):
        """Event-loop chunk pull (the ingress proxies): same semantics as
        ``__next__`` without parking an executor thread per open stream —
        N streams cost N coroutines, not N blocked threads."""
        import asyncio

        from ray_tpu._private import faultpoints
        from ray_tpu._private.config import rt_config
        from ray_tpu._private.worker import get_global_worker

        while not self._buf:
            if self._done:
                raise StopAsyncIteration
            try:
                if faultpoints.ACTIVE:
                    faultpoints.fire(
                        "serve.replica.stream", err=ConnectionError
                    )
                w = get_global_worker()
                chunks, done = await asyncio.wait_for(
                    w.as_asyncio_future(
                        self._replica.next_chunks.remote(self._stream_id)
                    ),
                    float(rt_config.serve_stream_chunk_timeout_s),
                )
            except Exception as e:
                mapped = self._pull_failed(e)
                if mapped is not None:
                    raise mapped from e
                raise
            self._ingest(chunks, done)
        return self._buf.pop(0)

    def close(self):
        """Settle the router slot for a stream abandoned mid-iteration
        AND release the replica-side generator + its slot (a client
        disconnect must not leak capacity until the idle sweep);
        idempotent, best-effort on the replica RPC."""
        if self._done:
            return
        self._done = True
        self._settle()
        try:
            # deliberate fire-and-forget: close() runs on disconnect/GC
            # paths where blocking on the ack would stall teardown
            _ = self._replica.cancel_stream.remote(self._stream_id)
        except Exception as e:
            logger.debug("stream %s cancel not delivered: %s",
                         self._stream_id, e)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeploymentResponse:
    """Future-like result of ``handle.remote()`` (reference:
    ``serve/handle.py DeploymentResponse``)."""

    def __init__(self, ref, router, replica_key, replica=None):
        self._ref = ref
        self._router = router
        self._key = replica_key
        self._replica = replica
        self._done = False

    def _failed(self, e: BaseException):
        """Settle + map an infra failure; returns the typed error to
        raise, or None to re-raise the original. The replica died while
        the request was (possibly) executing: evict it so the router
        reroutes its queue immediately, and surface the typed retryable
        class — transparent replay is NOT safe once user code may have
        run (reference: Serve only retries pre-execution failures;
        mid-execution death -> retryable 503)."""
        if _is_infra_failure(e):
            # evict ONLY (it pops the count): a settle enqueued lock-free
            # could outlive the eviction and later decrement the fresh
            # count of the same replica re-added by a refresh. _done
            # blocks the __del__ settle from re-introducing that.
            self._done = True
            self._router.evict(self._key)
            return ReplicaDiedError(
                f"replica died mid-request: {type(e).__name__}: {e}"
            )
        self._settle()
        return None

    def _finish(self, out):
        if (
            isinstance(out, dict)
            and "__rt_stream__" in out
            and self._replica is not None
        ):
            # generator deployment: hand back an iterator; the router slot
            # stays held until the stream drains
            self._done = True  # settling is the iterator's job now
            router, key = self._router, self._key
            return _StreamIterator(
                self._replica, out["__rt_stream__"],
                lambda: router.request_finished(key),
                router=router, replica_key=key,
            )
        self._settle()
        return out

    def result(self, timeout: Optional[float] = None):
        import ray_tpu

        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        except Exception as e:
            mapped = self._failed(e)
            if mapped is not None:
                raise mapped from e
            raise
        return self._finish(out)

    async def result_async(self, timeout: Optional[float] = None):
        """Awaitable ``result()`` for event-loop callers (the ingress
        proxies): identical settle/evict/typed-error semantics, but an
        in-flight request costs a coroutine, not a blocked executor
        thread — the proxy's concurrency is bounded by admission
        control, not by a thread pool."""
        import asyncio

        from ray_tpu import exceptions as exc
        from ray_tpu._private.worker import get_global_worker

        w = get_global_worker()
        try:
            out = await asyncio.wait_for(
                w.as_asyncio_future(self._ref),
                timeout if timeout and timeout > 0 else None,
            )
        except asyncio.TimeoutError:
            self._settle()
            raise exc.GetTimeoutError(
                f"request did not complete within {timeout}s"
            ) from None
        except Exception as e:
            mapped = self._failed(e)
            if mapped is not None:
                raise mapped from e
            raise
        return self._finish(out)

    def __iter__(self):
        out = self.result()
        if isinstance(out, _StreamIterator):
            return out
        return iter([out])

    def _settle(self):
        if not self._done:
            self._done = True
            self._router.request_finished(self._key)

    def __del__(self):
        # A response abandoned without result() must not strand its
        # router in-flight slot forever (fire-and-forget handle calls,
        # proxy aborts): settle best-effort — request_finished is safe
        # from __del__ (lock-free enqueue).
        try:
            self._settle()
        except Exception:
            pass

    @property
    def ref(self):
        """Underlying ObjectRef (compose into other task submissions)."""
        return self._ref


class BackPressureError(ServeRetryableError):
    """The handle's queue beyond replica capacity exceeds
    max_queued_requests: the caller should shed load (the HTTP proxy maps
    this to 503) rather than queue without bound (reference: Serve's
    BackPressureError)."""


class _PushRegistry:
    """Per-process fanout of serve replica-change pushes to live routers:
    ONE pubsub handler and ONE subscribe call per channel, routers held
    weakly (they churn with handle pickling)."""

    def __init__(self):
        import weakref

        self._lock = threading.Lock()
        self._channels: Dict[str, Any] = {}  # channel -> WeakSet of routers
        self._weakset = weakref.WeakSet

    def add(self, router: "_Router"):
        from ray_tpu._private.worker import get_global_worker

        channel = f"serve_replicas:{router._deployment}"
        with self._lock:
            routers = self._channels.get(channel)
            first = routers is None
            if first:
                routers = self._channels[channel] = self._weakset()
            routers.add(router)
        if not first:
            return
        w = get_global_worker()

        def _invalidate(_data, _frames, _ch=channel):
            with self._lock:
                live = list(self._channels.get(_ch, ()))
            for r in live:
                r._invalidation_gen += 1
                r._fetched_at = -10.0  # next pick() re-fetches
            return None

        w.pubsub_handlers.setdefault(channel, []).append(_invalidate)
        w.run_sync(w.gcs.call("subscribe", {"channel": channel}))


_push_registry = _PushRegistry()


def _rkey(handle) -> str:
    """Stable routing key for a replica handle. Handles are NEW objects on
    every controller fetch (actor handles re-materialize over the wire),
    so ``id(handle)`` changes per refresh — keying in-flight counts on it
    either strands counts forever or silently zeroes them each refresh,
    permanently skewing power-of-2 routing. The actor id is the replica's
    identity."""
    return handle._actor_id


class _Router:
    def __init__(self, deployment: str, refresh_s: float = 5.0):
        self._deployment = deployment
        # Globally unique: routers are recreated on every handle unpickle and
        # live in many processes; id(self) would collide across them.
        self._router_id = uuid.uuid4().hex
        self._refresh_s = refresh_s
        self._replicas: List[Any] = []
        self._inflight: Dict[str, int] = {}
        self._settled: List[str] = []  # finished keys awaiting lock-drain
        self._fetched_at = -10.0
        self._lock = threading.Lock()
        # Multiplexing: model_id -> {replica key}; only populated once a
        # model-routed request has been seen (non-multiplexed deployments
        # pay nothing).
        self._multiplex = False
        self._model_map: Dict[str, set] = {}
        # Load-shed cap from the deployment config (-1 = unbounded) and
        # per-replica execution capacity (queued = inflight - capacity).
        self._max_queued = -1
        self._max_ongoing = 16
        # Bumped by push invalidations; a refresh only stamps itself fresh
        # when no invalidation arrived while its RPC was in flight.
        self._invalidation_gen = 0
        # Push invalidation (long-poll fan-out analog): once subscribed,
        # a controller replica-change message forces the next pick() to
        # re-fetch, so the poll interval can stay long.
        self._subscribed = False
        # Autoscaling signal: refs of requests this handle has issued that
        # haven't completed yet (queued + executing), pushed to the
        # controller (reference: handle-side metrics in _private/router.py →
        # autoscaling_state.py; replica-side polls undercount because queued
        # requests sit invisible in the actor mailbox).
        self._refs: Dict[int, Any] = {}
        self._metrics_thread = None
        # Set when the controller acks "does not autoscale"; soft latch — a
        # redeploy can enable autoscaling later, so retry after a while.
        self._metrics_disabled_at: Optional[float] = None
        self._controller_handle = None

    METRICS_RETRY_S = 60.0

    def _ensure_metrics_thread(self):
        with self._lock:
            if (self._metrics_disabled_at is not None
                    and time.monotonic() - self._metrics_disabled_at
                    < self.METRICS_RETRY_S):
                return
            self._metrics_disabled_at = None
            if (self._metrics_thread is not None
                    and self._metrics_thread.is_alive()):
                return
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, daemon=True,
                name=f"serve-handle-metrics-{self._deployment}",
            )
            self._metrics_thread.start()

    def _metrics_loop(self):
        import ray_tpu
        from ray_tpu._private.backoff import Backoff

        failures = 0
        last_pushed = -1
        pushes = 0
        # Jittered cadence: many handles pushing on the same fixed tick
        # would synchronize their controller RPCs; idle handles decay
        # toward the cap, active ones reset to the fast tick.
        cadence = Backoff(base=0.25, cap=1.0, jitter=0.3)
        try:
            while failures < 8:
                cadence.sleep()
                try:
                    with self._lock:
                        refs = list(self._refs.items())
                    if refs:
                        cadence.reset()  # live traffic: keep the fast tick
                        ready, _ = ray_tpu.wait(
                            [r for _, r in refs],
                            num_returns=len(refs), timeout=0,
                        )
                        done = {id(r) for r in ready}
                        with self._lock:
                            for k, r in refs:
                                if id(r) in done:
                                    self._refs.pop(k, None)
                    with self._lock:
                        n = len(self._refs)
                    if n != last_pushed or n > 0:
                        ref = self._controller().record_handle_metrics.remote(
                            self._deployment, self._router_id, n
                        )
                        # Periodically read the ack: -1 means the deployment
                        # doesn't autoscale, so this thread is pure overhead
                        # — stop pushing for good (the latch also stops
                        # track_request from respawning us). 0 is transient
                        # (mid-redeploy / controller restart): keep pushing.
                        if pushes % 20 == 0:
                            if ray_tpu.get(ref, timeout=5) == -1:
                                with self._lock:
                                    self._metrics_disabled_at = time.monotonic()
                                return
                        pushes += 1
                        last_pushed = n
                    failures = 0
                except Exception:
                    self._controller_handle = None  # re-resolve next time
                    failures += 1
        finally:
            # A dead thread must not pin result objects; the next
            # track_request restarts tracking.
            with self._lock:
                self._refs.clear()

    def track_request(self, ref):
        with self._lock:
            self._refs[id(ref)] = ref
        self._ensure_metrics_thread()

    def _controller(self):
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        if self._controller_handle is None:
            self._controller_handle = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller_handle

    def _subscribe_push(self):
        """Register for controller replica-change pushes on the head
        pubsub (long-poll fan-out analog). Best-effort: without it the
        periodic poll still converges. One handler + one subscribe per
        (process, channel) — routers are re-created on every handle
        unpickle, so per-router subscriptions would leak handlers and
        duplicate head-side fanout; the registry holds routers weakly."""
        if self._subscribed:
            return
        self._subscribed = True
        try:
            _push_registry.add(self)
        except Exception:
            pass

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._fetched_at < self._refresh_s:
            return
        import ray_tpu

        self._subscribe_push()
        try:
            gen = self._invalidation_gen
            rinfo = ray_tpu.get(
                self._controller().get_router_info.remote(self._deployment),
                timeout=30,
            )
            handles = rinfo["handles"]
            self._max_queued = rinfo.get("max_queued", -1)
            self._max_ongoing = rinfo.get("max_ongoing", 16)
        except Exception:
            self._controller_handle = None  # stale after controller restart
            raise
        model_map: Dict[str, set] = {}
        if self._multiplex and handles:
            try:
                ids_per_replica = ray_tpu.get(
                    [h.multiplexed_ids.remote() for h in handles], timeout=10
                )
                for h, ids in zip(handles, ids_per_replica):
                    for m in ids:
                        model_map.setdefault(m, set()).add(_rkey(h))
            except Exception:
                model_map = {}  # affinity is an optimization, not required
        with self._lock:
            self._replicas = handles
            # Keys are stable actor ids, so counts SURVIVE a refresh for
            # replicas still in the set, and counts for replicas that left
            # (died, drained, scaled down) are cleared here — a replica
            # dying mid-request must not strand its in-flight count and
            # skew power-of-2 routing forever.
            live = {_rkey(h) for h in handles}
            self._inflight = {
                k: v for k, v in self._inflight.items() if k in live
            }
            for h in handles:
                self._inflight.setdefault(_rkey(h), 0)
            self._model_map = model_map
            # A push that landed while the fetch was in flight must win:
            # keep the invalidated timestamp so the next pick re-fetches.
            if self._invalidation_gen == gen:
                self._fetched_at = now

    def pick(self, model_id: Optional[str] = None):
        """Power-of-two-choices on locally tracked in-flight counts; with a
        model_id, replicas already holding that model are preferred
        (reference: model-multiplex-aware routing)."""
        from ray_tpu._private.backoff import Backoff

        if model_id and not self._multiplex:
            self._multiplex = True
            self._fetched_at = -10.0  # force a refresh with model info
        # Jittered re-resolve: a controller restart (get_actor fails, the
        # cached handle went stale) or an empty replica set mid-redeploy
        # must not hot-loop or thundering-herd the head — back off,
        # re-resolving the controller each round. Two horizons: refresh
        # FAILURES give up after 10s (this path runs on proxy executor
        # threads — parking them 30s per call under a controller outage
        # starves the executor co-located replicas share), while an empty
        # replica set gets the full 30s a rolling redeploy may need. Both
        # surface the typed retryable class: mid-redeploy emptiness and a
        # restarting controller are exactly the 503-then-retry cases.
        fail_deadline = time.monotonic() + 10
        empty_deadline = time.monotonic() + 30
        poll = Backoff(base=0.05, cap=1.0)
        force = False
        while True:
            try:
                self._refresh(force=force)
            except Exception as e:
                if time.monotonic() > fail_deadline:
                    raise ServeRetryableError(
                        f"deployment '{self._deployment}': controller "
                        f"unreachable: {type(e).__name__}: {e}"
                    ) from e
                poll.sleep()
                force = True
                continue
            if self._replicas:
                with self._lock:
                    # re-checked UNDER the lock: a concurrent evict() can
                    # empty the set between the check above and here, and
                    # sampling an empty pool would surface an untyped
                    # ValueError instead of retrying / a typed 503
                    self._drain_settled_locked()  # deferred __del__ counts
                    if self._max_queued >= 0 and self._replicas:
                        # Reference semantics: the cap counts requests
                        # QUEUED beyond what the replicas can execute
                        # concurrently, not total in-flight — shedding
                        # must not trigger while free execution slots
                        # remain.
                        total = sum(self._inflight.values())
                        capacity = (
                            len(self._replicas) * max(self._max_ongoing, 1)
                        )
                        if total - capacity >= self._max_queued:
                            raise BackPressureError(
                                f"deployment '{self._deployment}': "
                                f"{total - capacity} queued beyond replica "
                                f"capacity {capacity} >= "
                                f"max_queued_requests={self._max_queued}"
                            )
                    pool = self._replicas
                    if model_id:
                        holders = self._model_map.get(model_id, ())
                        preferred = [r for r in pool if _rkey(r) in holders]
                        if preferred:
                            pool = preferred
                    if pool:
                        if len(pool) == 1:
                            chosen = pool[0]
                        else:
                            a, b = random.sample(pool, 2)
                            chosen = (
                                a if self._inflight.get(_rkey(a), 0)
                                <= self._inflight.get(_rkey(b), 0) else b
                            )
                        key = _rkey(chosen)
                        self._inflight[key] = (
                            self._inflight.get(key, 0) + 1
                        )
                        return chosen, key
            if time.monotonic() > empty_deadline:
                raise ServeRetryableError(
                    f"no replicas for deployment '{self._deployment}'"
                )
            poll.sleep()
            force = True

    def request_finished(self, key: str):
        """Decrement a replica's in-flight count. Lock-free enqueue + best-
        effort drain: this is reachable from __del__ (abandoned stream
        iterators), where blocking on the router lock could self-deadlock a
        thread that already holds it mid-GC."""
        self._settled.append(key)  # list.append is atomic under the GIL
        if self._lock.acquire(blocking=False):
            try:
                self._drain_settled_locked()
            finally:
                self._lock.release()

    def _drain_settled_locked(self):
        while True:
            try:
                key = self._settled.pop()
            except IndexError:
                return
            if self._inflight.get(key, 0) > 0:
                self._inflight[key] -= 1

    def evict(self, key: str):
        """Drop a replica that failed a request and clear its counters —
        the dead replica's queue reroutes immediately (its queued requests
        fail over / surface typed errors on their own paths; the counts
        must not survive to skew future picks). Next pick refreshes."""
        with self._lock:
            self._replicas = [r for r in self._replicas if _rkey(r) != key]
            self._inflight.pop(key, None)
        self._fetched_at = -10.0

    def inflight_snapshot(self) -> Dict[str, int]:
        """Per-replica in-flight counts after draining pending settles
        (tests assert zero stranded counts once traffic quiesces)."""
        with self._lock:
            self._drain_settled_locked()
            return dict(self._inflight)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment: str, _router: Optional[_Router] = None,
                 _multiplexed_model_id: str = "", _stream: bool = False):
        self._deployment = deployment
        self._router = _router or _Router(deployment)
        self._multiplexed_model_id = _multiplexed_model_id
        self._stream = _stream

    @property
    def deployment_name(self) -> str:
        return self._deployment

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """Per-call options (reference: ``handle.options(...)``):
        ``multiplexed_model_id`` routes to replicas holding that model and
        is readable in the request via ``serve.get_multiplexed_model_id()``;
        ``stream=True`` returns an iterator over a generator deployment's
        chunks. The returned handle shares this handle's router state."""
        return DeploymentHandle(
            self._deployment,
            _router=self._router,
            _multiplexed_model_id=(
                self._multiplexed_model_id
                if multiplexed_model_id is None else multiplexed_model_id
            ),
            _stream=self._stream if stream is None else stream,
        )

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        from ray_tpu._private import faultpoints
        from ray_tpu._private.backoff import Backoff
        from ray_tpu._private.config import rt_config

        model_id = self._multiplexed_model_id
        # Transparent failover is safe ONLY here: a submission that fails
        # in this frame never reached user code, so replaying it on
        # another replica cannot double-execute anything. Bounded and
        # jittered; once the budget is gone the failure surfaces as the
        # typed retryable class (reference: Serve router retrying
        # pre-execution ActorUnavailable).
        attempts = max(int(rt_config.serve_failover_attempts), 0)
        retry = Backoff(base=0.05, cap=0.5)
        attempt = 0
        while True:
            replica, key = self._router.pick(model_id or None)
            try:
                if faultpoints.ACTIVE:
                    faultpoints.fire(
                        "serve.replica.call", err=ConnectionError
                    )
                if model_id or self._stream:
                    ref = replica.handle_request.remote(
                        method, args, kwargs,
                        model_id=model_id or None, stream=self._stream,
                    )
                else:
                    ref = replica.handle_request.remote(method, args, kwargs)
            except Exception as e:
                # evict alone pops the in-flight count; an extra settle
                # here could outlive the eviction in the lock-free queue
                # and later decrement a re-added replica's fresh count
                self._router.evict(key)
                if not _is_infra_failure(e):
                    raise
                if attempt >= attempts:
                    raise ReplicaDiedError(
                        f"deployment '{self._deployment}': submission "
                        f"failed on {attempt + 1} replica(s): "
                        f"{type(e).__name__}: {e}"
                    ) from e
                attempt += 1
                retry.sleep()
                continue
            self._router.track_request(ref)
            return DeploymentResponse(ref, self._router, key, replica=replica)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, item) -> _MethodCaller:
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._deployment, None, self._multiplexed_model_id,
             self._stream),
        )
