"""Deployment handles + power-of-two-choices routing.

Reference analogs: ``python/ray/serve/handle.py`` (DeploymentHandle /
DeploymentResponse), ``_private/router.py:516`` + ``request_router/
pow_2_router.py:27`` (pick 2 random replicas, route to the lower queue
length). The router tracks its *own* in-flight counts per replica (no
per-request RPC to ask replicas their length; counts refresh lazily).
"""
from __future__ import annotations

import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


class _StreamIterator:
    """Pulls chunks of a replica-side generator (reference: streaming
    DeploymentResponses / StreamingResponse). Iterating drives
    ``next_chunks`` pulls; the router slot settles on exhaustion."""

    def __init__(self, replica, stream_id: str, settle):
        self._replica = replica
        self._stream_id = stream_id
        self._settle = settle
        self._buf: list = []
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu

        while not self._buf:
            if self._done:
                raise StopIteration
            try:
                chunks, done = ray_tpu.get(
                    self._replica.next_chunks.remote(self._stream_id),
                    timeout=600,
                )
            except Exception:
                self._done = True
                self._settle()
                raise
            self._buf.extend(chunks)
            if done:
                self._done = True
                self._settle()
        return self._buf.pop(0)

    def close(self):
        """Settle the router slot for a stream abandoned mid-iteration
        (the replica-side generator is swept separately); idempotent."""
        if not self._done:
            self._done = True
            self._settle()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class DeploymentResponse:
    """Future-like result of ``handle.remote()`` (reference:
    ``serve/handle.py DeploymentResponse``)."""

    def __init__(self, ref, router, replica_key, replica=None):
        self._ref = ref
        self._router = router
        self._key = replica_key
        self._replica = replica
        self._done = False

    def result(self, timeout: Optional[float] = None):
        import ray_tpu

        try:
            out = ray_tpu.get(self._ref, timeout=timeout)
        except Exception:
            self._settle()
            raise
        if (
            isinstance(out, dict)
            and "__rt_stream__" in out
            and self._replica is not None
        ):
            # generator deployment: hand back an iterator; the router slot
            # stays held until the stream drains
            return _StreamIterator(
                self._replica, out["__rt_stream__"], self._settle
            )
        self._settle()
        return out

    def __iter__(self):
        out = self.result()
        if isinstance(out, _StreamIterator):
            return out
        return iter([out])

    def _settle(self):
        if not self._done:
            self._done = True
            self._router.request_finished(self._key)

    @property
    def ref(self):
        """Underlying ObjectRef (compose into other task submissions)."""
        return self._ref


class BackPressureError(Exception):
    """The handle's queue beyond replica capacity exceeds
    max_queued_requests: the caller should shed load (the HTTP proxy maps
    this to 503) rather than queue without bound (reference: Serve's
    BackPressureError)."""


class _PushRegistry:
    """Per-process fanout of serve replica-change pushes to live routers:
    ONE pubsub handler and ONE subscribe call per channel, routers held
    weakly (they churn with handle pickling)."""

    def __init__(self):
        import weakref

        self._lock = threading.Lock()
        self._channels: Dict[str, Any] = {}  # channel -> WeakSet of routers
        self._weakset = weakref.WeakSet

    def add(self, router: "_Router"):
        from ray_tpu._private.worker import get_global_worker

        channel = f"serve_replicas:{router._deployment}"
        with self._lock:
            routers = self._channels.get(channel)
            first = routers is None
            if first:
                routers = self._channels[channel] = self._weakset()
            routers.add(router)
        if not first:
            return
        w = get_global_worker()

        def _invalidate(_data, _frames, _ch=channel):
            with self._lock:
                live = list(self._channels.get(_ch, ()))
            for r in live:
                r._invalidation_gen += 1
                r._fetched_at = -10.0  # next pick() re-fetches
            return None

        w.pubsub_handlers.setdefault(channel, []).append(_invalidate)
        w.run_sync(w.gcs.call("subscribe", {"channel": channel}))


_push_registry = _PushRegistry()


class _Router:
    def __init__(self, deployment: str, refresh_s: float = 5.0):
        self._deployment = deployment
        # Globally unique: routers are recreated on every handle unpickle and
        # live in many processes; id(self) would collide across them.
        self._router_id = uuid.uuid4().hex
        self._refresh_s = refresh_s
        self._replicas: List[Any] = []
        self._inflight: Dict[int, int] = {}
        self._settled: List[int] = []  # finished keys awaiting lock-drain
        self._fetched_at = -10.0
        self._lock = threading.Lock()
        # Multiplexing: model_id -> {replica key}; only populated once a
        # model-routed request has been seen (non-multiplexed deployments
        # pay nothing).
        self._multiplex = False
        self._model_map: Dict[str, set] = {}
        # Load-shed cap from the deployment config (-1 = unbounded) and
        # per-replica execution capacity (queued = inflight - capacity).
        self._max_queued = -1
        self._max_ongoing = 16
        # Bumped by push invalidations; a refresh only stamps itself fresh
        # when no invalidation arrived while its RPC was in flight.
        self._invalidation_gen = 0
        # Push invalidation (long-poll fan-out analog): once subscribed,
        # a controller replica-change message forces the next pick() to
        # re-fetch, so the poll interval can stay long.
        self._subscribed = False
        # Autoscaling signal: refs of requests this handle has issued that
        # haven't completed yet (queued + executing), pushed to the
        # controller (reference: handle-side metrics in _private/router.py →
        # autoscaling_state.py; replica-side polls undercount because queued
        # requests sit invisible in the actor mailbox).
        self._refs: Dict[int, Any] = {}
        self._metrics_thread = None
        # Set when the controller acks "does not autoscale"; soft latch — a
        # redeploy can enable autoscaling later, so retry after a while.
        self._metrics_disabled_at: Optional[float] = None
        self._controller_handle = None

    METRICS_RETRY_S = 60.0

    def _ensure_metrics_thread(self):
        with self._lock:
            if (self._metrics_disabled_at is not None
                    and time.monotonic() - self._metrics_disabled_at
                    < self.METRICS_RETRY_S):
                return
            self._metrics_disabled_at = None
            if (self._metrics_thread is not None
                    and self._metrics_thread.is_alive()):
                return
            self._metrics_thread = threading.Thread(
                target=self._metrics_loop, daemon=True,
                name=f"serve-handle-metrics-{self._deployment}",
            )
            self._metrics_thread.start()

    def _metrics_loop(self):
        import ray_tpu

        failures = 0
        last_pushed = -1
        pushes = 0
        try:
            while failures < 8:
                time.sleep(0.25)
                try:
                    with self._lock:
                        refs = list(self._refs.items())
                    if refs:
                        ready, _ = ray_tpu.wait(
                            [r for _, r in refs],
                            num_returns=len(refs), timeout=0,
                        )
                        done = {id(r) for r in ready}
                        with self._lock:
                            for k, r in refs:
                                if id(r) in done:
                                    self._refs.pop(k, None)
                    with self._lock:
                        n = len(self._refs)
                    if n != last_pushed or n > 0:
                        ref = self._controller().record_handle_metrics.remote(
                            self._deployment, self._router_id, n
                        )
                        # Periodically read the ack: -1 means the deployment
                        # doesn't autoscale, so this thread is pure overhead
                        # — stop pushing for good (the latch also stops
                        # track_request from respawning us). 0 is transient
                        # (mid-redeploy / controller restart): keep pushing.
                        if pushes % 20 == 0:
                            if ray_tpu.get(ref, timeout=5) == -1:
                                with self._lock:
                                    self._metrics_disabled_at = time.monotonic()
                                return
                        pushes += 1
                        last_pushed = n
                    failures = 0
                except Exception:
                    self._controller_handle = None  # re-resolve next time
                    failures += 1
        finally:
            # A dead thread must not pin result objects; the next
            # track_request restarts tracking.
            with self._lock:
                self._refs.clear()

    def track_request(self, ref):
        with self._lock:
            self._refs[id(ref)] = ref
        self._ensure_metrics_thread()

    def _controller(self):
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        if self._controller_handle is None:
            self._controller_handle = ray_tpu.get_actor(CONTROLLER_NAME)
        return self._controller_handle

    def _subscribe_push(self):
        """Register for controller replica-change pushes on the head
        pubsub (long-poll fan-out analog). Best-effort: without it the
        periodic poll still converges. One handler + one subscribe per
        (process, channel) — routers are re-created on every handle
        unpickle, so per-router subscriptions would leak handlers and
        duplicate head-side fanout; the registry holds routers weakly."""
        if self._subscribed:
            return
        self._subscribed = True
        try:
            _push_registry.add(self)
        except Exception:
            pass

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._fetched_at < self._refresh_s:
            return
        import ray_tpu

        self._subscribe_push()
        try:
            gen = self._invalidation_gen
            rinfo = ray_tpu.get(
                self._controller().get_router_info.remote(self._deployment),
                timeout=30,
            )
            handles = rinfo["handles"]
            self._max_queued = rinfo.get("max_queued", -1)
            self._max_ongoing = rinfo.get("max_ongoing", 16)
        except Exception:
            self._controller_handle = None  # stale after controller restart
            raise
        model_map: Dict[str, set] = {}
        if self._multiplex and handles:
            try:
                ids_per_replica = ray_tpu.get(
                    [h.multiplexed_ids.remote() for h in handles], timeout=10
                )
                for h, ids in zip(handles, ids_per_replica):
                    for m in ids:
                        model_map.setdefault(m, set()).add(id(h))
            except Exception:
                model_map = {}  # affinity is an optimization, not required
        with self._lock:
            self._replicas = handles
            live = {id(h) for h in handles}
            self._inflight = {
                k: v for k, v in self._inflight.items() if k in live
            }
            for h in handles:
                self._inflight.setdefault(id(h), 0)
            self._model_map = model_map
            # A push that landed while the fetch was in flight must win:
            # keep the invalidated timestamp so the next pick re-fetches.
            if self._invalidation_gen == gen:
                self._fetched_at = now

    def pick(self, model_id: Optional[str] = None):
        """Power-of-two-choices on locally tracked in-flight counts; with a
        model_id, replicas already holding that model are preferred
        (reference: model-multiplex-aware routing)."""
        if model_id and not self._multiplex:
            self._multiplex = True
            self._fetched_at = -10.0  # force a refresh with model info
        self._refresh()
        deadline = time.monotonic() + 30
        while not self._replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no replicas for deployment '{self._deployment}'"
                )
            time.sleep(0.05)
            self._refresh(force=True)
        with self._lock:
            self._drain_settled_locked()  # counts deferred from __del__ paths
            if self._max_queued >= 0:
                # Reference semantics: the cap counts requests QUEUED
                # beyond what the replicas can execute concurrently, not
                # total in-flight — shedding must not trigger while free
                # execution slots remain.
                total = sum(self._inflight.values())
                capacity = len(self._replicas) * max(self._max_ongoing, 1)
                if total - capacity >= self._max_queued:
                    raise BackPressureError(
                        f"deployment '{self._deployment}': "
                        f"{total - capacity} queued beyond replica "
                        f"capacity {capacity} >= max_queued_requests="
                        f"{self._max_queued}"
                    )
            pool = self._replicas
            if model_id:
                holders = self._model_map.get(model_id, ())
                preferred = [r for r in pool if id(r) in holders]
                if preferred:
                    pool = preferred
            if len(pool) == 1:
                chosen = pool[0]
            else:
                a, b = random.sample(pool, 2)
                chosen = (
                    a if self._inflight.get(id(a), 0)
                    <= self._inflight.get(id(b), 0) else b
                )
            self._inflight[id(chosen)] = self._inflight.get(id(chosen), 0) + 1
            return chosen, id(chosen)

    def request_finished(self, key: int):
        """Decrement a replica's in-flight count. Lock-free enqueue + best-
        effort drain: this is reachable from __del__ (abandoned stream
        iterators), where blocking on the router lock could self-deadlock a
        thread that already holds it mid-GC."""
        self._settled.append(key)  # list.append is atomic under the GIL
        if self._lock.acquire(blocking=False):
            try:
                self._drain_settled_locked()
            finally:
                self._lock.release()

    def _drain_settled_locked(self):
        while True:
            try:
                key = self._settled.pop()
            except IndexError:
                return
            if self._inflight.get(key, 0) > 0:
                self._inflight[key] -= 1

    def evict(self, key: int):
        """Drop a replica that failed a request; next pick refreshes."""
        with self._lock:
            self._replicas = [r for r in self._replicas if id(r) != key]
            self._inflight.pop(key, None)
        self._fetched_at = -10.0


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment: str, _router: Optional[_Router] = None,
                 _multiplexed_model_id: str = "", _stream: bool = False):
        self._deployment = deployment
        self._router = _router or _Router(deployment)
        self._multiplexed_model_id = _multiplexed_model_id
        self._stream = _stream

    @property
    def deployment_name(self) -> str:
        return self._deployment

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """Per-call options (reference: ``handle.options(...)``):
        ``multiplexed_model_id`` routes to replicas holding that model and
        is readable in the request via ``serve.get_multiplexed_model_id()``;
        ``stream=True`` returns an iterator over a generator deployment's
        chunks. The returned handle shares this handle's router state."""
        return DeploymentHandle(
            self._deployment,
            _router=self._router,
            _multiplexed_model_id=(
                self._multiplexed_model_id
                if multiplexed_model_id is None else multiplexed_model_id
            ),
            _stream=self._stream if stream is None else stream,
        )

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        model_id = self._multiplexed_model_id
        replica, key = self._router.pick(model_id or None)
        try:
            if model_id or self._stream:
                ref = replica.handle_request.remote(
                    method, args, kwargs,
                    model_id=model_id or None, stream=self._stream,
                )
            else:
                ref = replica.handle_request.remote(method, args, kwargs)
        except Exception:
            self._router.evict(key)
            raise
        self._router.track_request(ref)
        return DeploymentResponse(ref, self._router, key, replica=replica)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, item) -> _MethodCaller:
        if item.startswith("_"):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (self._deployment, None, self._multiplexed_model_id,
             self._stream),
        )
