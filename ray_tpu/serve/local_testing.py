"""Local testing mode: run a serve app in-process, no cluster.

Reference analog: ``python/ray/serve/_private/local_testing_mode.py`` —
``serve.run(app, local_testing_mode=True)`` instantiates the deployment
graph directly in the driver process so unit tests exercise user callables
(including composition via handles) without actors, controllers, or HTTP.
"""
from __future__ import annotations

import inspect
import threading
from typing import Any, Dict


def _run_coro_in_thread(coro):
    """Run a coroutine to completion on its own loop in a fresh thread:
    nested handle calls (async deployment -> async deployment via
    .result()) each get an independent loop, mirroring how distinct
    replicas run on distinct loops in the cluster path."""
    box = {}

    def runner():
        import asyncio

        try:
            box["value"] = asyncio.run(coro)
        except BaseException as e:  # surfaced by the caller
            box["error"] = e

    t = threading.Thread(target=runner, name="rt-serve-local")
    t.start()
    t.join()
    if "error" in box:
        raise box["error"]
    return box["value"]


class LocalResponse:
    """Synchronously-computed stand-in for DeploymentResponse. Exceptions
    surface from .result(), matching the future contract — not at submit."""

    def __init__(self, value: Any = None, error: Exception = None):
        self._value = value
        self._error = error

    def result(self, timeout=None):
        if self._error is not None:
            raise self._error
        return self._value

    @property
    def ref(self):
        raise NotImplementedError(
            "DeploymentResponse.ref needs a cluster object store; "
            "local_testing_mode has none — run against a cluster for "
            "response composition"
        )


class LocalDeploymentHandle:
    """Handle API over an in-process instance."""

    def __init__(self, deployment_name: str, instance: Any,
                 is_function: bool):
        self.deployment_name = deployment_name
        self._instance = instance
        self._is_function = is_function

    def _call(self, method: str, args, kwargs) -> LocalResponse:
        try:
            if self._is_function:
                fn = self._instance
            else:
                fn = getattr(self._instance, method)
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = _run_coro_in_thread(out)
            return LocalResponse(out)
        except Exception as e:
            return LocalResponse(error=e)

    def remote(self, *args, **kwargs) -> LocalResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        # same caller shape as the cluster handle (reused, not duplicated)
        from ray_tpu.serve.handle import _MethodCaller

        return _MethodCaller(self, item)


def run_local(app) -> LocalDeploymentHandle:
    """Instantiate the app's deployment graph in-process; returns the
    ingress handle. Composition args that are bound Applications become
    local handles, mirroring the cluster path."""
    from ray_tpu.serve.deployment import Application

    cache: Dict[str, LocalDeploymentHandle] = {}

    def build(a: Application) -> LocalDeploymentHandle:
        d = a.deployment
        if d.name in cache:
            return cache[d.name]
        args = [
            build(x) if isinstance(x, Application) else x for x in a.args
        ]
        kwargs = {
            k: build(x) if isinstance(x, Application) else x
            for k, x in a.kwargs.items()
        }
        target = d.target
        is_function = not inspect.isclass(target)
        instance = target if is_function else target(*args, **kwargs)
        if not is_function and d.config.user_config is not None and hasattr(
            instance, "reconfigure"
        ):
            instance.reconfigure(d.config.user_config)
        handle = LocalDeploymentHandle(d.name, instance, is_function)
        cache[d.name] = handle
        return handle

    return build(app)
