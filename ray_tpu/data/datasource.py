"""Datasources: create datasets from memory, files, and generators; writers.

Reference analog: ``python/ray/data/read_api.py`` + ``datasource/`` (the
long tail of connectors — parquet/csv/json/images/SQL/... — shares this
file-per-block shape; the formats here are the ones a TPU training/eval
stack actually feeds from).
"""
from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, Iterable, List, Optional

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor, batch_to_block
from ray_tpu.data.dataset import Dataset, _split_table
from ray_tpu.data.executor import put_block


DEFAULT_BLOCK_ROWS = 64 * 1024


def _blocks_from_table(table: pa.Table, parallelism: int) -> List:
    n = max(1, min(parallelism, max(table.num_rows, 1)))
    return [put_block(t) for t in _split_table(table, n)]


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    table = pa.table({"id": pa.array(np.arange(n, dtype=np.int64))})
    return Dataset(_blocks_from_table(table, parallelism))


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    data = np.broadcast_to(
        np.arange(n, dtype=np.int64).reshape((n,) + (1,) * len(shape)),
        (n,) + tuple(shape),
    ).copy()
    return from_numpy({"data": data}, parallelism=parallelism)


def from_items(items: List[Any], *, parallelism: int = 8) -> Dataset:
    if items and not isinstance(items[0], dict):
        items = [{"item": x} for x in items]
    table = pa.Table.from_pylist(items) if items else pa.table({})
    return Dataset(_blocks_from_table(table, parallelism))


def from_numpy(arrays, *, parallelism: int = 8) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {"data": arrays}
    return Dataset(_blocks_from_table(batch_to_block(arrays), parallelism))


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    return Dataset(_blocks_from_table(batch_to_block(df), parallelism))


def from_arrow(table: pa.Table, *, parallelism: int = 8) -> Dataset:
    return Dataset(_blocks_from_table(table, parallelism))


def from_huggingface(hf_dataset, *, parallelism: int = 8) -> Dataset:
    """An in-memory ``datasets.Dataset`` → Dataset (reference:
    ``from_huggingface``)."""
    return from_arrow(hf_dataset.data.table, parallelism=parallelism)


def _expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")
            ))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


def _read_files(paths, read_one) -> Dataset:
    """One task per file: files are the natural block boundary."""
    files = _expand_paths(paths)
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        return Dataset([read_one(f) for f in files])
    import ray_tpu

    task = ray_tpu.remote(read_one)
    return Dataset([task.remote(f) for f in files])


def read_parquet(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        import pyarrow.parquet as pq

        return pq.read_table(path)

    return _read_files(paths, read_one)


def read_csv(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        from pyarrow import csv as pacsv

        return pacsv.read_csv(path)

    return _read_files(paths, read_one)


def read_json(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        from pyarrow import json as pajson

        return pajson.read_json(path)

    return _read_files(paths, read_one)


def read_text(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        return pa.table({"text": pa.array(lines)})

    return _read_files(paths, read_one)


def read_numpy(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        return batch_to_block({"data": np.load(path)})

    return _read_files(paths, read_one)


IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp", ".tiff")


def read_images(paths, *, size=None, mode: str = "RGB", **kw) -> Dataset:
    """Image files → rows {"image": HWC uint8 array, "path": str}
    (reference: ``ray.data.read_images`` / ``datasource/image_datasource``).
    ``size=(h, w)`` resizes on read — the data-layer place to normalize
    shapes before batching onto static-shape accelerator programs."""

    def read_one(path: str) -> pa.Table:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert(mode)
            if size is not None:
                im = im.resize((size[1], size[0]))
            arr = np.asarray(im)
        return batch_to_block({
            "image": arr[None],  # [1, H, W, C]
            "path": np.array([path]),
        })

    files = [
        p for p in _expand_paths(paths)
        if p.lower().endswith(IMAGE_EXTS)
    ]
    if not files:
        raise FileNotFoundError(f"no image files match {paths}")
    return _read_files(files, read_one)


def read_binary_files(paths, **kw) -> Dataset:
    def read_one(path: str) -> pa.Table:
        with open(path, "rb") as f:
            return pa.table({
                "bytes": pa.array([f.read()], type=pa.binary()),
                "path": pa.array([path]),
            })

    return _read_files(paths, read_one)


# ------------------------------------------------------------------ writers


def _write_blocks(ds: Dataset, path: str, ext: str, write_one) -> List[str]:
    os.makedirs(path, exist_ok=True)
    out = []
    for i, block in enumerate(ds._streaming_blocks()):
        fp = os.path.join(path, f"part-{i:05d}.{ext}")
        write_one(block, fp)
        out.append(fp)
    return out


def write_parquet(ds: Dataset, path: str, **kw) -> List[str]:
    import pyarrow.parquet as pq

    return _write_blocks(ds, path, "parquet",
                         lambda b, fp: pq.write_table(b, fp))


def write_csv(ds: Dataset, path: str, **kw) -> List[str]:
    from pyarrow import csv as pacsv

    return _write_blocks(ds, path, "csv",
                         lambda b, fp: pacsv.write_csv(b, fp))


def write_json(ds: Dataset, path: str, **kw) -> List[str]:
    def write_one(block, fp):
        BlockAccessor(block).to_pandas().to_json(
            fp, orient="records", lines=True
        )

    return _write_blocks(ds, path, "json", write_one)


def read_sql(sql: str, connection_factory, *, parallelism: int = 1,
             order_by: Optional[str] = None) -> Dataset:
    """Read a SQL query through a DBAPI2 connection factory (reference:
    ``data/datasource/sql_datasource.py`` — same shape: the factory runs on
    the reading task so connections never pickle). Works with stdlib
    sqlite3, psycopg2, mysqlclient, duckdb, ...

    ``parallelism`` > 1 re-runs the query once per shard and splits it by
    ``ROW_NUMBER() OVER (ORDER BY {order_by})`` windows, so it requires
    ``order_by``: a column list giving a total order. Without one, engines
    with nondeterministic scan order (e.g. Postgres parallel seq scans) can
    number rows differently per run, silently duplicating or dropping rows.
    Default is one task (the reference also reads unpartitioned queries in
    one task).
    """
    import cloudpickle

    if parallelism > 1 and not order_by:
        raise ValueError(
            "read_sql(parallelism>1) requires order_by=: sharding re-runs "
            "the query per shard and splits by row number, which is only "
            "stable under a total order. Pass order_by='<unique column(s)>' "
            "or use parallelism=1."
        )
    payload = cloudpickle.dumps((sql, connection_factory))

    def read_shard(shard: int, nshards: int) -> pa.Table:
        import cloudpickle as cp

        q, factory = cp.loads(payload)
        conn = factory()
        try:
            cur = conn.cursor()
            if nshards > 1:
                # Window functions are illegal in WHERE: project the row
                # number in a subquery, filter one level up. ORDER BY makes
                # the numbering stable across the per-shard re-runs.
                q = (
                    f"SELECT * FROM (SELECT __rt_sub.*, "
                    f"ROW_NUMBER() OVER (ORDER BY {order_by}) AS __rt_rn "
                    f"FROM ({q}) __rt_sub) "
                    f"__rt_outer WHERE __rt_rn % {nshards} = {shard}"
                )
            try:
                cur.execute(q)
            except Exception as e:
                # RuntimeError, not type(e): DBAPI error constructors take
                # driver-specific args and re-raising type(e)(str) masks
                # the real failure for e.g. MySQLdb's (errno, msg) shape.
                raise RuntimeError(f"read_sql failed: {e} (query: {q!r})") from e
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        if nshards > 1:
            cols = cols[:-1]  # drop the __rt_rn shard column
            rows = [r[:-1] for r in rows]
        arrays = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
        return pa.table(arrays)

    import builtins

    from ray_tpu._private import worker as worker_mod

    # NOT the module-level dataset range() that shadows the builtin here
    shards = list(builtins.range(max(parallelism, 1)))
    if worker_mod.global_worker is None:
        return Dataset([read_shard(s, len(shards)) for s in shards])
    import ray_tpu

    task = ray_tpu.remote(read_shard)
    return Dataset([task.remote(s, len(shards)) for s in shards])


def read_webdataset(paths, *, suffixes: Optional[List[str]] = None,
                    **kw) -> Dataset:
    """Read WebDataset tar shards (reference:
    ``data/datasource/webdataset_datasource.py``): files in each tar are
    grouped by key (basename before the first dot); each group becomes one
    row with a column per suffix holding the raw bytes."""

    def read_one(path: str) -> pa.Table:
        import tarfile
        from collections import OrderedDict

        groups: "OrderedDict[str, dict]" = OrderedDict()
        with tarfile.open(path) as tf:
            for member in tf:
                if not member.isfile():
                    continue
                base = os.path.basename(member.name)
                if "." not in base:
                    continue
                key, suffix = base.split(".", 1)
                if suffixes is not None and suffix not in suffixes:
                    continue
                groups.setdefault(key, {"__key__": key})[suffix] = (
                    tf.extractfile(member).read()
                )
        rows = list(groups.values())
        cols = ["__key__"] + sorted(
            {k for r in rows for k in r} - {"__key__"}
        )
        return pa.table(
            {c: [r.get(c) for r in rows] for c in cols}
        )

    return _read_files(paths, read_one)


def read_lance(uri: str, **kw) -> Dataset:
    """Read a Lance dataset (reference: ``data/datasource/lance_datasource``).
    Requires the optional ``lance`` package."""
    try:
        import lance
    except ImportError as e:
        raise ImportError(
            "read_lance requires the optional 'lance' package "
            "(pip install pylance)"
        ) from e
    ds = lance.dataset(uri)
    return Dataset([frag_table for frag_table in (
        ds.scanner(fragments=[f]).to_table() for f in ds.get_fragments()
    )])


def read_iceberg(table_identifier: str, *, catalog_kwargs=None,
                 **kw) -> Dataset:
    """Read an Apache Iceberg table (reference:
    ``data/datasource/iceberg_datasource.py``). Requires ``pyiceberg``."""
    try:
        from pyiceberg.catalog import load_catalog
    except ImportError as e:
        raise ImportError(
            "read_iceberg requires the optional 'pyiceberg' package"
        ) from e
    catalog = load_catalog(**(catalog_kwargs or {}))
    table = catalog.load_table(table_identifier)
    return from_arrow(table.scan().to_arrow())


def read_bigquery(query: str = None, *, project_id: str = None,
                  dataset: str = None, **kw) -> Dataset:
    """Read from Google BigQuery (reference:
    ``data/datasource/bigquery_datasource.py``). Requires
    ``google-cloud-bigquery``."""
    try:
        from google.cloud import bigquery
    except ImportError as e:
        raise ImportError(
            "read_bigquery requires the optional 'google-cloud-bigquery' "
            "package"
        ) from e
    client = bigquery.Client(project=project_id)
    if query is None:
        query = f"SELECT * FROM `{dataset}`"
    return from_arrow(client.query(query).to_arrow())


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, **kw) -> Dataset:
    """Read a MongoDB collection (reference:
    ``data/datasource/mongo_datasource.py``). Requires ``pymongo``."""
    try:
        import pymongo
    except ImportError as e:
        raise ImportError(
            "read_mongo requires the optional 'pymongo' package"
        ) from e
    client = pymongo.MongoClient(uri)
    coll = client[database][collection]
    docs = list(coll.aggregate(pipeline or []))
    for d in docs:
        d.pop("_id", None)
    return from_items(docs)


def read_tfrecords(paths, **kw) -> Dataset:
    """TFRecord files of tf.train.Example records → rows (reference:
    ``ray.data.read_tfrecords``). Feature types map: bytes_list[0] →
    bytes (utf-8 decoded when clean), int64/float lists → scalar when
    length 1, else 1-D numpy arrays."""
    import numpy as np
    import tensorflow as tf

    def read_one(path):
        # Two passes: parse every record keeping raw value lists, THEN
        # decide scalar-vs-list PER COLUMN (a column unwraps to scalars
        # only when every record has exactly one value). Per-row unwrapping
        # would hand arrow a column mixing scalars and arrays whenever a
        # feature's value count varies across records, which fails table
        # construction (reference unwraps per-column the same way). The
        # decision is per FILE (files are the block boundary); counts that
        # vary only across files still need a user-side schema.
        rows = []
        kinds = {}
        scalar_ok: dict = {}
        for raw in tf.data.TFRecordDataset([path]):
            ex = tf.train.Example()
            ex.ParseFromString(bytes(raw.numpy()))
            row = {}
            for name, feat in ex.features.feature.items():
                kind = feat.WhichOneof("kind")
                if kind == "bytes_list":
                    # bytes stay bytes (reference behavior): a per-value
                    # decode heuristic would mix str/bytes in one column
                    # and break arrow schema construction
                    vals = [bytes(v) for v in feat.bytes_list.value]
                elif kind == "int64_list":
                    vals = [int(v) for v in feat.int64_list.value]
                else:
                    vals = [float(v) for v in feat.float_list.value]
                row[name] = vals
                kinds[name] = kind
                if len(vals) != 1:
                    scalar_ok[name] = False
                else:
                    scalar_ok.setdefault(name, True)
            rows.append(row)
        for row in rows:
            for name, vals in row.items():
                if scalar_ok.get(name):
                    row[name] = vals[0]
                elif kinds.get(name) != "bytes_list":
                    row[name] = np.asarray(vals)
        import pyarrow as _pa

        from ray_tpu.data.block import _to_table

        return _to_table(rows) if rows else _pa.table({})

    return _read_files(paths, read_one)


def write_tfrecords(ds: Dataset, path: str, **kw) -> List[str]:
    """Blocks → TFRecord files of tf.train.Example (reference:
    ``Dataset.write_tfrecords``): int → int64_list, float → float_list,
    str/bytes → bytes_list, 1-D ndarray columns → multi-value lists."""
    import numpy as np
    import tensorflow as tf

    def write_one(block, fp):
        rows = BlockAccessor(block).to_pylist()
        with tf.io.TFRecordWriter(fp) as w:
            for row in rows:
                feats = {}
                for k, v in row.items():
                    if isinstance(v, np.ndarray):
                        v = v.tolist()
                    if isinstance(v, (list, tuple)):
                        vals = v
                    else:
                        vals = [v]
                    # bools ride int64_list (reference convention)
                    if all(isinstance(x, (bool, int, np.integer))
                           for x in vals):
                        feat = tf.train.Feature(int64_list=tf.train.Int64List(
                            value=[int(x) for x in vals]))
                    elif all(isinstance(x, (int, float, np.floating,
                                            np.integer)) for x in vals):
                        feat = tf.train.Feature(float_list=tf.train.FloatList(
                            value=[float(x) for x in vals]))
                    else:
                        feat = tf.train.Feature(bytes_list=tf.train.BytesList(
                            value=[
                                x.encode() if isinstance(x, str) else bytes(x)
                                for x in vals
                            ]))
                    feats[k] = feat
                w.write(tf.train.Example(
                    features=tf.train.Features(feature=feats)
                ).SerializeToString())

    return _write_blocks(ds, path, "tfrecord", write_one)


def write_sql(ds: Dataset, table: str, connection_factory) -> int:
    """Write rows into a SQL table via a DBAPI2 factory; returns row count
    (reference: ``Dataset.write_sql``)."""
    total = 0
    conn = connection_factory()
    # Placeholder style differs per driver (sqlite/duckdb: qmark '?';
    # psycopg2/mysqlclient: format '%s'): read it off the driver module.
    import importlib
    import sys as _sys

    mod = _sys.modules.get(type(conn).__module__.split(".")[0])
    style = getattr(mod, "paramstyle", "qmark") if mod else "qmark"
    mark = "%s" if style in ("format", "pyformat") else "?"
    try:
        cur = conn.cursor()
        for block in ds._streaming_blocks():
            acc = BlockAccessor(block)
            cols = block.column_names
            ph = ", ".join([mark] * len(cols))
            stmt = f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph})"
            rows = [tuple(r[c] for c in cols) for r in acc.iter_rows()]
            if rows:
                cur.executemany(stmt, rows)
                total += len(rows)
        conn.commit()
    finally:
        conn.close()
    return total
