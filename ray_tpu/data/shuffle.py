"""Distributed shuffle plane for Dataset barrier ops.

Reference analog: ``python/ray/data/_internal/execution/operators/
hash_shuffle.py:526`` (hash-partitioning map tasks feeding per-partition
aggregator/reduce tasks) plus the sample-based range partitioning its sort
uses. Round 2's barrier ops concatenated every block in the driver —
a dataset larger than driver RAM could not be shuffled at all. Here:

- **map tasks** apply the pending fused transforms to their block, split it
  into P partition pieces, ``put`` each piece into the cluster object store,
  and return only the (tiny) list of piece refs;
- **reduce tasks** take partition p's pieces from every map as ref args
  (fetched by the object plane, never the driver) and combine them —
  concat, sort, arrow group-aggregate, pyarrow join, or local permutation;
- the driver orchestrates refs only: its peak memory is O(M x P) refs.

Key hashing uses ``pandas.util.hash_pandas_object`` (fixed-key siphash) so
the same key value lands in the same partition from every map task in every
process.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor

_MIX = np.uint64(0x9E3779B97F4A7C15)


def _key_hash(table: pa.Table, keys: Sequence[str]) -> np.ndarray:
    """Deterministic cross-process row hash of the key column(s)."""
    import pandas as pd

    h: Optional[np.ndarray] = None
    for k in keys:
        s = table.column(k).to_pandas()
        hk = pd.util.hash_pandas_object(s, index=False).to_numpy()
        h = hk if h is None else (h * _MIX) ^ hk
    assert h is not None
    return h


def _split_by_assignment(table: pa.Table, assign: np.ndarray,
                         num_partitions: int) -> List[pa.Table]:
    """Split rows by partition id in one stable take + P slices."""
    order = np.argsort(assign, kind="stable")
    sorted_tab = table.take(pa.array(order)) if len(order) else table
    bounds = np.searchsorted(assign[order], np.arange(num_partitions + 1))
    return [
        sorted_tab.slice(int(bounds[p]), int(bounds[p + 1] - bounds[p]))
        for p in range(num_partitions)
    ]


def _assignment(table: pa.Table, spec: dict) -> np.ndarray:
    P = spec["P"]
    mode = spec["mode"]
    n = table.num_rows
    if mode == "hash":
        return (_key_hash(table, spec["keys"]) % np.uint64(P)).astype(
            np.int64
        )
    if mode == "random":
        # salt = stable block index -> same seed reproduces the same
        # permutation run-to-run (a task-id salt would not)
        rng = np.random.default_rng(
            None if spec.get("seed") is None
            else (spec["seed"], spec.get("salt", 0))
        )
        return rng.integers(0, P, size=n)
    if mode == "range":
        col = table.column(spec["keys"][0]).to_numpy(zero_copy_only=False)
        return np.searchsorted(
            np.asarray(spec["bounds"]), col, side="right"
        ).astype(np.int64)
    if mode == "contig":
        # Global contiguous split: row r of this block belongs to the
        # partition owning global index offset+r — output partitions
        # concatenated in order reproduce the input order exactly.
        gidx = spec["offset"] + np.arange(n)
        return np.searchsorted(
            np.asarray(spec["cuts"]), gidx, side="right"
        ).astype(np.int64)
    raise ValueError(f"unknown partition mode {mode!r}")


def _partition_map_task(payload, block: Block) -> List[Any]:
    """Map task body: fused transforms -> partition -> put pieces."""
    import cloudpickle

    import ray_tpu

    fns, spec = cloudpickle.loads(payload)
    for fn in fns:
        block = fn(block)
    pieces = _split_by_assignment(
        block, _assignment(block, spec), spec["P"]
    )
    return [ray_tpu.put(p) for p in pieces]


def _combine_task(payload, *pieces: Block) -> Block:
    """Reduce task body: combine partition p's pieces."""
    import cloudpickle

    spec = cloudpickle.loads(payload)
    tables = [p for p in pieces if p.num_rows > 0]
    if not tables:
        tables = [pieces[0]] if pieces else []
    table = (
        BlockAccessor.concat(list(tables)) if tables else pa.table({})
    )
    kind = spec["kind"]
    if kind == "concat":
        return table
    if kind == "sort":
        order = "descending" if spec.get("descending") else "ascending"
        idx = pa.compute.sort_indices(
            table, sort_keys=[(k, order) for k in spec["keys"]]
        )
        return table.take(idx)
    if kind == "shuffle":
        rng = np.random.default_rng(spec.get("seed"))
        return table.take(pa.array(rng.permutation(table.num_rows)))
    if kind == "agg":
        return table.group_by(spec["key"]).aggregate(spec["aggs"])
    if kind == "map_groups":
        from ray_tpu.data.block import batch_to_block

        fn = cloudpickle.loads(spec["fn"])
        key = spec["key"]
        outs = []
        for k in pa.compute.unique(table.column(key)).to_pylist():
            sub = table.filter(
                pa.compute.equal(table.column(key), pa.scalar(k))
            )
            acc = BlockAccessor(sub)
            outs.append(
                batch_to_block(fn(acc.batch(0, acc.num_rows(),
                                            spec["batch_format"])))
            )
        return BlockAccessor.concat(outs) if outs else table.slice(0, 0)
    raise ValueError(f"unknown combine kind {kind!r}")


def _join_task(payload, nleft: int, *pieces: Block) -> Block:
    import cloudpickle

    spec = cloudpickle.loads(payload)
    left = [p for p in pieces[:nleft] if p.num_rows > 0]
    right = [p for p in pieces[nleft:] if p.num_rows > 0]
    lt = BlockAccessor.concat(list(left)) if left else pieces[0].slice(0, 0)
    rt = (
        BlockAccessor.concat(list(right)) if right
        else pieces[nleft].slice(0, 0)
    )
    return lt.join(
        rt, keys=spec["keys"], join_type=spec["how"],
        right_suffix=spec["suffix"],
    )


def _count_rows_task(block: Block) -> int:
    return block.num_rows


def _sample_task(payload, block: Block) -> np.ndarray:
    """Map task body for sort sampling: fused transforms -> key sample."""
    import cloudpickle

    fns, key, cap = cloudpickle.loads(payload)
    for fn in fns:
        block = fn(block)
    col = block.column(key).to_numpy(zero_copy_only=False)
    if len(col) > cap:
        idx = np.random.default_rng(0).choice(len(col), cap, replace=False)
        col = col[idx]
    return np.asarray(col)


class ShufflePlan:
    """Driver-side orchestration of one map->reduce exchange."""

    def __init__(self, num_partitions: int):
        self.P = max(int(num_partitions), 1)

    def _map(self, blocks, pending, map_spec,
             per_block: Optional[List[dict]] = None) -> List[List[Any]]:
        import cloudpickle

        import ray_tpu

        task = ray_tpu.remote(_partition_map_task)
        ref_lists = []
        for i, b in enumerate(blocks):
            spec_i = dict(map_spec, salt=i)
            if per_block is not None:
                spec_i.update(per_block[i])
            payload = cloudpickle.dumps((list(pending), spec_i))
            ref_lists.append(task.remote(payload, b))
        # Each result is a tiny list of P refs; the data stays distributed.
        return ray_tpu.get(ref_lists)

    def exchange(self, blocks, pending, *, map_spec: dict,
                 reduce_spec: dict,
                 per_block: Optional[List[dict]] = None) -> List[Any]:
        """Full map->reduce pass; returns P output block refs."""
        import cloudpickle

        import ray_tpu

        map_spec = dict(map_spec, P=self.P)
        piece_refs = self._map(blocks, pending, map_spec,
                               per_block=per_block)
        reduce = ray_tpu.remote(_combine_task)
        payload = cloudpickle.dumps(reduce_spec)
        return [
            reduce.remote(payload, *[m[p] for m in piece_refs])
            for p in range(self.P)
        ]

    def block_row_counts(self, blocks) -> List[int]:
        """Per-block row counts via metadata tasks (blocks stay remote)."""
        import ray_tpu

        task = ray_tpu.remote(_count_rows_task)
        return ray_tpu.get([task.remote(b) for b in blocks])

    def exchange_join(self, left_blocks, left_pending, right_blocks,
                      right_pending, *, keys: List[str], how: str,
                      suffix: str) -> List[Any]:
        import cloudpickle

        import ray_tpu

        spec = {"mode": "hash", "keys": keys, "P": self.P}
        lp = self._map(left_blocks, left_pending, spec)
        rp = self._map(right_blocks, right_pending, spec)
        join = ray_tpu.remote(_join_task)
        payload = cloudpickle.dumps(
            {"keys": keys, "how": how, "suffix": suffix}
        )
        return [
            join.remote(
                payload, len(lp),
                *[m[p] for m in lp], *[m[p] for m in rp],
            )
            for p in range(self.P)
        ]

    def sample_bounds(self, blocks, pending, key: str,
                      sample_cap: int = 4096) -> np.ndarray:
        """Sort sampling pass: P-1 range boundaries from per-block samples."""
        import cloudpickle

        import ray_tpu

        task = ray_tpu.remote(_sample_task)
        payload = cloudpickle.dumps(
            (list(pending), key, max(sample_cap // max(len(blocks), 1), 64))
        )
        samples = ray_tpu.get([task.remote(payload, b) for b in blocks])
        nonempty = [s for s in samples if len(s)]
        if not nonempty:
            # Every block empty (e.g. post-filter): a valid empty dataset —
            # np.concatenate([]) would raise instead of sorting nothing.
            return np.asarray([])
        allv = np.sort(np.concatenate(nonempty))
        qs = np.linspace(0, len(allv) - 1, self.P + 1)[1:-1].astype(int)
        return allv[qs]
