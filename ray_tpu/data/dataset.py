"""Dataset: lazy, distributed, streaming-executed collections of blocks.

Reference analog: ``python/ray/data/dataset.py`` (lazy logical plan →
physical operators → StreamingExecutor). The plan here is a chain of fusable
per-block transforms punctuated by barrier ops (repartition / shuffle /
sort); execution materializes block ObjectRefs in the cluster object store.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, BlockAccessor, batch_to_block
from ray_tpu.data.executor import StreamingExecutor, put_block, resolve_block


def _map_rows_fn(fn):
    def apply(block: Block) -> Block:
        rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
        return batch_to_block(rows) if rows else block.slice(0, 0)

    return apply


def _flat_map_fn(fn):
    def apply(block: Block) -> Block:
        rows = list(
            itertools.chain.from_iterable(
                fn(r) for r in BlockAccessor(block).iter_rows()
            )
        )
        return batch_to_block(rows) if rows else block.slice(0, 0)

    return apply


def _filter_fn(fn):
    def apply(block: Block) -> Block:
        acc = BlockAccessor(block)
        keep = [fn(r) for r in acc.iter_rows()]
        return acc.table.filter(pa.array(keep, type=pa.bool_()))

    return apply


def _map_batches_fn(fn, batch_size: Optional[int], batch_format: str,
                    fn_kwargs: Optional[dict]):
    kwargs = fn_kwargs or {}

    def apply(block: Block) -> Block:
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if n == 0:
            return block
        size = batch_size or n
        outs = []
        for start in range(0, n, size):
            batch = acc.batch(start, min(start + size, n), batch_format)
            outs.append(batch_to_block(fn(batch, **kwargs)))
        return BlockAccessor.concat(outs)

    return apply


def _add_column_fn(name: str, fn):
    def apply(block: Block) -> Block:
        acc = BlockAccessor(block)
        col = fn(acc.batch(0, acc.num_rows(), "pandas"))
        return acc.table.append_column(name, pa.array(np.asarray(col)))

    return apply


def _drop_columns_fn(cols: List[str]):
    def apply(block: Block) -> Block:
        return block.drop_columns(cols)

    return apply


def _select_columns_fn(cols: List[str]):
    def apply(block: Block) -> Block:
        return block.select(cols)

    return apply


def _rename_columns_fn(mapping: Dict[str, str]):
    def apply(block: Block) -> Block:
        return block.rename_columns(
            [mapping.get(c, c) for c in block.column_names]
        )

    return apply


class Dataset:
    """Lazy plan: input block refs + pending fused transforms."""

    def __init__(self, blocks: List[Any], pending: Optional[List] = None,
                 executor: Optional[StreamingExecutor] = None):
        self._blocks = list(blocks)  # refs (cluster) or Blocks (local mode)
        self._pending: List[Callable[[Block], Block]] = list(pending or [])
        self._executor = executor or StreamingExecutor()

    # ------------------------------------------------------------- plan ops

    def _with(self, fn) -> "Dataset":
        return Dataset(self._blocks, self._pending + [fn], self._executor)

    @staticmethod
    def _transform_opts(op: str, num_cpus=None, num_gpus=None,
                        resources=None, concurrency=None, unknown=None):
        """Validate + package per-transform execution options. A kwarg we
        neither honor nor know is a TypeError, not a silent no-op
        (reference: ``data/dataset.py`` map signature validates kwargs)."""
        if unknown:
            raise TypeError(
                f"Dataset.{op}() got unexpected keyword argument(s) "
                f"{sorted(unknown)}; supported: num_cpus, num_gpus, "
                "resources, concurrency"
            )
        opts = {}
        if num_cpus is not None:
            opts["num_cpus"] = num_cpus
        if num_gpus is not None:
            opts["num_gpus"] = num_gpus
        if resources is not None:
            opts["resources"] = dict(resources)
        if concurrency is not None:
            opts["concurrency"] = int(concurrency)
        return opts

    def map(self, fn, *, num_cpus=None, num_gpus=None, resources=None,
            concurrency=None, **kw) -> "Dataset":
        opts = self._transform_opts(
            "map", num_cpus, num_gpus, resources, concurrency, kw
        )
        stage = _map_rows_fn(fn)
        stage._rt_opts = opts
        return self._with(stage)

    def flat_map(self, fn, *, num_cpus=None, num_gpus=None, resources=None,
                 concurrency=None, **kw) -> "Dataset":
        opts = self._transform_opts(
            "flat_map", num_cpus, num_gpus, resources, concurrency, kw
        )
        stage = _flat_map_fn(fn)
        stage._rt_opts = opts
        return self._with(stage)

    def filter(self, fn, *, num_cpus=None, num_gpus=None, resources=None,
               concurrency=None, **kw) -> "Dataset":
        opts = self._transform_opts(
            "filter", num_cpus, num_gpus, resources, concurrency, kw
        )
        stage = _filter_fn(fn)
        stage._rt_opts = opts
        return self._with(stage)

    def map_batches(self, fn, *, batch_size: Optional[int] = 1024,
                    batch_format: str = "numpy",
                    fn_kwargs: Optional[dict] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus=None, num_gpus=None,
                    resources: Optional[dict] = None, **kw) -> "Dataset":
        """Batch transform. A callable CLASS runs on a pool of stateful
        actors (constructed once per actor, reused across blocks —
        reference: actor_pool_map_operator); a plain function fuses into
        per-block tasks."""
        import inspect

        opts = self._transform_opts(
            "map_batches", num_cpus, num_gpus, resources, None, kw
        )
        if inspect.isclass(fn):
            from ray_tpu.data.executor import ActorStage

            return self._with(ActorStage(
                fn, fn_constructor_args, fn_constructor_kwargs,
                batch_size, batch_format, fn_kwargs, concurrency or 2,
                resources=resources, num_cpus=num_cpus, num_gpus=num_gpus,
            ))
        stage = _map_batches_fn(fn, batch_size, batch_format, fn_kwargs)
        # Only an EXPLICIT concurrency caps the fused stage's in-flight
        # window; the actor-pool default above must not throttle the
        # task path.
        if concurrency is not None:
            opts = dict(opts, concurrency=concurrency)
        stage._rt_opts = opts
        return self._with(stage)

    def add_column(self, name: str, fn, **_) -> "Dataset":
        return self._with(_add_column_fn(name, fn))

    def drop_columns(self, cols: List[str], **_) -> "Dataset":
        return self._with(_drop_columns_fn(cols))

    def select_columns(self, cols: List[str], **_) -> "Dataset":
        return self._with(_select_columns_fn(cols))

    def rename_columns(self, mapping: Dict[str, str], **_) -> "Dataset":
        return self._with(_rename_columns_fn(mapping))

    # -------------------------------------------------------- execution

    def materialize(self) -> "Dataset":
        """Execute pending transforms; blocks land in the object store."""
        if not self._pending:
            return self
        out = list(
            self._executor.execute(self._blocks, self._pending, name="fused")
        )
        return Dataset(out, [], self._executor)

    def _materialized_blocks(self) -> List[Block]:
        ds = self.materialize()
        return [resolve_block(r) for r in ds._blocks]

    def _streaming_blocks(self) -> Iterator[Block]:
        """Stream blocks through pending transforms without full
        materialization (the executor keeps a bounded window in flight)."""
        for ref in self._executor.execute(self._blocks, self._pending,
                                          name="stream"):
            yield resolve_block(ref)

    # ------------------------------------------------------- barrier ops
    #
    # On a cluster these run as a distributed map->reduce exchange
    # (``data/shuffle.py`` — reference: hash_shuffle.py operators): the
    # driver only ever holds block refs, so datasets far larger than
    # driver RAM shuffle/sort/join fine. Without a cluster (local mode)
    # they fall back to in-process arrow ops.

    def _distributed(self) -> bool:
        from ray_tpu._private import worker as worker_mod

        return worker_mod.global_worker is not None and bool(self._blocks)

    def _plan(self, num_partitions: Optional[int] = None):
        from ray_tpu.data.shuffle import ShufflePlan

        return ShufflePlan(num_partitions or max(len(self._blocks), 1))

    def repartition(self, num_blocks: int, **_) -> "Dataset":
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        if self._distributed():
            # Materialize pending transforms once (blocks stay remote),
            # then split on GLOBAL contiguous row ranges so the output
            # preserves row order exactly like the local path.
            ds = self.materialize()
            plan = ds._plan(num_blocks)
            counts = plan.block_row_counts(ds._blocks)
            total = sum(counts)
            sizes = [
                total // num_blocks + (1 if i < total % num_blocks else 0)
                for i in range(num_blocks)
            ]
            cuts = list(np.cumsum(sizes)[:-1])
            offsets = list(np.cumsum([0] + counts[:-1]))
            out = plan.exchange(
                ds._blocks, [],
                map_spec={"mode": "contig", "cuts": cuts},
                reduce_spec={"kind": "concat"},
                per_block=[{"offset": int(o)} for o in offsets],
            )
            return Dataset(out, [], self._executor)
        table = BlockAccessor.concat(self._materialized_blocks())
        return Dataset(
            [put_block(t) for t in _split_table(table, num_blocks)],
            [], self._executor,
        )

    def random_shuffle(self, *, seed: Optional[int] = None, **_) -> "Dataset":
        if self._distributed():
            out = self._plan().exchange(
                self._blocks, self._pending,
                map_spec={"mode": "random", "seed": seed},
                reduce_spec={"kind": "shuffle", "seed": seed},
            )
            return Dataset(out, [], self._executor)
        blocks = self._materialized_blocks()
        table = BlockAccessor.concat(blocks)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(table.num_rows)
        shuffled = table.take(pa.array(perm))
        k = max(len(blocks), 1)
        return Dataset([put_block(b) for b in _split_table(shuffled, k)],
                       [], self._executor)

    def sort(self, key: Union[str, List[str]], descending: bool = False,
             **_) -> "Dataset":
        keys = [key] if isinstance(key, str) else key
        if self._distributed():
            # Materialize once: sampling + partitioning would otherwise
            # each run the pending transform chain over the whole dataset.
            ds = self.materialize()
            plan = ds._plan()
            bounds = plan.sample_bounds(ds._blocks, [], keys[0])
            out = plan.exchange(
                ds._blocks, [],
                map_spec={"mode": "range", "keys": keys,
                          "bounds": list(bounds)},
                reduce_spec={"kind": "sort", "keys": keys,
                             "descending": descending},
            )
            # Range partitions are ascending by construction; descending
            # output = descending within partitions + reversed partitions.
            if descending:
                out = list(reversed(out))
            return Dataset(out, [], self._executor)
        table = BlockAccessor.concat(self._materialized_blocks())
        order = "descending" if descending else "ascending"
        idx = pa.compute.sort_indices(
            table, sort_keys=[(k, order) for k in keys]
        )
        return Dataset([put_block(table.take(idx))], [], self._executor)

    def join(self, other: "Dataset", on: Union[str, List[str]], *,
             how: str = "inner", suffix: str = "_r", **_) -> "Dataset":
        """Hash join on key column(s) (reference: the join physical operator
        under ``_internal/execution/operators``; distributed via two-sided
        hash partitioning on the key). Arrow-native per partition;
        supported ``how``: inner, left outer, right outer, full outer."""
        how_map = {
            "inner": "inner", "left": "left outer", "right": "right outer",
            "outer": "full outer", "left outer": "left outer",
            "right outer": "right outer", "full outer": "full outer",
        }
        if how not in how_map:
            raise ValueError(f"unsupported join type {how!r}")
        keys = [on] if isinstance(on, str) else list(on)
        if self._distributed() and other._distributed():
            out = self._plan(
                max(len(self._blocks), len(other._blocks))
            ).exchange_join(
                self._blocks, self._pending,
                other._blocks, other._pending,
                keys=keys, how=how_map[how], suffix=suffix,
            )
            return Dataset(out, [], self._executor)
        left = BlockAccessor.concat(self._materialized_blocks())
        right = BlockAccessor.concat(other._materialized_blocks())
        joined = left.join(
            right, keys=keys, join_type=how_map[how],
            right_suffix=suffix,
        )
        return Dataset([put_block(joined)], [], self._executor)

    def union(self, *others: "Dataset") -> "Dataset":
        blocks = list(self.materialize()._blocks)
        for o in others:
            blocks.extend(o.materialize()._blocks)
        return Dataset(blocks, [], self._executor)

    def zip(self, other: "Dataset") -> "Dataset":
        a = BlockAccessor.concat(self._materialized_blocks())
        b = BlockAccessor.concat(other._materialized_blocks())
        if a.num_rows != b.num_rows:
            raise ValueError("zip requires equal row counts")
        for name in b.column_names:
            col = b.column(name)
            out_name = name if name not in a.column_names else f"{name}_1"
            a = a.append_column(out_name, col)
        return Dataset([put_block(a)], [], self._executor)

    def limit(self, n: int) -> "Dataset":
        out, remaining = [], n
        for block in self._streaming_blocks():  # early-stops the stream
            if remaining <= 0:
                break
            rows = BlockAccessor(block).num_rows()
            out.append(put_block(block.slice(0, min(rows, remaining))))
            remaining -= rows
        return Dataset(out, [], self._executor)

    # ------------------------------------------------------ splits (Train)

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        blocks = self.materialize()._blocks
        if len(blocks) < n or equal:
            # equal=True must rebalance by rows, not deal blocks round-robin:
            # unequal shards make SPMD ranks run different step counts and
            # hang the next collective. (equal shards drop the remainder.)
            table = BlockAccessor.concat([resolve_block(r) for r in blocks])
            if equal:
                table = table.slice(0, (table.num_rows // n) * n)
            return [
                Dataset([put_block(t)], [], self._executor)
                for t in _split_table(table, n)
            ]
        out = [[] for _ in range(n)]
        for i, b in enumerate(blocks):
            out[i % n].append(b)
        return [Dataset(bs, [], self._executor) for bs in out]

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        table = BlockAccessor.concat(self._materialized_blocks())
        bounds = [0] + list(indices) + [table.num_rows]
        return [
            Dataset([put_block(table.slice(a, b - a))], [], self._executor)
            for a, b in zip(bounds, bounds[1:])
        ]

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None) -> Tuple["Dataset", "Dataset"]:
        ds = self.random_shuffle(seed=seed) if shuffle else self
        n = ds.count()
        cut = n - int(n * test_size) if test_size < 1 else n - int(test_size)
        parts = ds.split_at_indices([cut])
        return parts[0], parts[1]

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["Dataset"]:
        return self.split(n, equal=equal)

    # ------------------------------------------------------- consumption

    def iter_rows(self) -> Iterator[dict]:
        for block in self._streaming_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        """Re-batched stream across block boundaries."""
        carry: Optional[Block] = None
        rng = np.random.default_rng(local_shuffle_seed)
        for block in self._streaming_blocks():
            if local_shuffle_buffer_size:
                idx = rng.permutation(block.num_rows)
                block = block.take(pa.array(idx))
            carry = block if carry is None else BlockAccessor.concat(
                [carry, block]
            )
            while carry.num_rows >= batch_size:
                acc = BlockAccessor(carry)
                yield acc.batch(0, batch_size, batch_format)
                carry = carry.slice(batch_size, carry.num_rows - batch_size)
        if carry is not None and carry.num_rows > 0 and not drop_last:
            acc = BlockAccessor(carry)
            yield acc.batch(0, carry.num_rows, batch_format)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         sharding=None, dtypes: Optional[dict] = None,
                         drop_last: bool = True,
                         prefetch: int = 2) -> Iterator[Dict[str, Any]]:
        """Device-fed batches with transfer/compute overlap (TPU-first
        feature; reference ships ``iter_torch_batches`` with GPU pinning —
        here ``jax.device_put`` starts the host→HBM copy asynchronously and
        we keep ``prefetch`` batches in flight so step N computes while
        N+1 transfers).

        ``sharding``: a ``jax.sharding.Sharding`` (e.g. NamedSharding over
        the data axis) applied to every array; default = local device.
        """
        import collections as _c

        import jax

        def to_device(batch):
            arrs = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                arrs[k] = (
                    jax.device_put(v, sharding) if sharding is not None
                    else jax.device_put(v)
                )
            return arrs

        window: "_c.deque" = _c.deque()
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            window.append(to_device(batch))
            if len(window) > prefetch:
                yield window.popleft()
        while window:
            yield window.popleft()

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def iter_tf_batches(self, *, batch_size: int = 256,
                        drop_last: bool = False) -> Iterator[dict]:
        """Batches as dicts of ``tf.Tensor`` (reference:
        ``Dataset.iter_tf_batches``)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            yield {k: tf.convert_to_tensor(v) for k, v in batch.items()}

    def to_tf(self, feature_columns, label_columns, *,
              batch_size: int = 256, drop_last: bool = False):
        """A ``tf.data.Dataset`` of (features, labels) batches (reference:
        ``Dataset.to_tf`` — keras ``model.fit`` consumable). Column args
        take a name or list of names; single names yield bare tensors,
        lists yield dicts (the reference's convention)."""
        import tensorflow as tf

        feat_list = ([feature_columns] if isinstance(feature_columns, str)
                     else list(feature_columns))
        lab_list = ([label_columns] if isinstance(label_columns, str)
                    else list(label_columns))

        # one probe batch pins the signature (dtypes + trailing dims)
        try:
            probe = next(self.iter_batches(batch_size=1, batch_format="numpy"))
        except StopIteration:
            # An empty dataset has no batch to derive dtypes/shapes from;
            # StopIteration escaping a generator-adjacent call surfaces as
            # a baffling RuntimeError far from here.
            raise ValueError(
                "to_tf() requires a non-empty dataset: cannot derive the "
                "tf.data signature (dtypes/shapes) from zero rows"
            ) from None

        def spec(col):
            arr = np.asarray(probe[col])
            return tf.TensorSpec(
                shape=(None, *arr.shape[1:]), dtype=arr.dtype
            )

        def pick(batch, cols, single):
            if single:
                return tf.convert_to_tensor(batch[cols[0]])
            return {c: tf.convert_to_tensor(batch[c]) for c in cols}

        single_f = isinstance(feature_columns, str)
        single_l = isinstance(label_columns, str)

        def gen():
            for batch in self.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=drop_last):
                yield (pick(batch, feat_list, single_f),
                       pick(batch, lab_list, single_l))

        f_sig = (spec(feat_list[0]) if single_f
                 else {c: spec(c) for c in feat_list})
        l_sig = (spec(lab_list[0]) if single_l
                 else {c: spec(c) for c in lab_list})
        return tf.data.Dataset.from_generator(
            gen, output_signature=(f_sig, l_sig)
        )

    # ------------------------------------------------------- aggregates

    def count(self) -> int:
        return sum(
            BlockAccessor(b).num_rows() for b in self._streaming_blocks()
        )

    def _column_agg(self, on: str, per_block_fn, combine_fn):
        """Single pass over the stream; None when every block is empty."""
        vals = []
        for b in self._streaming_blocks():
            acc = BlockAccessor(b)
            if acc.num_rows() > 0:
                vals.append(per_block_fn(acc.to_numpy([on])[on]))
        return None if not vals else float(combine_fn(np.asarray(vals)))

    def sum(self, on: str):
        return self._column_agg(on, np.sum, np.sum)

    def min(self, on: str):
        return self._column_agg(on, np.min, np.min)

    def max(self, on: str):
        return self._column_agg(on, np.max, np.max)

    def mean(self, on: str):
        total, n = 0.0, 0
        for b in self._streaming_blocks():
            acc = BlockAccessor(b)
            if acc.num_rows():
                col = acc.to_numpy([on])[on]
                total += float(np.sum(col))
                n += len(col)
        return None if n == 0 else total / n

    def std(self, on: str, ddof: int = 1):
        cols = [c for c in (BlockAccessor(b).to_numpy([on])[on]
                            for b in self._streaming_blocks()) if len(c)]
        if not cols:
            return None
        return float(np.std(np.concatenate(cols), ddof=ddof))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def unique(self, column: str) -> List[Any]:
        # Streaming per-block uniques -> driver set union: the driver sees
        # only distinct values, never the rows.
        out: set = set()
        for b in self._streaming_blocks():
            out.update(pa.compute.unique(b.column(column)).to_pylist())
        return sorted(out, key=lambda v: (v is None, v))

    # ------------------------------------------------------- inspection

    def take(self, n: int = 20) -> List[dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[dict]:
        return [r for r in self.iter_rows()]

    def take_batch(self, n: int = 20, batch_format: str = "numpy"):
        for b in self.iter_batches(batch_size=n, batch_format=batch_format):
            return b
        return {}

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def schema(self) -> Optional[pa.Schema]:
        # Empty blocks may carry a stale pre-transform schema (a transform
        # can't know its output schema without rows) — prefer the first
        # block that actually has rows.
        first = None
        for b in self._streaming_blocks():
            acc = BlockAccessor(b)
            if acc.num_rows() > 0:
                return acc.schema()
            if first is None:
                first = acc.schema()
        return first

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def num_blocks(self) -> int:
        return len(self.materialize()._blocks)

    def size_bytes(self) -> int:
        return sum(BlockAccessor(b).size_bytes()
                   for b in self._streaming_blocks())

    def stats(self) -> str:
        return self._executor.stats.summary()

    def to_pandas(self):
        return BlockAccessor.concat(self._materialized_blocks()).to_pandas()

    def to_arrow(self) -> pa.Table:
        return BlockAccessor.concat(self._materialized_blocks())

    def __iter__(self):
        return self.iter_rows()

    def __repr__(self):
        s = self.schema()
        cols = ", ".join(s.names) if s else "?"
        return f"Dataset(blocks={len(self._blocks)}, columns=[{cols}])"

    # ------------------------------------------------------------ writes

    def write_parquet(self, path: str, **kw):
        from ray_tpu.data import datasource

        datasource.write_parquet(self, path, **kw)

    def write_csv(self, path: str, **kw):
        from ray_tpu.data import datasource

        datasource.write_csv(self, path, **kw)

    def write_json(self, path: str, **kw):
        from ray_tpu.data import datasource

        datasource.write_json(self, path, **kw)

    def write_tfrecords(self, path: str, **kw):
        from ray_tpu.data import datasource

        datasource.write_tfrecords(self, path, **kw)


class GroupedData:
    """Groupby over the distributed shuffle plane (reference:
    ``python/ray/data/grouped_data.py`` + hash_aggregate operators): rows
    hash-partition by key so each key lives wholly inside one partition,
    then partitions aggregate independently with arrow group_by. Local mode
    aggregates in-process."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, aggs: List[tuple]) -> Dataset:
        ds = self._ds
        if ds._distributed():
            out = ds._plan().exchange(
                ds._blocks, ds._pending,
                map_spec={"mode": "hash", "keys": [self._key]},
                reduce_spec={"kind": "agg", "key": self._key, "aggs": aggs},
            )
            return Dataset(out, [], ds._executor)
        table = BlockAccessor.concat(ds._materialized_blocks())
        return Dataset([put_block(table.group_by(self._key).aggregate(aggs))])

    def count(self) -> Dataset:
        return self._agg([(self._key, "count")])

    def sum(self, on: str) -> Dataset:
        return self._agg([(on, "sum")])

    def min(self, on: str) -> Dataset:
        return self._agg([(on, "min")])

    def max(self, on: str) -> Dataset:
        return self._agg([(on, "max")])

    def mean(self, on: str) -> Dataset:
        return self._agg([(on, "mean")])

    def map_groups(self, fn, *, batch_format: str = "numpy") -> Dataset:
        ds = self._ds
        if ds._distributed():
            import cloudpickle

            out = ds._plan().exchange(
                ds._blocks, ds._pending,
                map_spec={"mode": "hash", "keys": [self._key]},
                reduce_spec={"kind": "map_groups", "key": self._key,
                             "fn": cloudpickle.dumps(fn),
                             "batch_format": batch_format},
            )
            return Dataset(out, [], ds._executor)
        table = BlockAccessor.concat(ds._materialized_blocks())
        keys = pa.compute.unique(table.column(self._key)).to_pylist()
        outs = []
        for k in keys:
            mask = pa.compute.equal(table.column(self._key), pa.scalar(k))
            sub = table.filter(mask)
            acc = BlockAccessor(sub)
            outs.append(batch_to_block(
                fn(acc.batch(0, acc.num_rows(), batch_format))
            ))
        return Dataset([put_block(BlockAccessor.concat(outs))])


def _split_table(table: pa.Table, n: int) -> List[pa.Table]:
    rows = table.num_rows
    sizes = [rows // n + (1 if i < rows % n else 0) for i in range(n)]
    out, start = [], 0
    for s in sizes:
        out.append(table.slice(start, s))
        start += s
    return out
