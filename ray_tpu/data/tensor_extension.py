"""Arrow tensor extension types: ndarray columns as first-class arrow data.

Reference analog: ``python/ray/air/util/tensor_extensions/arrow.py``
(``ArrowTensorType`` / ``ArrowTensorArray`` / ``ArrowVariableShapedTensorType``)
— the reference stores multi-dimensional columns as arrow *extension types*
so tensor shape survives schema operations, IPC, and parquet round-trips
without side-channel metadata.

Design (independent, not a translation): fixed-shape tensors are a
``FixedSizeList`` storage array whose extension metadata carries the inner
shape + dtype; variable-shaped (ragged) tensors are a
``Struct{data: List, shape: List[Int64]}`` storage where each row owns its
own shape vector. Both register with arrow's global extension registry at
import so deserialized tables (plasma, parquet, IPC) reconstruct the typed
columns automatically. Zero-copy: ``to_numpy`` reshapes the flat storage
buffer without copying for fixed shapes.
"""
from __future__ import annotations

import json
from typing import Sequence

import numpy as np
import pyarrow as pa


class ArrowTensorType(pa.ExtensionType):
    """Fixed-shape tensor column: every row is an ndarray of ``shape``."""

    EXT_NAME = "ray_tpu.tensor"

    def __init__(self, shape: Sequence[int], value_type: pa.DataType):
        self._shape = tuple(int(s) for s in shape)
        size = 1
        for s in self._shape:
            size *= s
        super().__init__(
            pa.list_(value_type, size), self.EXT_NAME
        )

    @property
    def shape(self):
        return self._shape

    def __arrow_ext_serialize__(self) -> bytes:
        return json.dumps({"shape": list(self._shape)}).encode()

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        shape = json.loads(serialized.decode())["shape"]
        return cls(shape, storage_type.value_type)

    def __arrow_ext_class__(self):
        return ArrowTensorArray

    def __reduce__(self):
        return (
            ArrowTensorType, (self._shape, self.storage_type.value_type)
        )


class ArrowTensorArray(pa.ExtensionArray):
    """Array of fixed-shape tensors over FixedSizeList storage."""

    @staticmethod
    def from_numpy(arr: np.ndarray) -> "ArrowTensorArray":
        arr = np.ascontiguousarray(arr)
        if arr.ndim < 2:
            raise ValueError("tensor columns need ndim >= 2 ([row, ...])")
        inner = int(np.prod(arr.shape[1:]))
        storage = pa.FixedSizeListArray.from_arrays(
            pa.array(arr.reshape(-1)), inner
        )
        typ = ArrowTensorType(arr.shape[1:], storage.type.value_type)
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy(self, zero_copy_only: bool = False) -> np.ndarray:
        flat = self.storage.flatten().to_numpy(zero_copy_only=zero_copy_only)
        return flat.reshape((len(self), *self.type.shape))


class ArrowVariableShapedTensorType(pa.ExtensionType):
    """Ragged tensor column: each row is an ndarray with its own shape
    (same rank and dtype across rows is NOT required by storage, only by
    convention at the numpy boundary)."""

    EXT_NAME = "ray_tpu.var_tensor"

    def __init__(self, value_type: pa.DataType):
        storage = pa.struct([
            pa.field("data", pa.list_(value_type)),
            pa.field("shape", pa.list_(pa.int64())),
        ])
        super().__init__(storage, self.EXT_NAME)

    def __arrow_ext_serialize__(self) -> bytes:
        return b""

    @classmethod
    def __arrow_ext_deserialize__(cls, storage_type, serialized):
        return cls(storage_type.field("data").type.value_type)

    def __arrow_ext_class__(self):
        return ArrowVariableShapedTensorArray

    def __reduce__(self):
        return (
            ArrowVariableShapedTensorType,
            (self.storage_type.field("data").type.value_type,),
        )


class ArrowVariableShapedTensorArray(pa.ExtensionArray):
    @staticmethod
    def from_numpy(arrs) -> "ArrowVariableShapedTensorArray":
        """From a sequence of ndarrays with (possibly) different shapes."""
        arrs = [np.asarray(a) for a in arrs]
        if not arrs:
            raise ValueError("empty tensor sequence")
        dtype = arrs[0].dtype
        data = pa.array(
            [a.reshape(-1) for a in arrs],
            type=pa.list_(pa.from_numpy_dtype(dtype)),
        )
        shape = pa.array(
            [list(a.shape) for a in arrs], type=pa.list_(pa.int64())
        )
        storage = pa.StructArray.from_arrays([data, shape], ["data", "shape"])
        typ = ArrowVariableShapedTensorType(pa.from_numpy_dtype(dtype))
        return pa.ExtensionArray.from_storage(typ, storage)

    def to_numpy(self, zero_copy_only: bool = False) -> np.ndarray:
        """Object ndarray of per-row tensors (shapes differ by row)."""
        data = self.storage.field("data")
        shapes = self.storage.field("shape").to_pylist()
        out = np.empty(len(self), dtype=object)
        for i in range(len(self)):
            out[i] = np.asarray(data[i].values.to_numpy(
                zero_copy_only=False
            )).reshape(shapes[i])
        return out


_registered = False


def ensure_registered() -> None:
    """Idempotently register both extension types with arrow's global
    registry so IPC/parquet/plasma deserialization restores typed columns."""
    global _registered
    if _registered:
        return
    try:
        pa.register_extension_type(ArrowTensorType((1,), pa.float32()))
        pa.register_extension_type(
            ArrowVariableShapedTensorType(pa.float32())
        )
    except pa.ArrowKeyError:  # another module registered first
        pass
    _registered = True


ensure_registered()
