"""Per-operator execution budgets + backpressure policies for the data
streaming executor.

Reference behavior being reproduced (not code):
``python/ray/data/_internal/execution/resource_manager.py`` (global limits,
per-operator budgets with reserved minimums) and
``backpressure_policy/concurrency_cap_backpressure_policy.py`` — the
scheduling loop asks the policies whether an operator may launch more work.
The TPU-era failure mode this guards: a data-ingest pipeline co-located
with training actors must not occupy every cluster CPU — ingest gets a
configurable FRACTION of the cluster (``RT_DATA_CPU_FRACTION``), split
across this driver's concurrently-executing operators, with a reserved
minimum of one task per operator so progress is always possible.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ExecutionResources:
    """The resource vector budgets are expressed in (reference:
    ExecutionResources — cpu/gpu/object_store_memory; object-store bytes
    here are arena bytes)."""

    cpu: float = 0.0
    object_store_bytes: int = 0


@dataclass
class OpState:
    """Live accounting for one executing operator (stage)."""

    name: str
    concurrency_cap: int  # per-op cap (Dataset.map(concurrency=...))
    cpu_per_task: float = 1.0
    in_flight: int = 0
    tasks_launched: int = 0

    @property
    def cpu_in_use(self) -> float:
        return self.in_flight * self.cpu_per_task


class BackpressurePolicy:
    """One admission rule: may ``op`` launch another task right now?"""

    def can_add_input(self, op: OpState, rm: "ResourceManager") -> bool:
        raise NotImplementedError


class ConcurrencyCapBackpressurePolicy(BackpressurePolicy):
    """Per-operator in-flight cap (reference:
    concurrency_cap_backpressure_policy.py)."""

    def can_add_input(self, op: OpState, rm: "ResourceManager") -> bool:
        return op.in_flight < op.concurrency_cap


class ReservedCpuBackpressurePolicy(BackpressurePolicy):
    """Budget policy: all of this driver's data operators together stay
    within ``data_cpu_fraction`` of the cluster's CPUs, the budget split
    evenly across active operators — with a reserved minimum of ONE task
    per operator so a tight budget degrades to serial progress, never
    deadlock (reference: reserved resources in resource_manager.py)."""

    def can_add_input(self, op: OpState, rm: "ResourceManager") -> bool:
        if op.in_flight == 0:
            return True  # reserved minimum: one task always admits
        budget = rm.op_budget(op)
        return op.cpu_in_use + op.cpu_per_task <= budget.cpu + 1e-9


class ResourceManager:
    """Global limits + per-op budgets + the policy chain. One instance per
    driver process (operators of concurrent Dataset executions share the
    data budget — they contend for the same cluster)."""

    def __init__(self, policies: List[BackpressurePolicy] = None):
        self._ops: Dict[int, OpState] = {}
        self._lock = threading.Lock()
        self.policies: List[BackpressurePolicy] = policies or [
            ConcurrencyCapBackpressurePolicy(),
            ReservedCpuBackpressurePolicy(),
        ]

    # ------------------------------------------------------------- limits

    def global_limits(self) -> ExecutionResources:
        """What the DATA plane may use cluster-wide: a fraction of total
        CPUs (leaving the rest for co-located train/serve actors) and of
        the object-store arena."""
        from ray_tpu._private.config import rt_config

        total_cpu = 0.0
        try:
            import ray_tpu

            total_cpu = float(ray_tpu.cluster_resources().get("CPU", 0.0))
        except Exception:
            pass
        frac = float(rt_config.data_cpu_fraction)
        return ExecutionResources(
            cpu=max(total_cpu * frac, 1.0),
            object_store_bytes=int(rt_config.arena_bytes * frac),
        )

    def op_budget(self, op: OpState) -> ExecutionResources:
        """This operator's share: the data budget split evenly across the
        operators currently executing under this driver."""
        limits = self.global_limits()
        with self._lock:
            n = max(len(self._ops), 1)
        return ExecutionResources(
            cpu=limits.cpu / n,
            object_store_bytes=limits.object_store_bytes // n,
        )

    # ---------------------------------------------------------- lifecycle

    def register_op(self, name: str, concurrency_cap: int,
                    cpu_per_task: float = 1.0) -> OpState:
        # Explicit 0 is honored (num_cpus=0 IO stages consume no budget);
        # negative input clamps to 0.
        op = OpState(name=name, concurrency_cap=max(concurrency_cap, 1),
                     cpu_per_task=max(cpu_per_task, 0.0))
        with self._lock:
            self._ops[id(op)] = op
        return op

    def unregister_op(self, op: OpState):
        with self._lock:
            self._ops.pop(id(op), None)

    # --------------------------------------------------------- accounting

    def on_task_submitted(self, op: OpState):
        op.in_flight += 1
        op.tasks_launched += 1

    def on_task_output_consumed(self, op: OpState):
        op.in_flight = max(op.in_flight - 1, 0)

    def can_add_input(self, op: OpState) -> bool:
        return all(p.can_add_input(op, self) for p in self.policies)

    def debug_state(self) -> List[dict]:
        with self._lock:
            return [
                {"name": o.name, "in_flight": o.in_flight,
                 "launched": o.tasks_launched,
                 "budget_cpu": self.op_budget(o).cpu}
                for o in self._ops.values()
            ]


_default_manager: ResourceManager = None
_default_lock = threading.Lock()


def default_resource_manager() -> ResourceManager:
    global _default_manager
    if _default_manager is None:
        with _default_lock:
            if _default_manager is None:
                _default_manager = ResourceManager()
    return _default_manager
