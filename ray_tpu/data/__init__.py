"""ray_tpu.data: distributed datasets with streaming execution.

Reference analog: ``python/ray/data`` (Dataset, read_api, streaming
executor). Blocks are arrow tables in the cluster object store; transforms
fuse into per-block tasks executed with a bounded in-flight window; batches
feed jax via ``iter_jax_batches`` (double-buffered ``jax.device_put``).
"""
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import Dataset, GroupedData
from ray_tpu.data.datasource import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_images,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
    read_sql,
    read_tfrecords,
    read_webdataset,
    read_lance,
    read_iceberg,
    read_bigquery,
    read_mongo,
    write_sql,
    read_text,
)
from ray_tpu.data.executor import StreamingExecutor

__all__ = [
    "Block",
    "BlockAccessor",
    "Dataset",
    "GroupedData",
    "StreamingExecutor",
    "from_arrow",
    "from_huggingface",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_images",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_sql",
    "read_tfrecords",
    "read_webdataset",
    "read_lance",
    "read_iceberg",
    "read_bigquery",
    "read_mongo",
    "write_sql",
    "read_text",
]
