"""Streaming executor: bounded-in-flight per-block task pipeline.

Reference analog: ``python/ray/data/_internal/execution/streaming_executor.py``
(:76) with its scheduling loop (``streaming_executor_state.py:672
select_operator_to_run``) and backpressure policies. This design keeps the
essence — blocks stream through operator stages as distributed tasks with a
cap on concurrent in-flight work — with one TPU-era simplification: chains of
row/batch transforms are **fused into a single task per block** (the
reference's operator fusion rule, ``logical/optimizers.py``), so a block is
read, transformed N times, and stored exactly once. Barrier ops
(shuffle/sort/repartition) materialize between fused segments.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from ray_tpu.data.block import Block, BlockAccessor, batch_to_block


@dataclass
class ExecStats:
    tasks_submitted: int = 0
    blocks_produced: int = 0
    rows_produced: int = 0
    wall_time_s: float = 0.0
    per_stage: Dict[str, float] = field(default_factory=dict)

    def summary(self) -> str:
        lines = [
            f"tasks={self.tasks_submitted} blocks={self.blocks_produced} "
            f"rows={self.rows_produced} wall={self.wall_time_s:.3f}s"
        ]
        for name, t in self.per_stage.items():
            lines.append(f"  stage {name}: {t:.3f}s")
        return "\n".join(lines)


def _apply_fused(block: Block, fns: List[Callable[[Block], Block]]) -> Block:
    for fn in fns:
        block = fn(block)
    return block


def _remote_apply(serialized_fns, block: Block) -> Block:
    """Task body: run the fused transform chain on one block."""
    import cloudpickle

    fns = cloudpickle.loads(serialized_fns)
    return _apply_fused(block, fns)


class StreamingExecutor:
    """Executes a fused stage over input block refs with bounded in-flight
    tasks; yields output block refs as they finish (streaming, not barrier).
    """

    def __init__(self, max_in_flight: int = 16, locality: bool = True):
        self.max_in_flight = max_in_flight
        self.stats = ExecStats()

    def execute(
        self,
        in_refs: List[Any],
        fns: List[Callable[[Block], Block]],
        name: str = "map",
    ) -> Iterator[Any]:
        """in_refs: ObjectRefs of input blocks (or local Blocks when running
        without a cluster). Yields refs/blocks of transformed output."""
        import time

        t0 = time.monotonic()
        if not fns:
            yield from in_refs
            return
        from ray_tpu._private import worker as worker_mod

        if worker_mod.global_worker is None:
            # Local mode: run inline (reference local_testing_mode analog).
            for b in in_refs:
                out = _apply_fused(_resolve_local(b), fns)
                self.stats.blocks_produced += 1
                self.stats.rows_produced += BlockAccessor(out).num_rows()
                yield out
            self.stats.wall_time_s += time.monotonic() - t0
            return

        import cloudpickle

        import ray_tpu

        payload = cloudpickle.dumps(fns)
        apply_task = ray_tpu.remote(_remote_apply)

        pending = collections.deque()
        it = iter(in_refs)
        exhausted = False
        while pending or not exhausted:
            while not exhausted and len(pending) < self.max_in_flight:
                try:
                    ref = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(apply_task.remote(payload, ref))
                self.stats.tasks_submitted += 1
            if pending:
                # Pop in order: preserves block order; completed later tasks
                # simply wait in the store (streaming window gives overlap).
                out = pending.popleft()
                yield out
        self.stats.per_stage[name] = (
            self.stats.per_stage.get(name, 0.0) + time.monotonic() - t0
        )
        self.stats.wall_time_s += time.monotonic() - t0


def _resolve_local(b):
    return b


def resolve_block(ref) -> Block:
    """Ref-or-block → block."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.object_ref import ObjectRef

    if isinstance(ref, ObjectRef):
        import ray_tpu

        return ray_tpu.get(ref)
    return ref


def put_block(block: Block):
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        return block
    import ray_tpu

    return ray_tpu.put(block)
