"""Streaming executor: bounded-in-flight per-block task pipeline.

Reference analog: ``python/ray/data/_internal/execution/streaming_executor.py``
(:76) with its scheduling loop (``streaming_executor_state.py:672
select_operator_to_run``) and backpressure policies. This design keeps the
essence — blocks stream through operator stages as distributed tasks with a
cap on concurrent in-flight work — with one TPU-era simplification: chains of
row/batch transforms are **fused into a single task per block** (the
reference's operator fusion rule, ``logical/optimizers.py``), so a block is
read, transformed N times, and stored exactly once. Barrier ops
(shuffle/sort/repartition) materialize between fused segments.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from ray_tpu.data.block import Block, BlockAccessor, batch_to_block


@dataclass
class ExecStats:
    tasks_submitted: int = 0
    blocks_produced: int = 0
    rows_produced: int = 0
    wall_time_s: float = 0.0
    per_stage: Dict[str, float] = field(default_factory=dict)

    def summary(self) -> str:
        lines = [
            f"tasks={self.tasks_submitted} blocks={self.blocks_produced} "
            f"rows={self.rows_produced} wall={self.wall_time_s:.3f}s"
        ]
        for name, t in self.per_stage.items():
            lines.append(f"  stage {name}: {t:.3f}s")
        return "\n".join(lines)


def _apply_fused(block: Block, fns: List[Callable[[Block], Block]]) -> Block:
    for fn in fns:
        block = fn(block)
    return block


class ActorStage:
    """Plan marker: run this transform on a pool of stateful actors
    (reference: ``data/_internal/execution/operators/actor_pool_map_operator
    .py`` — callable-class UDFs construct ONCE per actor and serve many
    blocks; per-task construction would pay model-load per block)."""

    def __init__(self, cls, ctor_args, ctor_kwargs, batch_size, batch_format,
                 fn_kwargs, concurrency, resources=None, num_cpus=None,
                 num_gpus=None):
        import cloudpickle

        self.payload = cloudpickle.dumps(
            (cls, tuple(ctor_args or ()), dict(ctor_kwargs or {}),
             batch_size, batch_format, dict(fn_kwargs or {}))
        )
        self.concurrency = max(int(concurrency), 1)
        self.resources = resources
        self.num_cpus = num_cpus
        self.num_gpus = num_gpus

    def build_local(self):
        """Local-mode transform: one instance, applied inline."""
        import cloudpickle

        cls, args, kwargs, bs, fmt, fkw = cloudpickle.loads(self.payload)
        inst = cls(*args, **kwargs)

        def apply(block: Block) -> Block:
            return _apply_batched(block, inst, bs, fmt, fkw)

        return apply


def _apply_batched(block: Block, fn, batch_size, batch_format, fn_kwargs):
    # One batching implementation for function AND actor stages.
    from ray_tpu.data.dataset import _map_batches_fn

    return _map_batches_fn(fn, batch_size, batch_format, fn_kwargs)(block)


class _BatchPoolWorker:
    """Actor body for ActorStage pools: the UDF instance lives for the
    actor's lifetime."""

    def __init__(self, payload):
        import cloudpickle

        cls, args, kwargs, bs, fmt, fkw = cloudpickle.loads(payload)
        self.fn = cls(*args, **kwargs)
        self.bs, self.fmt, self.fkw = bs, fmt, fkw

    def apply(self, block: Block) -> Block:
        return _apply_batched(block, self.fn, self.bs, self.fmt, self.fkw)


def _remote_apply(serialized_fns, block: Block) -> Block:
    """Task body: run the fused transform chain on one block."""
    import cloudpickle

    fns = cloudpickle.loads(serialized_fns)
    return _apply_fused(block, fns)


class StreamingExecutor:
    """Executes a fused stage over input block refs with bounded in-flight
    tasks; yields output block refs as they finish (streaming, not barrier).
    """

    def __init__(self, max_in_flight: int = 16, locality: bool = True):
        self.max_in_flight = max_in_flight
        self.stats = ExecStats()

    def execute(
        self,
        in_refs: List[Any],
        fns: List[Any],
        name: str = "map",
    ) -> Iterator[Any]:
        """in_refs: ObjectRefs of input blocks (or local Blocks when running
        without a cluster). ``fns`` may mix plain block transforms (fused
        into one task per block) and ActorStage markers (stateful pools);
        the whole chain streams — no barrier between sub-stages. Yields
        refs/blocks of transformed output."""
        import time

        t0 = time.monotonic()
        if not fns:
            yield from in_refs
            return
        from ray_tpu._private import worker as worker_mod

        local = worker_mod.global_worker is None
        # Split into alternating fused-fn groups and actor stages.
        groups: List[tuple] = []
        for fn in fns:
            if isinstance(fn, ActorStage):
                groups.append(("actor", fn))
            elif groups and groups[-1][0] == "fns":
                groups[-1][1].append(fn)
            else:
                groups.append(("fns", [fn]))
        stream: Iterator[Any] = iter(in_refs)
        for kind, payload in groups:
            if kind == "fns":
                if local:
                    stream = self._fused_local(stream, payload)
                else:
                    stream = self._fused_tasks(stream, payload)
            else:
                if local:
                    stream = self._fused_local(
                        stream, [payload.build_local()]
                    )
                else:
                    stream = self._actor_pool(stream, payload)
        for out in stream:
            self.stats.blocks_produced += 1
            yield out
        self.stats.per_stage[name] = (
            self.stats.per_stage.get(name, 0.0) + time.monotonic() - t0
        )
        self.stats.wall_time_s += time.monotonic() - t0

    def _fused_local(self, stream, fns):
        for b in stream:
            out = _apply_fused(_resolve_local(b), fns)
            self.stats.rows_produced += BlockAccessor(out).num_rows()
            yield out

    def _fused_tasks(self, stream, fns):
        import cloudpickle

        import ray_tpu

        payload = cloudpickle.dumps(fns)
        # Per-transform execution options (Dataset.map(num_cpus=...,
        # resources=..., concurrency=...)): a fused group takes the max
        # CPU/GPU request, the union of custom resources, and the
        # tightest concurrency cap of its member transforms.
        num_cpus = num_gpus = None
        resources = {}
        in_flight = self.max_in_flight
        for fn in fns:
            o = getattr(fn, "_rt_opts", None) or {}
            if o.get("num_cpus") is not None:
                num_cpus = max(num_cpus or 0, o["num_cpus"])
            if o.get("num_gpus") is not None:
                num_gpus = max(num_gpus or 0, o["num_gpus"])
            for k, v in (o.get("resources") or {}).items():
                # per-key MAX (like num_cpus): the fused task runs EVERY
                # member transform, so it needs the largest request
                resources[k] = max(resources.get(k, 0), v)
            if o.get("concurrency"):
                in_flight = min(in_flight, o["concurrency"])
        task_opts = {}
        if num_cpus is not None:
            task_opts["num_cpus"] = num_cpus
        if num_gpus is not None:
            task_opts["num_gpus"] = num_gpus
        if resources:
            task_opts["resources"] = resources
        apply_task = ray_tpu.remote(_remote_apply)
        if task_opts:
            apply_task = apply_task.options(**task_opts)
        from ray_tpu.data.resource_manager import default_resource_manager

        rm = default_resource_manager()
        op = rm.register_op(
            "map", concurrency_cap=in_flight,
            cpu_per_task=num_cpus if num_cpus is not None else 1.0,
        )
        pending = collections.deque()
        exhausted = False
        try:
            while pending or not exhausted:
                # The policy chain (per-op cap + reserved-CPU budget)
                # gates every submission: ingest never occupies more than
                # its share of the cluster even while a consumer lags.
                while not exhausted and rm.can_add_input(op):
                    try:
                        ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    pending.append(apply_task.remote(payload, ref))
                    rm.on_task_submitted(op)
                    self.stats.tasks_submitted += 1
                if pending:
                    # Pop in order: preserves block order; completed later
                    # tasks simply wait in the store (streaming window
                    # gives overlap).
                    yield pending.popleft()
                    rm.on_task_output_consumed(op)
        finally:
            rm.unregister_op(op)

    def _actor_pool(self, stream, stage: ActorStage):
        """Bounded-in-flight round-robin over a pool of stateful actors;
        the pool dies with the stage (reference: actor_pool_map_operator
        autoscaling pool — fixed size here)."""
        import ray_tpu

        opts = {}
        if stage.resources:
            opts["resources"] = stage.resources
        if stage.num_cpus is not None:
            opts["num_cpus"] = stage.num_cpus
        if stage.num_gpus is not None:
            opts["num_gpus"] = stage.num_gpus
        worker_cls = ray_tpu.remote(**opts)(_BatchPoolWorker) if opts else (
            ray_tpu.remote(_BatchPoolWorker)
        )
        actors = [
            worker_cls.remote(stage.payload)
            for _ in range(stage.concurrency)
        ]
        produced: List[Any] = []
        try:
            pending = collections.deque()
            exhausted = False
            i = 0
            window = max(2 * stage.concurrency, 2)
            while pending or not exhausted:
                while not exhausted and len(pending) < window:
                    try:
                        ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    actor = actors[i % len(actors)]
                    i += 1
                    pending.append(actor.apply.remote(ref))
                    self.stats.tasks_submitted += 1
                if pending:
                    out = pending.popleft()
                    produced.append(out)
                    yield out
        finally:
            # A consumer may hold yielded refs unresolved (e.g. list() then
            # resolve later): wait for every produced task BEFORE killing
            # the pool, or the kill cancels their in-flight execution.
            try:
                if produced:
                    ray_tpu.wait(
                        produced, num_returns=len(produced), timeout=300,
                        fetch_local=False,
                    )
            except Exception:
                pass
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


def _resolve_local(b):
    return b


def resolve_block(ref) -> Block:
    """Ref-or-block → block."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.object_ref import ObjectRef

    if isinstance(ref, ObjectRef):
        import ray_tpu

        return ray_tpu.get(ref)
    return ref


def put_block(block: Block):
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        return block
    import ray_tpu

    return ray_tpu.put(block)
