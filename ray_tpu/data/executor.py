"""Streaming executor: bounded-in-flight per-block task pipeline.

Reference analog: ``python/ray/data/_internal/execution/streaming_executor.py``
(:76) with its scheduling loop (``streaming_executor_state.py:672
select_operator_to_run``) and backpressure policies. This design keeps the
essence — blocks stream through operator stages as distributed tasks with a
cap on concurrent in-flight work — with one TPU-era simplification: chains of
row/batch transforms are **fused into a single task per block** (the
reference's operator fusion rule, ``logical/optimizers.py``), so a block is
read, transformed N times, and stored exactly once. Barrier ops
(shuffle/sort/repartition) materialize between fused segments.
"""
from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

from ray_tpu.data.block import Block, BlockAccessor, batch_to_block


@dataclass
class StageStats:
    """Per-operator execution statistics (reference:
    ``data/_internal/stats.py`` per-operator summaries: tasks, blocks,
    rows, bytes, UDF time, block-size distribution)."""

    wall_s: float = 0.0
    tasks: int = 0
    blocks: int = 0
    rows: int = 0
    bytes: int = 0
    udf_s: float = 0.0          # summed in-task transform time
    min_block_rows: int = 0
    max_block_rows: int = 0
    min_block_bytes: int = 0
    max_block_bytes: int = 0

    def add_block(self, meta: Dict[str, Any]):
        rows, nbytes = int(meta.get("rows", 0)), int(meta.get("bytes", 0))
        if self.blocks == 0:
            self.min_block_rows = self.max_block_rows = rows
            self.min_block_bytes = self.max_block_bytes = nbytes
        else:
            self.min_block_rows = min(self.min_block_rows, rows)
            self.max_block_rows = max(self.max_block_rows, rows)
            self.min_block_bytes = min(self.min_block_bytes, nbytes)
            self.max_block_bytes = max(self.max_block_bytes, nbytes)
        self.blocks += 1
        self.rows += rows
        self.bytes += nbytes
        self.udf_s += float(meta.get("udf_s", 0.0))


@dataclass
class ExecStats:
    tasks_submitted: int = 0
    blocks_produced: int = 0
    rows_produced: int = 0
    wall_time_s: float = 0.0
    per_stage: Dict[str, StageStats] = field(default_factory=dict)

    def stage(self, name: str) -> StageStats:
        if name not in self.per_stage:
            self.per_stage[name] = StageStats()
        return self.per_stage[name]

    def summary(self) -> str:
        lines = [
            f"tasks={self.tasks_submitted} blocks={self.blocks_produced} "
            f"rows={self.rows_produced} wall={self.wall_time_s:.3f}s"
        ]
        for name, st in self.per_stage.items():
            lines.append(
                f"  operator {name}: {st.wall_s:.3f}s wall, "
                f"{st.tasks} tasks, {st.blocks} blocks, {st.rows} rows, "
                f"{st.bytes / 1e6:.2f}MB, udf {st.udf_s:.3f}s"
            )
            if st.blocks:
                mean_rows = st.rows / st.blocks
                mean_bytes = st.bytes / st.blocks
                lines.append(
                    f"    block rows min/mean/max: {st.min_block_rows}/"
                    f"{mean_rows:.0f}/{st.max_block_rows}; bytes "
                    f"min/mean/max: {st.min_block_bytes}/"
                    f"{mean_bytes:.0f}/{st.max_block_bytes}"
                )
        return "\n".join(lines)


def _apply_fused(block: Block, fns: List[Callable[[Block], Block]]) -> Block:
    for fn in fns:
        block = fn(block)
    return block


class ActorStage:
    """Plan marker: run this transform on a pool of stateful actors
    (reference: ``data/_internal/execution/operators/actor_pool_map_operator
    .py`` — callable-class UDFs construct ONCE per actor and serve many
    blocks; per-task construction would pay model-load per block)."""

    def __init__(self, cls, ctor_args, ctor_kwargs, batch_size, batch_format,
                 fn_kwargs, concurrency, resources=None, num_cpus=None,
                 num_gpus=None):
        import cloudpickle

        self.payload = cloudpickle.dumps(
            (cls, tuple(ctor_args or ()), dict(ctor_kwargs or {}),
             batch_size, batch_format, dict(fn_kwargs or {}))
        )
        self.concurrency = max(int(concurrency), 1)
        self.resources = resources
        self.num_cpus = num_cpus
        self.num_gpus = num_gpus

    def build_local(self):
        """Local-mode transform: one instance, applied inline."""
        import cloudpickle

        cls, args, kwargs, bs, fmt, fkw = cloudpickle.loads(self.payload)
        inst = cls(*args, **kwargs)

        def apply(block: Block) -> Block:
            return _apply_batched(block, inst, bs, fmt, fkw)

        return apply


def _apply_batched(block: Block, fn, batch_size, batch_format, fn_kwargs):
    # One batching implementation for function AND actor stages.
    from ray_tpu.data.dataset import _map_batches_fn

    return _map_batches_fn(fn, batch_size, batch_format, fn_kwargs)(block)


class _BatchPoolWorker:
    """Actor body for ActorStage pools: the UDF instance lives for the
    actor's lifetime."""

    def __init__(self, payload):
        import cloudpickle

        cls, args, kwargs, bs, fmt, fkw = cloudpickle.loads(payload)
        self.fn = cls(*args, **kwargs)
        self.bs, self.fmt, self.fkw = bs, fmt, fkw

    def apply(self, block: Block) -> Block:
        return _apply_batched(block, self.fn, self.bs, self.fmt, self.fkw)

    def apply_meta(self, block: Block):
        """(block, meta) variant feeding per-operator stats."""
        import time as _time

        t0 = _time.monotonic()
        out = self.apply(block)
        return out, _block_meta(out, _time.monotonic() - t0)


def _remote_apply(serialized_fns, block: Block) -> Block:
    """Task body: run the fused transform chain on one block."""
    import cloudpickle

    fns = cloudpickle.loads(serialized_fns)
    return _apply_fused(block, fns)


def _block_meta(block: Block, udf_s: float) -> Dict[str, Any]:
    acc = BlockAccessor(block)
    return {"rows": acc.num_rows(), "bytes": acc.size_bytes(),
            "udf_s": udf_s}


def _remote_apply_meta(serialized_fns, block: Block):
    """Task body returning (block, meta): meta carries rows/bytes/udf-time
    so the driver's stats never have to fetch the (possibly large) block."""
    import time as _time

    import cloudpickle

    fns = cloudpickle.loads(serialized_fns)
    t0 = _time.monotonic()
    out = _apply_fused(block, fns)
    return out, _block_meta(out, _time.monotonic() - t0)


class StreamingExecutor:
    """Executes a fused stage over input block refs with bounded in-flight
    tasks; yields output block refs as they finish (streaming, not barrier).
    """

    def __init__(self, max_in_flight: int = 16, locality: bool = True):
        self.max_in_flight = max_in_flight
        self.stats = ExecStats()

    def execute(
        self,
        in_refs: List[Any],
        fns: List[Any],
        name: str = "map",
    ) -> Iterator[Any]:
        """in_refs: ObjectRefs of input blocks (or local Blocks when running
        without a cluster). ``fns`` may mix plain block transforms (fused
        into one task per block) and ActorStage markers (stateful pools);
        the whole chain streams — no barrier between sub-stages. Yields
        refs/blocks of transformed output."""
        import time

        t0 = time.monotonic()
        if not fns:
            yield from in_refs
            return
        from ray_tpu._private import worker as worker_mod

        local = worker_mod.global_worker is None
        # Split into alternating fused-fn groups and actor stages.
        groups: List[tuple] = []
        for fn in fns:
            if isinstance(fn, ActorStage):
                groups.append(("actor", fn))
            elif groups and groups[-1][0] == "fns":
                groups[-1][1].append(fn)
            else:
                groups.append(("fns", [fn]))
        st = self.stats.stage(name)
        stream: Iterator[Any] = iter(in_refs)
        for gi, (kind, payload) in enumerate(groups):
            # Only the FINAL group's outputs are the operator's outputs:
            # intermediate groups of a chained stage (fns -> actor pool)
            # must not inflate block/row accounting.
            final = gi == len(groups) - 1
            if kind == "fns":
                if local:
                    stream = self._fused_local(stream, payload, st, final)
                else:
                    stream = self._fused_tasks(stream, payload, st, final)
            else:
                if local:
                    stream = self._fused_local(
                        stream, [payload.build_local()], st, final
                    )
                else:
                    stream = self._actor_pool(stream, payload, st, final)
        for out in stream:
            self.stats.blocks_produced += 1
            yield out
        st.wall_s += time.monotonic() - t0
        self.stats.wall_time_s += time.monotonic() - t0

    def _fused_local(self, stream, fns, st: StageStats, final: bool = True):
        import time as _time

        for b in stream:
            t0 = _time.monotonic()
            out = _apply_fused(_resolve_local(b), fns)
            st.tasks += 1
            if final:
                meta = _block_meta(out, _time.monotonic() - t0)
                st.add_block(meta)
                self.stats.rows_produced += meta["rows"]
            yield out

    def _fused_tasks(self, stream, fns, st: StageStats,
                     final: bool = True):
        import cloudpickle

        import ray_tpu

        payload = cloudpickle.dumps(fns)
        # Per-transform execution options (Dataset.map(num_cpus=...,
        # resources=..., concurrency=...)): a fused group takes the max
        # CPU/GPU request, the union of custom resources, and the
        # tightest concurrency cap of its member transforms.
        num_cpus = num_gpus = None
        resources = {}
        in_flight = self.max_in_flight
        for fn in fns:
            o = getattr(fn, "_rt_opts", None) or {}
            if o.get("num_cpus") is not None:
                num_cpus = max(num_cpus or 0, o["num_cpus"])
            if o.get("num_gpus") is not None:
                num_gpus = max(num_gpus or 0, o["num_gpus"])
            for k, v in (o.get("resources") or {}).items():
                # per-key MAX (like num_cpus): the fused task runs EVERY
                # member transform, so it needs the largest request
                resources[k] = max(resources.get(k, 0), v)
            if o.get("concurrency"):
                in_flight = min(in_flight, o["concurrency"])
        task_opts = {}
        if num_cpus is not None:
            task_opts["num_cpus"] = num_cpus
        if num_gpus is not None:
            task_opts["num_gpus"] = num_gpus
        if resources:
            task_opts["resources"] = resources
        task_opts["num_returns"] = 2  # (block, meta) — stats without fetch
        apply_task = ray_tpu.remote(_remote_apply_meta).options(**task_opts)
        from ray_tpu.data.resource_manager import default_resource_manager

        rm = default_resource_manager()
        op = rm.register_op(
            "map", concurrency_cap=in_flight,
            cpu_per_task=num_cpus if num_cpus is not None else 1.0,
        )
        pending = collections.deque()
        meta_refs: List[Any] = []
        exhausted = False
        try:
            while pending or not exhausted:
                # The policy chain (per-op cap + reserved-CPU budget)
                # gates every submission: ingest never occupies more than
                # its share of the cluster even while a consumer lags.
                while not exhausted and rm.can_add_input(op):
                    try:
                        ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    block_ref, meta_ref = apply_task.remote(payload, ref)
                    pending.append(block_ref)
                    meta_refs.append(meta_ref)
                    rm.on_task_submitted(op)
                    self.stats.tasks_submitted += 1
                    st.tasks += 1
                if pending:
                    # Pop in order: preserves block order; completed later
                    # tasks simply wait in the store (streaming window
                    # gives overlap).
                    yield pending.popleft()
                    rm.on_task_output_consumed(op)
        finally:
            rm.unregister_op(op)
            # Collect per-block metadata (tiny messages; every consumed
            # block's task has finished, so these resolve immediately).
            # Bounded wait: an early-abandoned stream (take(5)) must not
            # hang the generator close on still-running stragglers.
            ready = []
            if meta_refs and final:
                try:
                    ready, _ = ray_tpu.wait(
                        meta_refs, num_returns=len(meta_refs), timeout=10,
                    )
                except Exception:
                    pass
            for mr in ready:
                try:
                    meta = ray_tpu.get(mr, timeout=5)
                    st.add_block(meta)
                    self.stats.rows_produced += meta["rows"]
                except Exception:
                    pass

    def _actor_pool(self, stream, stage: ActorStage, st: StageStats,
                    final: bool = True):
        """Bounded-in-flight round-robin over a pool of stateful actors;
        the pool dies with the stage (reference: actor_pool_map_operator
        autoscaling pool — fixed size here)."""
        import ray_tpu

        opts = {}
        if stage.resources:
            opts["resources"] = stage.resources
        if stage.num_cpus is not None:
            opts["num_cpus"] = stage.num_cpus
        if stage.num_gpus is not None:
            opts["num_gpus"] = stage.num_gpus
        worker_cls = ray_tpu.remote(**opts)(_BatchPoolWorker) if opts else (
            ray_tpu.remote(_BatchPoolWorker)
        )
        actors = [
            worker_cls.remote(stage.payload)
            for _ in range(stage.concurrency)
        ]
        produced: List[Any] = []
        meta_refs: List[Any] = []
        try:
            pending = collections.deque()
            exhausted = False
            i = 0
            window = max(2 * stage.concurrency, 2)
            while pending or not exhausted:
                while not exhausted and len(pending) < window:
                    try:
                        ref = next(stream)
                    except StopIteration:
                        exhausted = True
                        break
                    actor = actors[i % len(actors)]
                    i += 1
                    block_ref, meta_ref = actor.apply_meta.options(
                        num_returns=2
                    ).remote(ref)
                    pending.append(block_ref)
                    meta_refs.append(meta_ref)
                    self.stats.tasks_submitted += 1
                    st.tasks += 1
                if pending:
                    out = pending.popleft()
                    produced.append(out)
                    yield out
        finally:
            # A consumer may hold yielded refs unresolved (e.g. list() then
            # resolve later): wait for every produced task BEFORE killing
            # the pool, or the kill cancels their in-flight execution.
            try:
                if produced:
                    ray_tpu.wait(
                        produced, num_returns=len(produced), timeout=300,
                        fetch_local=False,
                    )
            except Exception:
                pass
            ready = []
            if meta_refs and final:
                try:
                    ready, _ = ray_tpu.wait(
                        meta_refs, num_returns=len(meta_refs), timeout=10,
                    )
                except Exception:
                    pass
            for mr in ready:
                try:
                    meta = ray_tpu.get(mr, timeout=5)
                    st.add_block(meta)
                    self.stats.rows_produced += meta["rows"]
                except Exception:
                    pass
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass


def _resolve_local(b):
    return b


def resolve_block(ref) -> Block:
    """Ref-or-block → block."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.object_ref import ObjectRef

    if isinstance(ref, ObjectRef):
        import ray_tpu

        return ray_tpu.get(ref)
    return ref


def put_block(block: Block):
    from ray_tpu._private import worker as worker_mod

    if worker_mod.global_worker is None:
        return block
    import ray_tpu

    return ray_tpu.put(block)
