"""Blocks: the unit of data movement (reference: ``python/ray/data/block.py``
+ ``_internal/arrow_block.py``).

A block is a ``pyarrow.Table``. ``BlockAccessor`` wraps one with the
operations the executor and iterators need. Batches cross into user code as
dicts of numpy arrays (the natural jax feed format), pandas, or arrow.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table


def _to_table(data: Any) -> pa.Table:
    """Coerce rows/batches/frames into an arrow table."""
    import pandas as pd

    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pd.DataFrame):
        return pa.Table.from_pandas(data, preserve_index=False)
    if isinstance(data, dict):  # dict of columns (numpy arrays or lists)
        import json

        arrays, fields = [], []
        for k, v in data.items():
            arr = np.asarray(v)
            if arr.ndim > 1:  # tensor column → fixed-shape list array; the
                # full inner shape rides in field metadata so >2-D tensors
                # round-trip exactly (not silently flattened to 2-D)
                inner = int(np.prod(arr.shape[1:]))  # safe for 0-row arrays
                fsl = pa.FixedSizeListArray.from_arrays(
                    pa.array(arr.reshape(-1)), inner
                )
                arrays.append(fsl)
                fields.append(pa.field(
                    k, fsl.type,
                    metadata={b"tensor_shape": json.dumps(
                        list(arr.shape[1:])).encode()},
                ))
            else:
                a = pa.array(arr)
                arrays.append(a)
                fields.append(pa.field(k, a.type))
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    if isinstance(data, list):  # list of rows
        if data and isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"cannot convert {type(data)} to a block")


def _column_to_numpy(table: pa.Table, name: str) -> np.ndarray:
    import json

    col = table.column(name)
    if pa.types.is_fixed_size_list(col.type):
        flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
        field = table.schema.field(name)
        meta = field.metadata or {}
        if b"tensor_shape" in meta:
            shape = json.loads(meta[b"tensor_shape"].decode())
            return flat.reshape((len(table), *shape))
        return flat.reshape(len(table), -1)
    return col.to_numpy(zero_copy_only=False)


class BlockAccessor:
    def __init__(self, block: Block):
        self._table = _to_table(block)

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(block)

    @property
    def table(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        names = columns or self._table.column_names
        return {n: _column_to_numpy(self._table, n) for n in names}

    def to_pylist(self) -> List[dict]:
        return self._table.to_pylist()

    def iter_rows(self) -> Iterator[dict]:
        for batch in self._table.to_batches():
            yield from batch.to_pylist()

    def batch(self, start: int, end: int, batch_format: str = "numpy"):
        sub = self.slice(start, end)
        if batch_format in ("numpy", "default"):
            return BlockAccessor(sub).to_numpy()
        if batch_format == "pandas":
            return sub.to_pandas()
        if batch_format in ("arrow", "pyarrow"):
            return sub
        raise ValueError(f"unknown batch_format {batch_format}")

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        tables = [_to_table(b) for b in blocks if b is not None]
        tables = [t for t in tables if t.num_rows > 0] or tables[:1]
        if not tables:
            return pa.table({})
        return pa.concat_tables(tables, promote_options="default")


def batch_to_block(batch: Any) -> Block:
    """User map_batches output → block (accepts dict/pandas/arrow/list)."""
    return _to_table(batch)
