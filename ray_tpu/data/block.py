"""Blocks: the unit of data movement (reference: ``python/ray/data/block.py``
+ ``_internal/arrow_block.py``).

A block is a ``pyarrow.Table``. ``BlockAccessor`` wraps one with the
operations the executor and iterators need. Batches cross into user code as
dicts of numpy arrays (the natural jax feed format), pandas, or arrow.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table


def _to_table(data: Any) -> pa.Table:
    """Coerce rows/batches/frames into an arrow table."""
    import pandas as pd

    if isinstance(data, pa.Table):
        return data
    if isinstance(data, pd.DataFrame):
        return pa.Table.from_pandas(data, preserve_index=False)
    if isinstance(data, dict):  # dict of columns (numpy arrays or lists)
        from ray_tpu.data.tensor_extension import (
            ArrowTensorArray,
            ArrowVariableShapedTensorArray,
        )

        arrays, fields = [], []
        for k, v in data.items():
            if (isinstance(v, (list, tuple)) and v
                    and all(isinstance(a, np.ndarray) for a in v)
                    and len({a.shape for a in v}) > 1):
                # ragged tensor column (per-row shapes differ)
                a = ArrowVariableShapedTensorArray.from_numpy(v)
            else:
                arr = np.asarray(v)
                if arr.dtype == object and arr.ndim == 1 and len(arr) and \
                        isinstance(arr[0], np.ndarray):
                    a = ArrowVariableShapedTensorArray.from_numpy(list(arr))
                elif arr.ndim > 1:
                    # tensor column → arrow extension type: shape+dtype are
                    # part of the TYPE, so they survive schema ops, IPC,
                    # and parquet (reference: air ArrowTensorType)
                    a = ArrowTensorArray.from_numpy(arr)
                else:
                    a = pa.array(arr)
            arrays.append(a)
            fields.append(pa.field(k, a.type))
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))
    if isinstance(data, list):  # list of rows
        if data and isinstance(data[0], dict):
            return pa.Table.from_pylist(data)
        return pa.table({"item": pa.array(data)})
    raise TypeError(f"cannot convert {type(data)} to a block")


def _column_to_numpy(table: pa.Table, name: str) -> np.ndarray:
    import json

    from ray_tpu.data.tensor_extension import (
        ArrowTensorType,
        ArrowVariableShapedTensorType,
    )

    col = table.column(name)
    if isinstance(col.type, (ArrowTensorType, ArrowVariableShapedTensorType)):
        chunk = col.combine_chunks()
        if isinstance(chunk, pa.ChunkedArray):  # 0- or multi-chunk fallback
            parts = [c.to_numpy() for c in chunk.chunks]
            if not parts:
                return np.empty(
                    (0, *getattr(col.type, "shape", ())), np.float64
                )
            return np.concatenate(parts)
        return chunk.to_numpy()
    if pa.types.is_fixed_size_list(col.type):
        # legacy blocks (pre-extension-type) carried shape in field metadata
        flat = col.combine_chunks().flatten().to_numpy(zero_copy_only=False)
        field = table.schema.field(name)
        meta = field.metadata or {}
        if b"tensor_shape" in meta:
            shape = json.loads(meta[b"tensor_shape"].decode())
            return flat.reshape((len(table), *shape))
        return flat.reshape(len(table), -1)
    return col.to_numpy(zero_copy_only=False)


class BlockAccessor:
    def __init__(self, block: Block):
        self._table = _to_table(block)

    @staticmethod
    def for_block(block: Any) -> "BlockAccessor":
        return BlockAccessor(block)

    @property
    def table(self) -> pa.Table:
        return self._table

    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
        names = columns or self._table.column_names
        return {n: _column_to_numpy(self._table, n) for n in names}

    def _tensor_columns(self) -> List[str]:
        from ray_tpu.data.tensor_extension import (
            ArrowTensorType,
            ArrowVariableShapedTensorType,
        )

        return [
            f.name for f in self._table.schema
            if isinstance(
                f.type, (ArrowTensorType, ArrowVariableShapedTensorType)
            )
        ]

    def to_pylist(self) -> List[dict]:
        tensor_cols = self._tensor_columns()
        rows = self._table.to_pylist()
        if tensor_cols:
            # rows must carry ndarrays for tensor columns, not the storage
            # array's flattened lists
            for name in tensor_cols:
                col = _column_to_numpy(self._table, name)
                for i, row in enumerate(rows):
                    row[name] = col[i]
        return rows

    def iter_rows(self) -> Iterator[dict]:
        if self._tensor_columns():
            yield from self.to_pylist()
            return
        for batch in self._table.to_batches():
            yield from batch.to_pylist()

    def batch(self, start: int, end: int, batch_format: str = "numpy"):
        sub = self.slice(start, end)
        if batch_format in ("numpy", "default"):
            return BlockAccessor(sub).to_numpy()
        if batch_format == "pandas":
            return sub.to_pandas()
        if batch_format in ("arrow", "pyarrow"):
            return sub
        raise ValueError(f"unknown batch_format {batch_format}")

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        tables = [_to_table(b) for b in blocks if b is not None]
        tables = [t for t in tables if t.num_rows > 0] or tables[:1]
        if not tables:
            return pa.table({})
        return pa.concat_tables(tables, promote_options="default")


def batch_to_block(batch: Any) -> Block:
    """User map_batches output → block (accepts dict/pandas/arrow/list)."""
    return _to_table(batch)
