"""Job submission SDK.

Reference analog: ``python/ray/job_submission/`` +
``dashboard/modules/job/sdk.py:132 submit_job`` — REST+SDK job lifecycle
(submit/status/logs/stop). Transport here is the head's RPC protocol
directly (the dashboard-lite HTTP app exposes the same surface over REST).
"""
from __future__ import annotations

import time
from enum import Enum
from typing import Any, Dict, Optional

from ray_tpu._private.backoff import Backoff


class JobStatus(str, Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    def is_terminal(self) -> bool:
        return self in (
            JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED
        )


class JobSubmissionClient:
    def __init__(self, address: str):
        from ray_tpu._private.sync_client import SyncHeadClient

        self._client = SyncHeadClient(address)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[Dict[str, Any]] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        h, _ = self._client.call("submit_job", {
            "entrypoint": entrypoint,
            "submission_id": submission_id,
            "runtime_env": runtime_env,
            "metadata": metadata,
        })
        return h["submission_id"]

    def get_job_status(self, submission_id: str) -> JobStatus:
        h, _ = self._client.call("job_status", {"submission_id": submission_id})
        if not h.get("found"):
            raise RuntimeError(f"job {submission_id} not found")
        return JobStatus(h["job"]["status"])

    def get_job_info(self, submission_id: str) -> dict:
        h, _ = self._client.call("job_status", {"submission_id": submission_id})
        if not h.get("found"):
            raise RuntimeError(f"job {submission_id} not found")
        return h["job"]

    def get_job_logs(self, submission_id: str) -> str:
        h, frames = self._client.call(
            "job_logs", {"submission_id": submission_id}
        )
        if not h.get("found"):
            raise RuntimeError(f"job {submission_id} not found")
        return bytes(frames[0]).decode(errors="replace") if frames else ""

    def stop_job(self, submission_id: str) -> bool:
        h, _ = self._client.call("stop_job", {"submission_id": submission_id})
        return h.get("stopped", False)

    def list_jobs(self) -> list:
        h, _ = self._client.call("list_jobs", {})
        return h["jobs"]

    def wait_until_status(self, submission_id: str, timeout: float = 120.0,
                          target: Optional[JobStatus] = None) -> JobStatus:
        deadline = time.monotonic() + timeout
        poll = Backoff(base=0.1, cap=1.0)
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if (target is not None and status == target) or (
                target is None and status.is_terminal()
            ):
                return status
            poll.sleep()
        raise TimeoutError(
            f"job {submission_id} not terminal within {timeout}s"
        )

    def close(self):
        self._client.close()
