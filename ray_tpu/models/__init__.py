"""Model zoo: functional JAX model families sharing one interface.

Each model module exposes: a frozen ``*Config`` dataclass, ``PRESETS``,
``init_params``, ``param_axes``, ``forward``, ``forward_cached``,
``init_kv_cache``, ``loss_fn``, ``count_params``, ``flops_per_token`` (and
optionally ``forward_pipelined``). Train/LLM layers dispatch on the config
type via :func:`module_for` — adding a family means adding a module here.
"""
from __future__ import annotations

from typing import Any


def module_for(config: Any):
    """Return the model module that owns this config object."""
    from ray_tpu.models import gpt2, llama

    if isinstance(config, llama.LlamaConfig):
        return llama
    if isinstance(config, gpt2.GPT2Config):
        return gpt2
    raise TypeError(f"unknown model config type: {type(config).__name__}")


def get_preset(name: str):
    """Look up a preset config by name across all families."""
    from ray_tpu.models import gpt2, llama

    for mod in (gpt2, llama):
        if name in mod.PRESETS:
            return mod.PRESETS[name]
    known = sorted(
        list(gpt2.PRESETS) + list(llama.PRESETS)
    )
    raise KeyError(f"unknown model preset {name!r}; known: {known}")
