"""GPT-2 in pure functional JAX: the flagship train/serve model.

Matches the architecture the reference benchmarks with torch ("Ray Train
GPT-2 tokens/sec/chip", BASELINE.md north star): learned positional
embeddings, pre-LN transformer blocks, GELU MLP, weight-tied LM head.
TPU-first choices:

- Params are a plain pytree with a parallel *logical axis* tree
  (``param_axes``) consumed by ``ray_tpu.parallel.sharding`` — pjit shards
  params (fsdp/tensor), XLA inserts the collectives.
- Layers are stacked into one scanned super-layer (``lax.scan`` over the
  depth dimension): O(1) compile time in depth and the natural layout for
  pipeline parallelism (the "stage" mesh axis splits the stacked dim).
- ``jax.checkpoint`` on the block body: remat trades FLOPs for HBM.
- Attention pluggable: xla | flash (pallas) | ring (seq-parallel) | ulysses.
- bfloat16 activations, f32 params + optimizer (standard mixed precision).
"""
from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.moe import (
    MoEConfig,
    init_moe_params,
    moe_layer,
    moe_param_axes,
)


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304          # padded to a multiple of 128 for the MXU
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    embed_dim: int = 768
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16        # activation dtype
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"     # auto | xla | flash | flash_interpret | ring | ulysses
    remat: bool = True
    # "dots": save matmul outputs, recompute elementwise (cheap recompute,
    # moderate memory — the right default below memory pressure). "full":
    # save only block boundaries (max memory savings, ~1 extra forward).
    remat_policy: str = "dots"
    seq_axis: str = "seq"            # mesh axis for ring/ulysses
    moe: Optional[MoEConfig] = None  # replace MLPs with MoE when set (EP)

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def mlp_dim(self) -> int:
        return self.embed_dim * self.mlp_ratio


# Model zoo sizes (OpenAI GPT-2 family).
GPT2_SMALL = GPT2Config(num_layers=12, num_heads=12, embed_dim=768)
GPT2_MEDIUM = GPT2Config(num_layers=24, num_heads=16, embed_dim=1024)
GPT2_LARGE = GPT2Config(num_layers=36, num_heads=20, embed_dim=1280)
GPT2_XL = GPT2Config(num_layers=48, num_heads=25, embed_dim=1600)
GPT2_TINY = GPT2Config(  # test size
    vocab_size=512, max_seq_len=128, num_layers=2, num_heads=2, embed_dim=64
)

PRESETS = {
    "gpt2-tiny": GPT2_TINY,
    "gpt2-small": GPT2_SMALL,
    "gpt2-medium": GPT2_MEDIUM,
    "gpt2-large": GPT2_LARGE,
    "gpt2-xl": GPT2_XL,
}


def init_params(config: GPT2Config, key: jax.Array) -> Dict[str, Any]:
    """Initialize parameters. Block params carry a leading [num_layers] dim
    (scanned / stage-shardable)."""
    k = jax.random.split(key, 10)
    E, H, M, V, L = (
        config.embed_dim,
        config.num_heads,
        config.mlp_dim,
        config.vocab_size,
        config.num_layers,
    )
    D = config.head_dim
    pd = config.param_dtype
    std = 0.02

    def normal(key, shape, s=std):
        return (jax.random.normal(key, shape) * s).astype(pd)

    # residual-scaled init for output projections (GPT-2 paper)
    res_std = std / (2 * L) ** 0.5
    params = {
        "wte": normal(k[0], (V, E)),
        "wpe": normal(k[1], (config.max_seq_len, E), 0.01),
        "blocks": {
            "ln1_g": jnp.ones((L, E), pd),
            "ln1_b": jnp.zeros((L, E), pd),
            "qkv_w": normal(k[2], (L, E, 3, H, D)),
            "qkv_b": jnp.zeros((L, 3, H, D), pd),
            "proj_w": normal(k[3], (L, H, D, E), res_std),
            "proj_b": jnp.zeros((L, E), pd),
            "ln2_g": jnp.ones((L, E), pd),
            "ln2_b": jnp.zeros((L, E), pd),
            "fc_w": normal(k[4], (L, E, M)),
            "fc_b": jnp.zeros((L, M), pd),
            "out_w": normal(k[5], (L, M, E), res_std),
            "out_b": jnp.zeros((L, E), pd),
        },
        "ln_f_g": jnp.ones((E,), pd),
        "ln_f_b": jnp.zeros((E,), pd),
    }
    if config.moe is not None:
        params["blocks"]["moe"] = init_moe_params(
            k[6], E, M, config.moe, pd, num_layers=L
        )
    return params


def param_axes(config: GPT2Config) -> Dict[str, Any]:
    """Logical axis names per parameter (see sharding.DEFAULT_RULES)."""
    axes = {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_g": ("stage", "norm"),
            "ln1_b": ("stage", "norm"),
            "qkv_w": ("stage", "embed", None, "heads", "head_dim"),
            "qkv_b": ("stage", None, "heads", "head_dim"),
            "proj_w": ("stage", "heads", "head_dim", "embed"),
            "proj_b": ("stage", "norm"),
            "ln2_g": ("stage", "norm"),
            "ln2_b": ("stage", "norm"),
            "fc_w": ("stage", "embed", "mlp"),
            "fc_b": ("stage", "mlp"),
            "out_w": ("stage", "mlp", "embed"),
            "out_b": ("stage", "norm"),
        },
        "ln_f_g": ("norm",),
        "ln_f_b": ("norm",),
    }
    if config.moe is not None:
        axes["blocks"]["moe"] = moe_param_axes(
            num_layers=config.num_layers, config=config.moe
        )
    return axes


def _remat_policy(config):
    """Checkpoint policy for the block body. "full" recomputes everything;
    "dots" (default) keeps matmul outputs + the flash-attention forward's
    named residuals (out + logsumexp, so the backward never re-runs the
    attention kernel) and recomputes elementwise ops; "dots_all"
    additionally keeps batched dots — least recompute short of remat=False,
    for chips with HBM headroom."""
    policy = getattr(config, "remat_policy", "dots")
    if policy == "full":
        return None
    base = (
        jax.checkpoint_policies.dots_saveable
        if policy == "dots_all"
        else jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    )
    return jax.checkpoint_policies.save_from_both_policies(
        base,
        jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        ),
    )


def _layer_norm(x, g, b, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * g + b).astype(x.dtype)


def _attention_dispatch(config: GPT2Config, q, k, v, mesh: Optional[Mesh]):
    """Adds the mesh-aware ring/ulysses branches on top of the shared
    single-device dispatcher (``ops.attention.attention``)."""
    impl = config.attention_impl
    if impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh=mesh, axis=config.seq_axis, causal=True)
    if impl == "ulysses":
        from ray_tpu.parallel.ring_attention import ulysses_attention

        return ulysses_attention(q, k, v, mesh=mesh, axis=config.seq_axis, causal=True)
    return attention(q, k, v, causal=True, impl=impl)


def _qkv(layer, h):
    """[B, T, E] → (q, k, v) each [B, T, H, D]."""
    qkv = jnp.einsum("bte,eshd->btshd", h, layer["qkv_w"].astype(h.dtype))
    qkv = qkv + layer["qkv_b"].astype(h.dtype)
    return qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]


def _attn_residual(layer, x, attn):
    """Output projection + residual add."""
    attn = jnp.einsum("bthd,hde->bte", attn, layer["proj_w"].astype(x.dtype))
    return x + attn + layer["proj_b"].astype(x.dtype)


def _mlp_residual(config: GPT2Config, layer, x, rng=None):
    """ln2 + MLP (or MoE) + residual. Returns (x, aux_loss)."""
    h = _layer_norm(x, layer["ln2_g"], layer["ln2_b"])
    if config.moe is not None:
        h, aux = moe_layer(layer["moe"], h, config.moe, rng=rng)
        return x + h, aux
    h = jnp.einsum("bte,em->btm", h, layer["fc_w"].astype(h.dtype))
    h = jax.nn.gelu(h + layer["fc_b"].astype(h.dtype))
    h = jnp.einsum("btm,me->bte", h, layer["out_w"].astype(h.dtype))
    return x + h + layer["out_b"].astype(h.dtype), jnp.float32(0.0)


def _block(config: GPT2Config, mesh: Optional[Mesh], x, layer, rng=None):
    """One transformer block. x: [B, T, E] (dtype), layer: one slice of the
    stacked block params. ``rng`` (optional) feeds MoE router jitter."""
    h = _layer_norm(x, layer["ln1_g"], layer["ln1_b"])
    q, k, v = _qkv(layer, h)
    attn = _attention_dispatch(config, q, k, v, mesh)
    x = _attn_residual(layer, x, attn)
    return _mlp_residual(config, layer, x, rng=rng)


def forward_features(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: GPT2Config,
    mesh: Optional[Mesh] = None,
    rng: Optional[jax.Array] = None,
) -> tuple:
    """tokens [B, T] int32 → (final-trunk features [B, T, E], aux loss).
    The loss path consumes features directly (vocab-chunked cross entropy,
    ``ops/xent.py``) so the [B, T, V] logits tensor never materializes.
    ``rng``: optional key enabling stochastic layers (MoE router jitter)."""
    B, T = tokens.shape
    x = params["wte"][tokens].astype(config.dtype)
    x = x + params["wpe"][:T][None].astype(config.dtype)

    body = functools.partial(_block, config, mesh)
    if config.remat:
        body = jax.checkpoint(body, policy=_remat_policy(config))

    if rng is not None:
        layer_rngs = jax.random.split(rng, config.num_layers)

        def scan_fn(carry, xs):
            layer, lrng = xs
            x, aux = carry
            x, layer_aux = body(x, layer, lrng)
            return (x, aux + layer_aux), None

        (x, aux), _ = jax.lax.scan(
            scan_fn, (x, jnp.float32(0.0)), (params["blocks"], layer_rngs)
        )
    else:

        def scan_fn(carry, layer):
            x, aux = carry
            x, layer_aux = body(x, layer)
            return (x, aux + layer_aux), None

        (x, aux), _ = jax.lax.scan(
            scan_fn, (x, jnp.float32(0.0)), params["blocks"]
        )
    x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
    return x, aux


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: GPT2Config,
    mesh: Optional[Mesh] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens [B, T] int32 → (logits [B, T, V] f32, moe aux loss scalar)."""
    x, aux = forward_features(params, tokens, config, mesh, rng=rng)
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(x.dtype))
    return logits.astype(jnp.float32), aux


def init_kv_cache(config: GPT2Config, batch: int, max_len: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Static-shape KV cache for incremental decoding: [L, B, S, H, D].
    (Reference capability analog: the vLLM engine Ray LLM delegates to —
    ``llm/_internal/serve/engines/vllm``; here the cache is a jax pytree so
    the whole decode step stays one XLA program.)"""
    dtype = dtype or config.dtype
    L, H, D = config.num_layers, config.num_heads, config.head_dim
    shape = (L, batch, max_len, H, D)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def forward_cached(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: Dict[str, jax.Array],
    start: jax.Array,
    config: GPT2Config,
) -> tuple:
    """Incremental forward: attend over the KV cache, append new K/V.

    tokens [B, T] — a prompt chunk (prefill, start=0) or one decode step
    (T=1, start=seq_len). start [B] int32: absolute position of tokens[:, 0]
    per sequence. Returns (logits [B, T, V] f32, updated cache). All shapes
    static; per-sequence offsets go through vmapped dynamic_update_slice so
    slot-based continuous batching is one compiled program.
    """
    B, T = tokens.shape
    S = cache["k"].shape[2]
    pos = start[:, None] + jnp.arange(T)[None, :]          # [B, T] absolute
    x = params["wte"][tokens].astype(config.dtype)
    x = x + params["wpe"][pos].astype(config.dtype)

    key_pos = jnp.arange(S)[None, None, :]                  # [1, 1, S]
    # causal vs cache: key visible iff key_pos <= query absolute position
    mask = key_pos <= pos[:, :, None]                       # [B, T, S]

    def block(carry, layer_and_cache):
        x = carry
        layer, ck, cv = layer_and_cache
        h = _layer_norm(x, layer["ln1_g"], layer["ln1_b"])
        q, k_new, v_new = _qkv(layer, h)
        upd = jax.vmap(
            lambda c, n, s: jax.lax.dynamic_update_slice(c, n, (s, 0, 0))
        )
        ck = upd(ck, k_new.astype(ck.dtype), start)         # [B, S, H, D]
        cv = upd(cv, v_new.astype(cv.dtype), start)
        # attention core differs from _block: queries attend the cache
        scores = jnp.einsum("bthd,bshd->bhts", q, ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))
        scores = jnp.where(mask[:, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bhts,bshd->bthd", probs, cv)
        x = _attn_residual(layer, x, attn)
        x, _ = _mlp_residual(config, layer, x)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(x.dtype))
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    config: GPT2Config,
    mesh: Optional[Mesh] = None,
    pipeline_microbatches: Optional[int] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token cross entropy. batch: {"tokens": [B, T+1]} or
    {"inputs": [B,T], "targets": [B,T]}. ``rng`` feeds MoE router jitter
    (unpipelined path only)."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if pipeline_microbatches:
        logits, aux = forward_pipelined(
            params, inputs, config, mesh, pipeline_microbatches
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        mask = batch.get("mask")
        if mask is None:
            return -ll.mean() + aux
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1) + aux
    from ray_tpu.ops.xent import chunked_softmax_xent

    x, aux = forward_features(params, inputs, config, mesh, rng=rng)
    return chunked_softmax_xent(
        x, params["wte"], targets, batch.get("mask")
    ) + aux


def count_params(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


def flops_per_token(config: GPT2Config) -> float:
    """~6N FLOPs/token for training (fwd+bwd), N = non-embedding params."""
    L, E, M = config.num_layers, config.embed_dim, config.mlp_dim
    n = L * (4 * E * E + 2 * E * M) + config.vocab_size * E
    return 6.0 * n


def forward_pipelined(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: GPT2Config,
    mesh: Mesh,
    num_microbatches: int = 4,
) -> jax.Array:
    """Pipeline-parallel forward: blocks run under the GPipe microbatch loop
    (``parallel.pipeline.pipeline_apply``) over the "stage" mesh axis;
    embedding/head run outside the pipe. MoE models accumulate the router's
    load-balancing aux loss across the microbatch loop
    (``pipeline_apply(collect_aux=True)``)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.pipeline import pipeline_apply

    B, T = tokens.shape
    x = params["wte"][tokens].astype(config.dtype)
    x = x + params["wpe"][:T][None].astype(config.dtype)

    body = functools.partial(_block, config, mesh)
    if config.remat:
        body = jax.checkpoint(body, policy=_remat_policy(config))
    collect_aux = config.moe is not None

    def apply_stage(local_blocks, mb):
        def scan_fn(carry, layer):
            x, aux = carry
            y, a = body(x, layer)
            return (y, aux + a.astype(jnp.float32)), None

        (out, aux), _ = jax.lax.scan(
            scan_fn, (mb, jnp.float32(0.0)), local_blocks
        )
        return (out, aux) if collect_aux else out

    # Manual spec covers only the stage dim; tensor/fsdp dims of the weights
    # remain auto-sharded by XLA inside the stage program.
    params_spec = jax.tree.map(lambda _: P("stage"), params["blocks"])
    res = pipeline_apply(
        params["blocks"],
        x,
        mesh=mesh,
        apply_stage=apply_stage,
        num_microbatches=num_microbatches,
        params_spec=params_spec,
        x_spec=P(),
        collect_aux=collect_aux,
    )
    x, aux = res if collect_aux else (res, jnp.float32(0.0))
    x = _layer_norm(x, params["ln_f_g"], params["ln_f_b"])
    logits = jnp.einsum("bte,ve->btv", x, params["wte"].astype(x.dtype))
    return logits.astype(jnp.float32), aux
