"""Llama-family decoder in pure functional JAX: second flagship model.

Covers the architecture family the reference serves through its LLM layer
(vLLM engine passthrough, ``python/ray/llm/_internal/serve/engines/vllm/``;
the reference ships no model code of its own): RMSNorm, rotary position
embeddings (RoPE), SwiGLU MLP, grouped-query attention (GQA), untied LM
head. Same TPU-first skeleton as :mod:`ray_tpu.models.gpt2`:

- plain-pytree params with a parallel logical-axis tree for pjit sharding
- one scanned super-layer (``lax.scan`` over depth), remat on the body
- pluggable attention (xla | flash pallas | ring | ulysses)
- bfloat16 activations over f32 params
- static-shape KV cache (GQA-sized: kv heads, not query heads) for the
  slot-based continuous-batching decode engine
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.ops.attention import attention
from ray_tpu.parallel.moe import (
    MoEConfig,
    init_moe_params,
    moe_layer,
    moe_param_axes,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 2048
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 4            # GQA: kv heads < query heads
    embed_dim: int = 1024
    mlp_dim: Optional[int] = None    # default: 8/3 * E rounded to 128
    rope_theta: float = 10000.0      # 500000.0 for llama-3-style long context
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "auto"     # auto | xla | flash | ring | ulysses
    remat: bool = True
    # "dots": save matmul outputs, recompute elementwise; "full": save only
    # block boundaries (max memory savings, ~1 extra forward of FLOPs).
    remat_policy: str = "dots"
    seq_axis: str = "seq"
    # Mixtral-style MoE: replaces the SwiGLU MLP with routed experts (use
    # MoEConfig(activation="swiglu") for the Mixtral shape).
    moe: Optional[MoEConfig] = None

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def hidden_dim(self) -> int:
        if self.mlp_dim is not None:
            return self.mlp_dim
        h = int(self.embed_dim * 8 / 3)
        return (h + 127) // 128 * 128

    @property
    def q_per_kv(self) -> int:
        return self.num_heads // self.num_kv_heads


LLAMA_TINY = LlamaConfig(  # test size
    vocab_size=512, max_seq_len=128, num_layers=2, num_heads=4,
    num_kv_heads=2, embed_dim=64,
)
LLAMA_160M = LlamaConfig(
    num_layers=12, num_heads=12, num_kv_heads=4, embed_dim=768,
    vocab_size=32000,
)
LLAMA_1B = LlamaConfig(
    num_layers=16, num_heads=32, num_kv_heads=8, embed_dim=2048,
    max_seq_len=4096, rope_theta=500000.0,
)
LLAMA_8B = LlamaConfig(
    num_layers=32, num_heads=32, num_kv_heads=8, embed_dim=4096,
    mlp_dim=14336, max_seq_len=8192, vocab_size=128256, rope_theta=500000.0,
)

PRESETS = {
    "llama-tiny": LLAMA_TINY,
    "llama-160m": LLAMA_160M,
    "llama-1b": LLAMA_1B,
    "llama-8b": LLAMA_8B,
}


def init_params(config: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Block params carry a leading [num_layers] dim (scanned)."""
    k = jax.random.split(key, 9)
    E, H, KV, M, V, L, D = (
        config.embed_dim, config.num_heads, config.num_kv_heads,
        config.hidden_dim, config.vocab_size, config.num_layers,
        config.head_dim,
    )
    pd = config.param_dtype
    std = 0.02
    res_std = std / (2 * L) ** 0.5

    def normal(key, shape, s=std):
        return (jax.random.normal(key, shape) * s).astype(pd)

    blocks = {
        "attn_norm": jnp.ones((L, E), pd),
        "wq": normal(k[1], (L, E, H, D)),
        "wk": normal(k[2], (L, E, KV, D)),
        "wv": normal(k[3], (L, E, KV, D)),
        "wo": normal(k[4], (L, H, D, E), res_std),
        "mlp_norm": jnp.ones((L, E), pd),
    }
    if config.moe is not None:
        # routed experts replace the dense FFN (never materialize both)
        blocks["moe"] = init_moe_params(
            k[5], E, M, config.moe, pd, num_layers=L
        )
    else:
        blocks["w_gate"] = normal(k[5], (L, E, M))
        blocks["w_up"] = normal(k[6], (L, E, M))
        blocks["w_down"] = normal(k[7], (L, M, E), res_std)
    return {
        "wte": normal(k[0], (V, E)),
        "blocks": blocks,
        "norm_f": jnp.ones((E,), pd),
        "lm_head": normal(k[8], (V, E)),
    }


def param_axes(config: LlamaConfig) -> Dict[str, Any]:
    """Logical axis names per parameter (see sharding.DEFAULT_RULES).
    kv-head dims use the "kv" axis (replicated by default — GQA kv heads
    often don't divide the tensor axis; override rules to shard them)."""
    axes = {
        "wte": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("stage", "norm"),
            "wq": ("stage", "embed", "heads", "head_dim"),
            "wk": ("stage", "embed", "kv", "head_dim"),
            "wv": ("stage", "embed", "kv", "head_dim"),
            "wo": ("stage", "heads", "head_dim", "embed"),
            "mlp_norm": ("stage", "norm"),
            "w_gate": ("stage", "embed", "mlp"),
            "w_up": ("stage", "embed", "mlp"),
            "w_down": ("stage", "mlp", "embed"),
        },
        "norm_f": ("norm",),
        "lm_head": ("vocab", "embed"),
    }
    if config.moe is not None:
        for name in ("w_gate", "w_up", "w_down"):
            del axes["blocks"][name]
        axes["blocks"]["moe"] = moe_param_axes(
            num_layers=config.num_layers, config=config.moe
        )
    return axes


def _remat_policy(config):
    """See gpt2._remat_policy: "dots" saves matmul outputs, "full" saves
    only block boundaries."""
    if getattr(config, "remat_policy", "dots") == "full":
        return None
    return jax.checkpoint_policies.dots_with_no_batch_dims_saveable


def _rms_norm(x, g, eps):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt((x32 * x32).mean(-1, keepdims=True) + eps)
    return (x32 * scale * g).astype(x.dtype)


def _rope(x: jax.Array, pos: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [B, T, H, D], pos: [B, T] absolute positions."""
    D = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, D // 2, dtype=jnp.float32) / (D // 2))
    angles = pos[..., None].astype(jnp.float32) * freqs      # [B, T, D/2]
    cos = jnp.cos(angles)[:, :, None, :].astype(x.dtype)     # [B, T, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., : D // 2], x[..., D // 2:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def _repeat_kv(x: jax.Array, n: int) -> jax.Array:
    """[B, T, KV, D] -> [B, T, KV*n, D] (GQA head expansion)."""
    if n == 1:
        return x
    B, T, KV, D = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (B, T, KV, n, D)
    ).reshape(B, T, KV * n, D)


def _attention_dispatch(config: LlamaConfig, q, k, v, mesh: Optional[Mesh]):
    impl = config.attention_impl
    if impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh=mesh, axis=config.seq_axis,
                              causal=True)
    if impl == "ulysses":
        from ray_tpu.parallel.ring_attention import ulysses_attention

        return ulysses_attention(q, k, v, mesh=mesh, axis=config.seq_axis,
                                 causal=True)
    return attention(q, k, v, causal=True, impl=impl)


def _ffn(config: LlamaConfig, layer, x, rng=None):
    """mlp_norm + SwiGLU MLP (or routed MoE) + residual → (x, aux_loss)."""
    h = _rms_norm(x, layer["mlp_norm"], config.rms_eps)
    if config.moe is not None:
        h, aux = moe_layer(layer["moe"], h, config.moe, rng=rng)
        return x + h, aux
    gate = jnp.einsum("bte,em->btm", h, layer["w_gate"].astype(h.dtype))
    up = jnp.einsum("bte,em->btm", h, layer["w_up"].astype(h.dtype))
    h = jax.nn.silu(gate) * up
    h = jnp.einsum("btm,me->bte", h, layer["w_down"].astype(h.dtype))
    return x + h, jnp.float32(0.0)


def _block(config: LlamaConfig, mesh: Optional[Mesh], x, layer,
           pos: jax.Array, rng=None):
    """One decoder block → (x, aux). x: [B, T, E], pos: [B, T] absolute."""
    h = _rms_norm(x, layer["attn_norm"], config.rms_eps)
    q = jnp.einsum("bte,ehd->bthd", h, layer["wq"].astype(h.dtype))
    k = jnp.einsum("bte,ehd->bthd", h, layer["wk"].astype(h.dtype))
    v = jnp.einsum("bte,ehd->bthd", h, layer["wv"].astype(h.dtype))
    q = _rope(q, pos, config.rope_theta)
    k = _rope(k, pos, config.rope_theta)
    k = _repeat_kv(k, config.q_per_kv)
    v = _repeat_kv(v, config.q_per_kv)
    attn = _attention_dispatch(config, q, k, v, mesh)
    x = x + jnp.einsum("bthd,hde->bte", attn, layer["wo"].astype(x.dtype))
    return _ffn(config, layer, x, rng=rng)


def forward_features(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    rng: Optional[jax.Array] = None,  # feeds MoE router jitter
) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, T] int32 -> (final-trunk features [B, T, E], aux loss).
    The loss path consumes features directly (vocab-chunked cross entropy)
    so the [B, T, V] logits tensor never materializes."""
    B, T = tokens.shape
    x = params["wte"][tokens].astype(config.dtype)
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

    body = functools.partial(_block, config, mesh)
    if config.remat:
        body = jax.checkpoint(body, policy=_remat_policy(config))

    if rng is not None:
        layer_rngs = jax.random.split(rng, config.num_layers)

        def scan_rng(carry, xs):
            layer, lrng = xs
            x, aux = carry
            x, layer_aux = body(x, layer, pos, lrng)
            return (x, aux + layer_aux), None

        (x, aux), _ = jax.lax.scan(
            scan_rng, (x, jnp.float32(0.0)), (params["blocks"], layer_rngs)
        )
    else:

        def scan_fn(carry, layer):
            x, aux = carry
            x, layer_aux = body(x, layer, pos)
            return (x, aux + layer_aux), None

        (x, aux), _ = jax.lax.scan(
            scan_fn, (x, jnp.float32(0.0)), params["blocks"]
        )
    x = _rms_norm(x, params["norm_f"], config.rms_eps)
    return x, aux


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    rng: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, T] int32 -> (logits [B, T, V] f32, moe aux loss)."""
    x, aux = forward_features(params, tokens, config, mesh, rng=rng)
    logits = jnp.einsum("bte,ve->btv", x, params["lm_head"].astype(x.dtype))
    return logits.astype(jnp.float32), aux


def init_kv_cache(config: LlamaConfig, batch: int, max_len: int,
                  dtype=None) -> Dict[str, jax.Array]:
    """Static-shape GQA cache: [L, B, S, KV, D] — kv heads only, an
    H/KV-fold HBM saving over caching query-expanded heads."""
    dtype = dtype or config.dtype
    L, KV, D = config.num_layers, config.num_kv_heads, config.head_dim
    shape = (L, batch, max_len, KV, D)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def forward_cached(
    params: Dict[str, Any],
    tokens: jax.Array,
    cache: Dict[str, jax.Array],
    start: jax.Array,
    config: LlamaConfig,
) -> tuple:
    """Incremental forward with RoPE at absolute positions; same contract as
    :func:`ray_tpu.models.gpt2.forward_cached` (static shapes; per-sequence
    offsets via vmapped dynamic_update_slice). MoE configs route each
    decoded token through its top-k experts (aux loss is a training-only
    concern and is discarded here)."""
    B, T = tokens.shape
    S = cache["k"].shape[2]
    pos = start[:, None] + jnp.arange(T)[None, :]            # [B, T]
    x = params["wte"][tokens].astype(config.dtype)

    key_pos = jnp.arange(S)[None, None, :]
    mask = key_pos <= pos[:, :, None]                        # [B, T, S]

    def block(carry, layer_and_cache):
        x = carry
        layer, ck, cv = layer_and_cache
        h = _rms_norm(x, layer["attn_norm"], config.rms_eps)
        q = jnp.einsum("bte,ehd->bthd", h, layer["wq"].astype(h.dtype))
        k_new = jnp.einsum("bte,ehd->bthd", h, layer["wk"].astype(h.dtype))
        v_new = jnp.einsum("bte,ehd->bthd", h, layer["wv"].astype(h.dtype))
        q = _rope(q, pos, config.rope_theta)
        k_new = _rope(k_new, pos, config.rope_theta)
        upd = jax.vmap(
            lambda c, n, s: jax.lax.dynamic_update_slice(c, n, (s, 0, 0))
        )
        ck = upd(ck, k_new.astype(ck.dtype), start)          # [B, S, KV, D]
        cv = upd(cv, v_new.astype(cv.dtype), start)
        # GQA attention over the cache: group query heads per kv head.
        g = config.q_per_kv
        qg = q.reshape(B, T, config.num_kv_heads, g, config.head_dim)
        scores = jnp.einsum("btkgd,bskd->bkgts", qg, ck).astype(jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(config.head_dim))
        scores = jnp.where(mask[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        attn = jnp.einsum("bkgts,bskd->btkgd", probs, cv)
        attn = attn.reshape(B, T, config.num_heads, config.head_dim)
        x = x + jnp.einsum("bthd,hde->bte", attn, layer["wo"].astype(x.dtype))
        h = _rms_norm(x, layer["mlp_norm"], config.rms_eps)
        if config.moe is not None:
            routed, _aux = moe_layer(layer["moe"], h, config.moe)
            x = x + routed
        else:
            gate = jnp.einsum(
                "bte,em->btm", h, layer["w_gate"].astype(h.dtype)
            )
            up = jnp.einsum("bte,em->btm", h, layer["w_up"].astype(h.dtype))
            h = jax.nn.silu(gate) * up
            x = x + jnp.einsum(
                "btm,me->bte", h, layer["w_down"].astype(h.dtype)
            )
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = _rms_norm(x, params["norm_f"], config.rms_eps)
    logits = jnp.einsum("bte,ve->btv", x, params["lm_head"].astype(x.dtype))
    return logits.astype(jnp.float32), {"k": new_k, "v": new_v}


def loss_fn(
    params: Dict[str, Any],
    batch: Dict[str, jax.Array],
    config: LlamaConfig,
    mesh: Optional[Mesh] = None,
    pipeline_microbatches: Optional[int] = None,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token cross entropy; same batch contract as gpt2.loss_fn.
    ``rng`` feeds MoE router jitter (unpipelined path only)."""
    if "tokens" in batch:
        inputs = batch["tokens"][:, :-1]
        targets = batch["tokens"][:, 1:]
    else:
        inputs, targets = batch["inputs"], batch["targets"]
    if pipeline_microbatches:
        logits, aux = forward_pipelined(
            params, inputs, config, mesh, pipeline_microbatches
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        mask = batch.get("mask")
        if mask is None:
            return -ll.mean() + aux
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1) + aux
    from ray_tpu.ops.xent import chunked_softmax_xent

    x, aux = forward_features(params, inputs, config, mesh, rng=rng)
    return chunked_softmax_xent(
        x, params["lm_head"], targets, batch.get("mask")
    ) + aux


def forward_pipelined(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: LlamaConfig,
    mesh: Mesh,
    num_microbatches: int = 4,
) -> Tuple[jax.Array, jax.Array]:
    """Pipeline-parallel forward over the "stage" mesh axis (GPipe microbatch
    loop, ``parallel.pipeline.pipeline_apply``); embedding/head outside.
    MoE models accumulate the router's load-balancing aux loss across the
    microbatch loop (``pipeline_apply(collect_aux=True)``)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.pipeline import pipeline_apply

    B, T = tokens.shape
    x = params["wte"][tokens].astype(config.dtype)
    pos = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

    body = functools.partial(_block, config, mesh)
    if config.remat:
        body = jax.checkpoint(body, policy=_remat_policy(config))
    collect_aux = config.moe is not None

    def apply_stage(local_blocks, mb):
        # Microbatches split the batch dim; positions are batch-invariant.
        mb_pos = pos[: mb.shape[0]]

        def scan_fn(carry, layer):
            x, aux = carry
            y, a = body(x, layer, mb_pos)
            return (y, aux + a.astype(jnp.float32)), None

        (out, aux), _ = jax.lax.scan(
            scan_fn, (mb, jnp.float32(0.0)), local_blocks
        )
        return (out, aux) if collect_aux else out

    params_spec = jax.tree.map(lambda _: P("stage"), params["blocks"])
    res = pipeline_apply(
        params["blocks"], x, mesh=mesh, apply_stage=apply_stage,
        num_microbatches=num_microbatches, params_spec=params_spec,
        x_spec=P(), collect_aux=collect_aux,
    )
    x, aux = res if collect_aux else (res, jnp.float32(0.0))
    x = _rms_norm(x, params["norm_f"], config.rms_eps)
    logits = jnp.einsum("bte,ve->btv", x, params["lm_head"].astype(x.dtype))
    return logits.astype(jnp.float32), aux


def count_params(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


def flops_per_token(config: LlamaConfig) -> float:
    """~6N FLOPs/token for training; N = ACTIVE non-embedding params
    (MoE counts only the top_k routed experts per token)."""
    E, D = config.embed_dim, config.head_dim
    attn = E * config.num_heads * D * 2 + E * config.num_kv_heads * D * 2
    if config.moe is not None:
        per_expert = (
            3 if config.moe.activation == "swiglu" else 2
        ) * E * config.hidden_dim
        mlp = config.moe.top_k * per_expert + E * config.moe.num_experts
    else:
        mlp = 3 * E * config.hidden_dim
    n = config.num_layers * (attn + mlp) + config.vocab_size * E
    return 6.0 * n
