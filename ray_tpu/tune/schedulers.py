"""Trial schedulers: FIFO, ASHA, median-stopping, PBT.

Reference analogs: ``python/ray/tune/schedulers/`` — ``async_hyperband.py``
(ASHA), ``median_stopping_rule.py``, ``pbt.py``. The scheduler sees every
reported result and answers CONTINUE/STOP; PBT additionally mutates trial
configs and transplants checkpoints at perturbation boundaries.
"""
from __future__ import annotations

import math
import random

import numpy as np
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_start(self, trial):
        pass

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: Optional[dict]):
        pass

    def choose_exploit(self, trial, all_trials) -> Optional[tuple]:
        """PBT hook: (source_trial, mutated_config) or None."""
        return None


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous Successive Halving (reference:
    ``schedulers/async_hyperband.py AsyncHyperBandScheduler``): rungs at
    grace_period * reduction_factor^k; a trial reaching a rung stops unless
    it's in the top 1/reduction_factor of results recorded at that rung."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, reduction_factor: int = 4,
                 max_t: int = 100):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = {}
        self._judged: set = set()  # (trial_id, rung): one entry per trial
        rung = grace_period
        while rung < max_t:
            self._rungs[rung] = []
            rung *= reduction_factor

    def on_result(self, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        # Judge at the highest newly-reached rung; each trial contributes
        # exactly one value per rung (successive-halving semantics) — a trial
        # already promoted past a rung is not re-judged by it.
        for rung in sorted(self._rungs, reverse=True):
            if t >= rung:
                if (trial.trial_id, rung) in self._judged:
                    return CONTINUE
                self._judged.add((trial.trial_id, rung))
                recorded = self._rungs[rung]
                recorded.append(float(v))
                if len(recorded) < self.rf:
                    return CONTINUE  # not enough data: optimistic continue
                srt = sorted(recorded, reverse=(self.mode == "max"))
                k = max(1, math.floor(len(srt) / self.rf))
                cutoff = srt[k - 1]
                good = (v <= cutoff) if self.mode == "min" else (v >= cutoff)
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running averages at the same step (reference:
    ``schedulers/median_stopping_rule.py``)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_result(self, trial, result) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is None:
            return CONTINUE
        self._avgs.setdefault(trial.trial_id, []).append(float(v))
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        others = [
            sum(h) / len(h) for tid, h in self._avgs.items()
            if tid != trial.trial_id and h
        ]
        if len(others) + 1 < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = self._avgs[trial.trial_id]
        best = min(mine) if self.mode == "min" else max(mine)
        bad = (best > median) if self.mode == "min" else (best < median)
        return STOP if bad else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: ``schedulers/pbt.py``): every
    ``perturbation_interval`` iterations, bottom-quantile trials clone a
    top-quantile trial's checkpoint and continue with a mutated config."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last: Dict[str, dict] = {}  # trial_id -> last result
        self._perturbed_at: Dict[str, int] = {}

    def on_trial_start(self, trial):
        # A PBT clone starts with iteration = its source's progress; seed its
        # perturbation clock there or it is "due" on its very first poll and
        # gets re-cloned every cycle (unbounded trial churn).
        self._perturbed_at[trial.trial_id] = getattr(trial, "iteration", 0)

    def on_result(self, trial, result) -> str:
        self._last[trial.trial_id] = dict(result)
        return CONTINUE

    def _score(self, tid: str) -> Optional[float]:
        r = self._last.get(tid)
        v = None if r is None else r.get(self.metric)
        return None if v is None else float(v)

    def due_for_perturbation(self, trial) -> bool:
        r = self._last.get(trial.trial_id)
        if r is None:
            return False
        t = r.get(self.time_attr, 0)
        last = self._perturbed_at.get(trial.trial_id, 0)
        return t - last >= self.interval

    def choose_exploit(self, trial, all_trials) -> Optional[tuple]:
        if not self.due_for_perturbation(trial):
            return None
        scored = [
            (t, self._score(t.trial_id)) for t in all_trials
            if self._score(t.trial_id) is not None
        ]
        if len(scored) < 2:
            return None
        scored.sort(key=lambda x: x[1], reverse=(self.mode == "max"))
        k = max(1, int(len(scored) * self.quantile))
        top = [t for t, _ in scored[:k]]
        bottom = {t.trial_id for t, _ in scored[-k:]}
        self._perturbed_at[trial.trial_id] = self._last[trial.trial_id].get(
            self.time_attr, 0
        )
        if trial.trial_id not in bottom or trial in top:
            return None
        source = self._rng.choice(top)
        return source, self._mutate(dict(source.config))

    def _mutate(self, config: dict) -> dict:
        from ray_tpu.tune.search import Domain

        for key, spec in self.mutations.items():
            if key not in config:
                continue
            if self._rng.random() < self.resample_p or not isinstance(
                config[key], (int, float)
            ):
                if isinstance(spec, Domain):
                    config[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    config[key] = self._rng.choice(spec)
                elif callable(spec):
                    config[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                config[key] = type(config[key])(config[key] * factor)
        return config


class PB2(PopulationBasedTraining):
    """Population-Based Bandits (reference: ``tune/schedulers/pb2.py``,
    Parker-Holder et al., NeurIPS 2020): PBT's exploit step, but exploration
    picks new hyperparameters by maximizing a GP-UCB acquisition fit on
    (hyperparameters → reward change) observations instead of random
    perturbation — far more sample-efficient for small populations. The GP
    is a numpy RBF kernel ridge (no external GP library needed at this
    dimensionality).

    ``hyperparam_bounds``: {key: (low, high)} continuous ranges; bounds
    spanning >=2 orders of magnitude are searched in log space.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, tuple]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 2.0,
                 seed: Optional[int] = None):
        super().__init__(
            metric=metric, mode=mode, time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={}, quantile_fraction=quantile_fraction,
            seed=seed,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={key: (lo, hi)}")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.keys = sorted(self.bounds)
        self.kappa = ucb_kappa
        self._np_rng = np.random.RandomState(seed)
        # observations: (normalized config vector, reward delta)
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        self._prev_score: Dict[str, float] = {}

    # ---------------------------------------------------------- GP data

    def _log_scaled(self, key: str) -> bool:
        lo, hi = self.bounds[key]
        return lo > 0 and hi / max(lo, 1e-300) >= 100.0

    def _normalize(self, config: dict) -> np.ndarray:
        out = []
        for k in self.keys:
            lo, hi = self.bounds[k]
            v = float(config.get(k, lo))
            if self._log_scaled(k):
                out.append(
                    (np.log(max(v, 1e-300)) - np.log(lo))
                    / (np.log(hi) - np.log(lo))
                )
            else:
                out.append((v - lo) / (hi - lo))
        return np.clip(np.asarray(out), 0.0, 1.0)

    def _denormalize(self, x: np.ndarray) -> dict:
        out = {}
        for i, k in enumerate(self.keys):
            lo, hi = self.bounds[k]
            if self._log_scaled(k):
                out[k] = float(np.exp(
                    np.log(lo) + x[i] * (np.log(hi) - np.log(lo))
                ))
            else:
                out[k] = float(lo + x[i] * (hi - lo))
        return out

    def on_result(self, trial, result) -> str:
        score = result.get(self.metric)
        if score is not None:
            score = float(score)
            if self.mode == "min":
                score = -score
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                self._X.append(self._normalize(trial.config))
                self._y.append(score - prev)
            self._prev_score[trial.trial_id] = score
        return super().on_result(trial, result)

    # ------------------------------------------------------- GP-UCB pick

    def choose_exploit(self, trial, all_trials):
        out = super().choose_exploit(trial, all_trials)
        if out is not None:
            # The exploited trial jumps to the source's checkpoint: its next
            # score delta reflects the clone, not the new hyperparameters —
            # it must not become a (spurious) GP observation.
            self._prev_score.pop(trial.trial_id, None)
        return out

    def _mutate(self, config: dict) -> dict:
        config = dict(config)
        config.update(self._denormalize(self._suggest()))
        return config

    def _suggest(self) -> np.ndarray:
        d = len(self.keys)
        cands = self._np_rng.rand(256, d)
        if len(self._y) < 4:
            return cands[0]  # cold start: random exploration
        X = np.stack(self._X[-256:])  # bound the fit cost
        y = np.asarray(self._y[-256:])
        std = y.std()
        y = (y - y.mean()) / (std + 1e-9)

        def rbf(A, B, ls=0.3):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ls * ls))

        K = rbf(X, X) + 1e-2 * np.eye(len(X))
        try:
            Kinv_y = np.linalg.solve(K, y)
            Ks = rbf(cands, X)
            mu = Ks @ Kinv_y
            Kinv_Ks = np.linalg.solve(K, Ks.T)
            var = np.clip(1.0 - np.sum(Ks * Kinv_Ks.T, axis=1), 1e-9, None)
            ucb = mu + self.kappa * np.sqrt(var)
        except np.linalg.LinAlgError:
            return cands[0]
        return cands[int(np.argmax(ucb))]
