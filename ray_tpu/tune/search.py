"""Search spaces and search algorithms.

Reference analogs: ``python/ray/tune/search/sample.py`` (Domain objects:
uniform/loguniform/choice/randint/...), ``search/basic_variant.py``
(grid + random variant generation), ``search/search_algorithm.py`` +
``ConcurrencyLimiter``. Third-party searchers (optuna/hyperopt/...) plug in
via the same ``Searcher`` interface; only the built-ins ship here.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional


class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class QUniform(Uniform):
    def __init__(self, low, high, q):
        super().__init__(low, high)
        self.q = q

    def sample(self, rng):
        return round(super().sample(rng) / self.q) * self.q


class LogUniform(Domain):
    def __init__(self, low: float, high: float):
        import math

        self.lo, self.hi = math.log(low), math.log(high)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.lo, self.hi))


class RandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class LogRandInt(Domain):
    def __init__(self, low: int, high: int):
        self.low, self.high = low, high

    def sample(self, rng):
        import math

        return int(round(math.exp(
            rng.uniform(math.log(self.low), math.log(self.high - 1))
        )))


class Choice(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class SampleFrom(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def lograndint(low, high) -> LogRandInt:
    return LogRandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def grid_search(values: List[Any]) -> Dict[str, List[Any]]:
    return {"grid_search": list(values)}


class Searcher:
    """Suggest/observe interface (reference: ``search/searcher.py``)."""

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        pass

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        return True


class BasicVariantGenerator(Searcher):
    """Cross-product of every grid_search axis × num_samples random draws of
    the Domain leaves (reference: ``search/basic_variant.py``)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None):
        self._rng = random.Random(seed)
        self._variants = list(self._expand(param_space, num_samples))
        self._next = 0

    def _expand(self, space: Dict[str, Any], num_samples: int):
        grid_keys, grid_vals = [], []

        def find_grids(prefix, node):
            for k, v in node.items():
                if isinstance(v, dict) and "grid_search" in v:
                    grid_keys.append(prefix + (k,))
                    grid_vals.append(v["grid_search"])
                elif isinstance(v, dict):
                    find_grids(prefix + (k,), v)

        find_grids((), space)
        combos = list(itertools.product(*grid_vals)) if grid_vals else [()]
        for _ in range(num_samples):
            for combo in combos:
                yield self._materialize(space, dict(zip(grid_keys, combo)))

    def _materialize(self, node, grid_assign, prefix=()):
        out = {}
        for k, v in node.items():
            path = prefix + (k,)
            if isinstance(v, dict) and "grid_search" in v:
                out[k] = grid_assign[path]
            elif isinstance(v, dict):
                out[k] = self._materialize(v, grid_assign, path)
            elif isinstance(v, Domain):
                out[k] = v.sample(self._rng)
            else:
                out[k] = v
        return out

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg


class ConcurrencyLimiter(Searcher):
    """Caps concurrent suggestions (reference: ``search/concurrency_limiter``)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return "PENDING"  # sentinel: ask again later
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "PENDING":
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class OptunaSearch(Searcher):
    """Optuna TPE searcher (reference: ``search/optuna``). Import-guarded:
    optuna is an optional dependency. ``metric`` is required (the study
    needs an objective); ``num_samples`` bounds the trial count (external
    searchers are not capped by TuneConfig.num_samples)."""

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", num_samples: int = 16,
                 seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the optional 'optuna' package "
                "(pip install optuna); built-in alternatives: "
                "BasicVariantGenerator (random/grid) + ASHA/PBT schedulers"
            ) from e
        if not metric:
            raise ValueError("OptunaSearch requires metric=")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        # Validate the whole space up front: a bad domain must fail at
        # configuration time, not abort a running experiment at the first
        # suggest() call.
        for name, domain in param_space.items():
            if isinstance(domain, dict) and "grid_search" in domain:
                raise ValueError(
                    f"OptunaSearch does not support grid_search (param "
                    f"{name!r}): TPE samples and cannot guarantee every "
                    f"grid value runs — use choice() or the default "
                    f"BasicVariantGenerator"
                )
            if isinstance(domain, dict):
                raise ValueError(
                    f"OptunaSearch does not support nested spaces "
                    f"(param {name!r}); flatten the space"
                )
        self._optuna = optuna
        self._space = param_space
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._suggested = 0
        sampler = optuna.samplers.TPESampler(seed=seed)
        self._study = optuna.create_study(
            direction="minimize" if mode == "min" else "maximize",
            sampler=sampler,
        )
        self._trials: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if metric:
            self._metric = metric
        if mode and mode != self._mode:
            # the study's direction is frozen at construction; pretending to
            # flip it would silently optimize the wrong way
            return False
        return True

    def _suggest_value(self, trial, name: str, domain):
        import math

        if isinstance(domain, LogUniform):
            # LogUniform stores log-space bounds (lo/hi)
            return trial.suggest_float(
                name, math.exp(domain.lo), math.exp(domain.hi), log=True
            )
        if isinstance(domain, QUniform):
            return trial.suggest_float(name, domain.low, domain.high,
                                       step=domain.q)
        if isinstance(domain, Uniform):
            return trial.suggest_float(name, domain.low, domain.high)
        if isinstance(domain, LogRandInt):
            return trial.suggest_int(name, domain.low, domain.high - 1,
                                     log=True)
        if isinstance(domain, RandInt):
            return trial.suggest_int(name, domain.low, domain.high - 1)
        if isinstance(domain, Choice):
            return trial.suggest_categorical(name, domain.categories)
        raise ValueError(
            f"OptunaSearch cannot optimize param {name!r} of type "
            f"{type(domain).__name__}; supported: uniform/quniform/"
            f"loguniform/randint/lograndint/choice"
        )

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._num_samples:
            return None  # search exhausted -> Tuner terminates
        self._suggested += 1
        trial = self._study.ask()
        self._trials[trial_id] = trial
        cfg = {}
        for name, domain in self._space.items():
            if isinstance(domain, Domain):
                cfg[name] = self._suggest_value(trial, name, domain)
            else:
                cfg[name] = domain  # constants (dicts rejected in __init__)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        trial = self._trials.pop(trial_id, None)
        if trial is None:
            return
        if error or not result or self._metric not in result:
            self._study.tell(
                trial, state=self._optuna.trial.TrialState.FAIL
            )
        else:
            self._study.tell(trial, result[self._metric])


class BayesOptSearch(Searcher):
    """Native GP-UCB Bayesian searcher — no external dependency.

    Reference analog: ``python/ray/tune/search/bayesopt`` (which wraps the
    bayesian-optimization package). Here the model is the same
    numpy-RBF-kernel-ridge GP recipe PB2 already uses (``schedulers.PB2``):
    continuous domains are normalized to [0, 1]^d, UCB (mu + kappa * sigma)
    is maximized over random candidates, and observations come from
    ``on_trial_complete``. Categorical/choice axes fall back to random
    sampling (GP-UCB over one-hot axes adds noise at these trial counts).
    """

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", num_samples: int = 16,
                 random_startup: int = 4, kappa: float = 1.5,
                 seed: Optional[int] = None):
        import math

        import numpy as np

        if not metric:
            raise ValueError("BayesOptSearch requires metric=")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        self._np = np
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._startup = random_startup
        self._kappa = kappa
        self._rng = random.Random(seed)
        self._np_rng = np.random.RandomState(seed)
        # (name, transform) for GP axes; everything else samples randomly.
        self._axes: List[tuple] = []
        self._other: Dict[str, Domain] = {}
        self._fixed: Dict[str, Any] = {}
        for name, dom in param_space.items():
            if isinstance(dom, dict):
                raise ValueError(
                    "BayesOptSearch does not support nested/grid spaces "
                    f"(param {name!r}); flatten the space or use the "
                    "default BasicVariantGenerator"
                )
            if isinstance(dom, LogUniform):
                lo, hi = dom.lo, dom.hi  # already log-space bounds
                self._axes.append(
                    (name, lambda u, lo=lo, hi=hi: math.exp(
                        lo + u * (hi - lo)))
                )
            elif isinstance(dom, QUniform):
                lo, hi, q = dom.low, dom.high, dom.q
                self._axes.append(
                    (name, lambda u, lo=lo, hi=hi, q=q: round(
                        (lo + u * (hi - lo)) / q) * q)
                )
            elif isinstance(dom, Uniform):
                lo, hi = dom.low, dom.high
                self._axes.append(
                    (name, lambda u, lo=lo, hi=hi: lo + u * (hi - lo))
                )
            elif isinstance(dom, LogRandInt):
                llo, lhi = math.log(dom.low), math.log(dom.high)
                self._axes.append(
                    (name, lambda u, llo=llo, lhi=lhi, hi=dom.high:
                        min(int(round(math.exp(llo + u * (lhi - llo)))),
                            hi - 1))
                )
            elif isinstance(dom, RandInt):
                lo, hi = dom.low, dom.high
                self._axes.append(
                    (name, lambda u, lo=lo, hi=hi: min(
                        int(lo + u * (hi - lo)), hi - 1))
                )
            elif isinstance(dom, Domain):
                self._other[name] = dom
            else:
                self._fixed[name] = dom
        self._suggested = 0
        self._pending: Dict[str, "Any"] = {}  # trial_id -> unit vector
        self._X: List[Any] = []
        self._y: List[float] = []

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._num_samples:
            return None
        self._suggested += 1
        u = self._pick_unit()
        self._pending[trial_id] = u
        cfg = dict(self._fixed)
        for (name, tf), ui in zip(self._axes, u):
            cfg[name] = tf(float(ui))
        for name, dom in self._other.items():
            cfg[name] = dom.sample(self._rng)
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        u = self._pending.pop(trial_id, None)
        if u is None or error or not result or self._metric not in result:
            return
        score = float(result[self._metric])
        if self._mode == "min":
            score = -score
        self._X.append(u)
        self._y.append(score)

    # ------------------------------------------------------- GP-UCB pick

    def _pick_unit(self):
        np = self._np
        d = max(len(self._axes), 1)
        cands = self._np_rng.rand(256, d)
        if len(self._y) < self._startup or not self._axes:
            return cands[0]
        X = np.stack(self._X[-256:])
        y = np.asarray(self._y[-256:])
        y = (y - y.mean()) / (y.std() + 1e-9)

        def rbf(A, B, ls=0.3):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / (ls * ls))

        K = rbf(X, X) + 1e-2 * np.eye(len(X))
        try:
            Kinv_y = np.linalg.solve(K, y)
            Ks = rbf(cands, X)
            mu = Ks @ Kinv_y
            Kinv_Ks = np.linalg.solve(K, Ks.T)
            var = np.clip(1.0 - np.sum(Ks * Kinv_Ks.T, axis=1), 1e-9, None)
            ucb = mu + self._kappa * np.sqrt(var)
        except np.linalg.LinAlgError:
            return cands[0]
        return cands[int(np.argmax(ucb))]


class HyperOptSearch(Searcher):
    """HyperOpt TPE searcher (reference: ``search/hyperopt``).
    Import-guarded: hyperopt is an optional dependency; built-ins
    (BasicVariantGenerator, BayesOptSearch) cover the common cases
    without it. Ask/tell rides hyperopt's Trials book-keeping the same
    way the reference wrapper does."""

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", num_samples: int = 16,
                 seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires the optional 'hyperopt' package "
                "(pip install hyperopt); built-in alternatives: "
                "BasicVariantGenerator (random/grid) + BayesOptSearch"
            ) from e
        import math

        import numpy as np
        from hyperopt import hp

        if not metric:
            raise ValueError("HyperOptSearch requires metric=")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        space = {}
        self._constants: Dict[str, Any] = {}
        for name, domain in param_space.items():
            if isinstance(domain, dict):
                raise ValueError(
                    f"HyperOptSearch does not support nested/grid spaces "
                    f"(param {name!r}); flatten the space"
                )
            if isinstance(domain, LogUniform):
                space[name] = hp.loguniform(name, domain.lo, domain.hi)
            elif isinstance(domain, QUniform):
                space[name] = hp.quniform(
                    name, domain.low, domain.high, domain.q
                )
            elif isinstance(domain, Uniform):
                space[name] = hp.uniform(name, domain.low, domain.high)
            elif isinstance(domain, LogRandInt):
                # log-uniform over integers (randint would spend half the
                # budget in the top decade); high is EXCLUSIVE
                hi = domain.high - 1
                if hi <= domain.low:
                    self._constants[name] = domain.low  # single-value range
                else:
                    space[name] = hp.qloguniform(
                        name, math.log(domain.low), math.log(hi), 1
                    )
            elif isinstance(domain, RandInt):
                space[name] = hp.randint(name, domain.low, domain.high)
            elif isinstance(domain, Choice):
                space[name] = hp.choice(name, domain.categories)
            elif isinstance(domain, Domain):
                raise ValueError(
                    f"HyperOptSearch cannot optimize param {name!r} of "
                    f"type {type(domain).__name__}"
                )
            else:
                self._constants[name] = domain
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._param_space = param_space
        self._hpo = hyperopt
        self._domain = hyperopt.Domain(lambda _spc: 0, space)
        self._trials = hyperopt.Trials()
        self._rng = np.random.default_rng(seed)
        self._live: Dict[str, int] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if metric:
            self._metric = metric
        if mode and mode != self._mode:
            return False
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._num_samples:
            return None
        self._suggested += 1
        hpo = self._hpo
        new_id = self._trials.new_trial_ids(1)[0]
        docs = hpo.tpe.suggest(
            [new_id], self._domain, self._trials,
            int(self._rng.integers(2 ** 31 - 1)),
        )
        self._trials.insert_trial_docs(docs)
        self._trials.refresh()
        trial = self._trials._dynamic_trials[-1]
        trial["state"] = hpo.JOB_STATE_RUNNING
        vals = {
            k: v[0] for k, v in trial["misc"]["vals"].items() if v
        }
        cfg = dict(self._constants)
        for name, domain in self._param_space.items():
            if name not in vals:
                continue
            v = vals[name]
            if isinstance(domain, Choice):
                v = domain.categories[int(v)]
            elif isinstance(domain, (RandInt, LogRandInt)):
                v = int(v)
            cfg[name] = v
        self._live[trial_id] = new_id
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        hpo = self._hpo
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        for trial in self._trials._dynamic_trials:
            if trial["tid"] != tid:
                continue
            if error or not result or self._metric not in result:
                trial["state"] = hpo.JOB_STATE_ERROR
            else:
                val = float(result[self._metric])
                if self._mode == "max":
                    val = -val
                trial["state"] = hpo.JOB_STATE_DONE
                trial["result"] = {"loss": val, "status": hpo.STATUS_OK}
            break
        self._trials.refresh()


class NevergradSearch(Searcher):
    """Nevergrad searcher (reference: ``search/nevergrad``).
    Import-guarded; ask/tell maps directly onto an ``ng.optimizers``
    optimizer over a parametrization built from the space."""

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", num_samples: int = 16,
                 optimizer: str = "NGOpt", seed: Optional[int] = None):
        try:
            import nevergrad as ng
        except ImportError as e:
            raise ImportError(
                "NevergradSearch requires the optional 'nevergrad' package "
                "(pip install nevergrad); built-in alternatives: "
                "BasicVariantGenerator (random/grid) + BayesOptSearch"
            ) from e
        import math

        if not metric:
            raise ValueError("NevergradSearch requires metric=")
        if mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {mode!r}")
        params = {}
        self._constants: Dict[str, Any] = {}
        for name, domain in param_space.items():
            if isinstance(domain, dict):
                raise ValueError(
                    f"NevergradSearch does not support nested/grid spaces "
                    f"(param {name!r}); flatten the space"
                )
            if isinstance(domain, LogUniform):
                params[name] = ng.p.Log(
                    lower=math.exp(domain.lo), upper=math.exp(domain.hi)
                )
            elif isinstance(domain, (Uniform, QUniform)):
                # QUniform rides a continuous scalar; suggest() rounds to
                # the declared q so configs stay on the quantized grid
                params[name] = ng.p.Scalar(
                    lower=domain.low, upper=domain.high
                )
            elif isinstance(domain, LogRandInt):
                hi = domain.high - 1  # high is EXCLUSIVE
                if hi <= domain.low:
                    self._constants[name] = domain.low
                else:
                    params[name] = ng.p.Log(
                        lower=domain.low, upper=hi
                    ).set_integer_casting()
            elif isinstance(domain, RandInt):
                params[name] = ng.p.Scalar(
                    lower=domain.low, upper=domain.high - 1
                ).set_integer_casting()
            elif isinstance(domain, Choice):
                params[name] = ng.p.Choice(domain.categories)
            elif isinstance(domain, Domain):
                raise ValueError(
                    f"NevergradSearch cannot optimize param {name!r} of "
                    f"type {type(domain).__name__}"
                )
            else:
                self._constants[name] = domain
        self._metric = metric
        self._mode = mode
        self._num_samples = num_samples
        self._suggested = 0
        self._param_space = param_space
        inst = ng.p.Dict(**params)
        if seed is not None:
            inst.random_state.seed(seed)
        opt_cls = ng.optimizers.registry[optimizer]
        self._opt = opt_cls(parametrization=inst, budget=num_samples)
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if metric:
            self._metric = metric
        if mode and mode != self._mode:
            return False
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._suggested >= self._num_samples:
            return None
        self._suggested += 1
        cand = self._opt.ask()
        self._live[trial_id] = cand
        cfg = {**self._constants, **dict(cand.value)}
        for name, domain in self._param_space.items():
            if isinstance(domain, QUniform) and name in cfg:
                cfg[name] = round(cfg[name] / domain.q) * domain.q
        return cfg

    def on_trial_complete(self, trial_id: str, result: Optional[dict],
                          error: bool = False):
        cand = self._live.pop(trial_id, None)
        if cand is None:
            return
        if error or not result or self._metric not in result:
            return  # nevergrad has no error-tell; drop the candidate
        val = float(result[self._metric])
        if self._mode == "max":
            val = -val
        self._opt.tell(cand, val)
